"""Pluggable network stacks (msg/stack.py; the reference's NetworkStack
family, src/msg/async/Stack.h, selected by ms_type).

The protocol layer must be byte-identical over every stack, so the same
exchanges run over posix (TCP) and inproc (in-process pipes) — including
secure (AES-GCM) sessions — and a full mon+OSD+client cluster comes up
with ms_type=async+inproc end to end.
"""

import asyncio

import pytest

from ceph_tpu.common.config import Config
from ceph_tpu.msg.messages import MOSDPing
from ceph_tpu.msg.messenger import Dispatcher, Messenger
from ceph_tpu.msg.stack import InProcStack, PosixStack, make_stack


class _Catcher(Dispatcher):
    def __init__(self):
        self.got = []
        self.event = asyncio.Event()

    def ms_dispatch(self, conn, msg) -> bool:
        self.got.append(msg)
        self.event.set()
        return True


def test_make_stack_aliases():
    assert isinstance(make_stack("posix"), PosixStack)
    assert isinstance(make_stack("async+posix"), PosixStack)
    assert isinstance(make_stack("inproc"), InProcStack)
    assert isinstance(make_stack("async+inproc"), InProcStack)
    with pytest.raises(ValueError):
        make_stack("rdma")  # not implemented -> loud error, not a fallback


@pytest.mark.parametrize("kind", ["posix", "inproc"])
def test_messenger_roundtrip_over_stack(kind):
    async def run():
        a = Messenger("client.a", stack=kind)
        b = Messenger("osd.b", stack=kind)
        catcher = _Catcher()
        b.add_dispatcher_tail(catcher)
        await b.bind("127.0.0.1:0")
        await a.bind("127.0.0.1:0")
        await a.send_to(b.addr, MOSDPing(op=MOSDPing.PING, stamp=1.0, epoch=1, from_osd=7))
        await asyncio.wait_for(catcher.event.wait(), 5.0)
        assert catcher.got[0].from_osd == 7
        if kind == "inproc":
            assert b.addr.startswith("inproc:")
        await a.shutdown()
        await b.shutdown()

    asyncio.run(run())


def test_inproc_secure_session():
    """The on-wire layers (cephx + AES-GCM + compression negotiation) run
    unchanged over the inproc stack."""
    from ceph_tpu.msg.crypto import AESGCM

    if AESGCM is None:
        pytest.skip("cryptography package not installed")

    async def run():
        from ceph_tpu.auth.cephx import CephxAuth
        from ceph_tpu.auth.keyring import KeyRing

        kr = KeyRing()
        kr.add("osd.b", b"k" * 16)
        kr.add("client.a", b"c" * 16)
        auth_b = CephxAuth.for_daemon("osd.b", kr)
        auth_a = CephxAuth.for_daemon("client.a", kr)
        a = Messenger("client.a", stack="inproc", auth=auth_a, secure=True)
        b = Messenger("osd.b", stack="inproc", auth=auth_b, secure=True)
        catcher = _Catcher()
        b.add_dispatcher_tail(catcher)
        await b.bind(":0")
        await a.bind(":0")
        await a.send_to(b.addr, MOSDPing(op=MOSDPing.PING, stamp=9.0, epoch=1, from_osd=3))
        await asyncio.wait_for(catcher.event.wait(), 5.0)
        assert catcher.got[0].from_osd == 3
        await a.shutdown()
        await b.shutdown()

    asyncio.run(run())


def test_inproc_connect_refused_without_listener():
    async def run():
        a = Messenger("client.x", stack="inproc")
        with pytest.raises(ConnectionError):
            await a.send_to("inproc:nobody", MOSDPing(op=MOSDPing.PING, stamp=1.0, epoch=1, from_osd=1))
        await a.shutdown()

    asyncio.run(run())


class TestInProcCluster:
    def test_full_cluster_over_inproc(self):
        """mon + OSDs + librados client entirely over in-process pipes
        (ms_type=async+inproc): pool create, EC put/get round trip."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.mon import MonMap, Monitor
            from ceph_tpu.osd.osd import OSD

            monmap = MonMap(addrs={"a": "inproc:mon.a"})
            mon = Monitor("a", monmap, election_timeout=0.3, stack="inproc")
            await mon.start()
            await mon.wait_for_quorum()
            osds = []
            for i in range(3):
                conf = Config(
                    {
                        "name": f"osd.{i}",
                        "ms_type": "async+inproc",
                        "osd_heartbeat_interval": 0.1,
                        "osd_heartbeat_grace": 0.6,
                    },
                    env=False,
                )
                o = OSD(i, monmap, conf=conf)
                await o.start()
                osds.append(o)
            for o in osds:
                await o.wait_for_up()
            client = Rados(monmap, stack="inproc")
            await client.connect()
            rv, rs, _ = await client.mon_command(
                {
                    "prefix": "osd erasure-code-profile set",
                    "name": "ip21",
                    "profile": ["k=2", "m=1", "plugin=tpu"],
                }
            )
            assert rv == 0, rs
            await client.pool_create("ipool", "erasure", profile="ip21", pg_num=4)
            io = await client.open_ioctx("ipool")
            payload = bytes(range(256)) * 64
            await io.write_full("obj", payload)
            assert await io.read("obj") == payload
            await client.shutdown()
            for o in osds:
                await o.stop()
            await mon.stop()

        asyncio.run(run())


class TestInProcVstart:
    def test_devcluster_over_inproc(self):
        """vstart honors ms_type cluster-wide: mons get inproc monmap
        addresses, OSDs/mgr/client share the stack, and the whole dev
        topology boots and serves I/O with zero TCP sockets."""

        async def run():
            from ceph_tpu.client import Rados
            from ceph_tpu.tools.vstart import DevCluster

            cluster = DevCluster(
                1, 3, with_mgr=True,
                conf_overrides={"ms_type": "async+inproc"},
            )
            monmap = await cluster.start()
            assert all(a.startswith("inproc:") for a in monmap.addrs.values())
            client = Rados(monmap, stack="inproc")
            await client.connect()
            await client.pool_create("vp", "replicated", pg_num=4)
            io = await client.open_ioctx("vp")
            await io.write_full("o", b"inproc vstart")
            assert await io.read("o") == b"inproc vstart"
            await client.shutdown()
            await cluster.stop()

        asyncio.run(run())
