"""Codec-level tests — the tier-1 pattern from the reference test suite.

Models /root/reference/src/test/erasure-code/TestErasureCodeIsa.cc: build the
codec directly, encode a payload, verify chunk layout equals input slices
(compare_chunks, :39-49), erase every combination, decode, compare (:51-90);
plus registry failure-mode fixtures (TestErasureCodePlugin.cc).
"""

import itertools
import sys
import types

import numpy as np
import pytest

from ceph_tpu.codec import (
    CAUCHY,
    VANDERMONDE,
    EcError,
    ErasureCodeTpuRs,
)
from ceph_tpu.codec import registry as reg_mod
from ceph_tpu.codec.registry import EC_VERSION, ErasureCodePluginRegistry
from ceph_tpu.gf import gf_matmul, isa_cauchy_matrix, isa_rs_vandermonde_matrix


def make_rs(k, m, technique=VANDERMONDE):
    ec = ErasureCodeTpuRs(technique=technique)
    ec.init({"k": str(k), "m": str(m)})
    return ec


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n).astype(np.uint8).tobytes()


class TestGeometry:
    def test_chunk_size_alignment(self):
        ec = make_rs(8, 3)
        # ceil(obj/k) padded to ALIGNMENT (ErasureCodeIsa.cc:65-79).
        assert ec.get_chunk_size(8 * 128) == 128
        assert ec.get_chunk_size(8 * 128 + 1) == 256
        assert ec.get_chunk_size(1) == 128
        assert ec.get_chunk_count() == 11
        assert ec.get_data_chunk_count() == 8
        assert ec.get_coding_chunk_count() == 3
        assert ec.get_sub_chunk_count() == 1

    def test_defaults(self):
        ec = ErasureCodeTpuRs()
        ec.init({})
        assert (ec.k, ec.m) == (7, 3)  # ErasureCodeIsa.cc:46-47

    def test_vandermonde_envelope(self):
        # ErasureCodeIsa.cc:331-361
        with pytest.raises(EcError):
            make_rs(33, 3)
        with pytest.raises(EcError):
            make_rs(8, 5)
        with pytest.raises(EcError):
            make_rs(22, 4)
        make_rs(21, 4)
        make_rs(32, 3)
        # Cauchy has no envelope cap below k+m <= 256.
        make_rs(33, 5, technique=CAUCHY)

    def test_sanity_k_m(self):
        with pytest.raises(EcError):
            make_rs(1, 1)
        with pytest.raises(EcError):
            make_rs(4, 0)

    def test_reinit_refreshes_matrix(self):
        # Regression: a second init() with new geometry must rebuild the
        # distribution matrix, not serve the stale cached one.
        ec = ErasureCodeTpuRs()
        ec.init({"k": "4", "m": "2"})
        assert ec.distribution_matrix().shape == (6, 4)
        ec.init({"k": "6", "m": "3"})
        assert ec.distribution_matrix().shape == (9, 6)
        raw = payload(6 * 128, seed=13)
        encoded = ec.encode(set(range(9)), raw)
        decoded = ec.decode({0}, {i: encoded[i] for i in range(1, 9)})
        assert np.array_equal(decoded[0], encoded[0])


class TestEncodeDecode:
    @pytest.mark.parametrize("technique", [VANDERMONDE, CAUCHY])
    @pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 3), (6, 4)])
    def test_roundtrip_all_erasures(self, k, m, technique):
        if technique == VANDERMONDE and m == 4 and k > 21:
            pytest.skip("outside envelope")
        ec = make_rs(k, m, technique)
        raw = payload(k * 128 + 17)  # force padding
        want = set(range(k + m))
        encoded = ec.encode(want, raw)
        assert set(encoded) == want
        chunk_size = ec.get_chunk_size(len(raw))
        # Data chunks must equal the padded input slices (systematic layout,
        # ErasureCodeInterface.h:39-58).
        padded = np.zeros(k * chunk_size, dtype=np.uint8)
        padded[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        for i in range(k):
            assert np.array_equal(encoded[i], padded[i * chunk_size : (i + 1) * chunk_size])
        # Every erasure combination up to m must decode byte-identically.
        for nerr in range(1, m + 1):
            for erasures in itertools.combinations(range(k + m), nerr):
                avail = {i: encoded[i] for i in range(k + m) if i not in erasures}
                decoded = ec.decode(set(erasures), avail)
                for e in erasures:
                    assert np.array_equal(decoded[e], encoded[e]), (erasures, e)

    def test_decode_concat_roundtrip(self):
        ec = make_rs(5, 3)
        raw = payload(5 * 256 + 99, seed=7)
        encoded = ec.encode(set(range(8)), raw)
        avail = {i: encoded[i] for i in (0, 2, 3, 4, 6)}  # drop 1, 5, 7
        out = ec.decode_concat(avail)
        assert out[: len(raw)].tobytes() == raw

    def test_parity_matches_gf_matmul(self):
        """Encode output must equal the plain GF(2^8) matrix product — the
        host-math oracle for byte-parity with ISA-L."""
        for technique, gen in [
            (VANDERMONDE, isa_rs_vandermonde_matrix),
            (CAUCHY, isa_cauchy_matrix),
        ]:
            k, m = 8, 3
            ec = make_rs(k, m, technique)
            raw = payload(k * 128, seed=3)
            encoded = ec.encode(set(range(k + m)), raw)
            data = np.stack([encoded[i] for i in range(k)])
            expect = gf_matmul(gen(k, m)[k:], data)
            for i in range(m):
                assert np.array_equal(encoded[k + i], expect[i])

    @pytest.mark.parametrize("technique", [VANDERMONDE, CAUCHY])
    def test_m1_xor_parity(self, technique):
        # m==1 is a pure XOR regardless of technique (ErasureCodeIsa.cc:125-127).
        ec = make_rs(4, 1, technique)
        raw = payload(4 * 128, seed=5)
        encoded = ec.encode(set(range(5)), raw)
        expect = np.bitwise_xor.reduce(np.stack([encoded[i] for i in range(4)]), axis=0)
        assert np.array_equal(encoded[4], expect)

    @pytest.mark.parametrize("technique", [VANDERMONDE, CAUCHY])
    def test_m1_device_decode_consistent(self, technique):
        # Regression: decode_array must agree with the XOR-encoded parity for
        # m==1 (the bulk/sharded device path, not just the chunk fast path).
        ec = make_rs(4, 1, technique)
        raw = payload(4 * 128, seed=6)
        encoded = ec.encode(set(range(5)), raw)
        erasures = [0]
        idx = ec.decode_index(erasures)
        survivors = np.stack([encoded[i] for i in idx])
        rec = np.asarray(ec.decode_array(erasures, survivors))
        assert np.array_equal(rec[0], encoded[0])

    def test_too_many_erasures(self):
        ec = make_rs(4, 2)
        raw = payload(4 * 128)
        encoded = ec.encode(set(range(6)), raw)
        avail = {i: encoded[i] for i in (0, 1, 2)}  # 3 erasures > m=2
        with pytest.raises(EcError):
            ec.decode({3, 4, 5}, avail)

    def test_minimum_to_decode(self):
        ec = make_rs(4, 2)
        # want subset of available -> want itself
        got = ec.minimum_to_decode({0, 1}, {0, 1, 2, 3})
        assert set(got) == {0, 1}
        assert got[0] == [(0, 1)]
        # missing chunk -> first k available
        got = ec.minimum_to_decode({0}, {1, 2, 3, 4, 5})
        assert set(got) == {1, 2, 3, 4}
        with pytest.raises(EcError):
            ec.minimum_to_decode({0}, {1, 2, 3})


class TestChunkMapping:
    def test_mapping_remaps_positions(self):
        # mapping=_DDDD puts a coding chunk at position 0
        # (ErasureCode.cc:260-279).
        ec = ErasureCodeTpuRs()
        ec.init({"k": "4", "m": "1", "mapping": "_DDDD"})
        assert ec.get_chunk_mapping() == [1, 2, 3, 4, 0]
        raw = payload(4 * 128, seed=11)
        encoded = ec.encode(set(range(5)), raw)
        # Data lives at positions 1..4; parity at 0.
        data = np.frombuffer(raw, dtype=np.uint8).reshape(4, 128)
        for i in range(4):
            assert np.array_equal(encoded[i + 1], data[i])
        expect = np.bitwise_xor.reduce(data, axis=0)
        assert np.array_equal(encoded[0], expect)
        out = ec.decode_concat({i: encoded[i] for i in (0, 2, 3, 4)})
        assert out.tobytes() == raw


class TestRegistry:
    def fresh_registry(self):
        return ErasureCodePluginRegistry()

    def test_factory_roundtrip(self):
        r = self.fresh_registry()
        profile = {"k": "4", "m": "2"}
        ec = r.factory("tpu", profile)
        assert ec.get_chunk_count() == 6
        assert ec.get_profile() == profile
        assert ec.get_profile() is not profile  # codec owns its copy

    def test_xor_plugin(self):
        r = self.fresh_registry()
        ec = r.factory("xor", {"k": "3"})
        raw = payload(3 * 128)
        encoded = ec.encode(set(range(4)), raw)
        decoded = ec.decode({1}, {i: encoded[i] for i in (0, 2, 3)})
        assert np.array_equal(decoded[1], encoded[1])

    def test_xor_plugin_with_mapping(self):
        # Regression: mapping-aware positions in the example plugin.
        r = self.fresh_registry()
        ec = r.factory("xor", {"k": "2", "mapping": "_DD"})
        raw = payload(2 * 128, seed=9)
        encoded = ec.encode(set(range(3)), raw)
        data = np.frombuffer(raw, dtype=np.uint8).reshape(2, 128)
        assert np.array_equal(encoded[1], data[0])
        assert np.array_equal(encoded[2], data[1])
        assert np.array_equal(encoded[0], data[0] ^ data[1])
        out = ec.decode_concat({0: encoded[0], 2: encoded[2]})
        assert out.tobytes() == raw

    def test_unknown_plugin(self):
        r = self.fresh_registry()
        with pytest.raises(EcError) as ei:
            r.load("doesnotexist")
        assert ei.value.errno == -2  # ENOENT

    def _fake_plugin(self, name, **attrs):
        mod = types.ModuleType(f"{reg_mod.PLUGIN_PACKAGE}.{name}")
        for key, val in attrs.items():
            setattr(mod, key, val)
        sys.modules[mod.__name__] = mod
        return mod

    def test_missing_version(self):
        # ErasureCodePluginMissingVersion.cc analog.
        self._fake_plugin("noversion", __erasure_code_init__=lambda r: None)
        r = self.fresh_registry()
        with pytest.raises(EcError) as ei:
            r.load("noversion")
        assert ei.value.errno == -18  # EXDEV

    def test_bad_version(self):
        self._fake_plugin(
            "badversion",
            __erasure_code_version__="bogus-0",
            __erasure_code_init__=lambda r: None,
        )
        r = self.fresh_registry()
        with pytest.raises(EcError) as ei:
            r.load("badversion")
        assert ei.value.errno == -18

    def test_missing_entry_point(self):
        # ErasureCodePluginMissingEntryPoint.cc analog.
        self._fake_plugin("noentry", __erasure_code_version__=EC_VERSION)
        r = self.fresh_registry()
        with pytest.raises(EcError) as ei:
            r.load("noentry")
        assert ei.value.errno == -2

    def test_init_without_register(self):
        # ErasureCodePluginFailToRegister.cc analog.
        self._fake_plugin(
            "noregister",
            __erasure_code_version__=EC_VERSION,
            __erasure_code_init__=lambda r: None,
        )
        r = self.fresh_registry()
        with pytest.raises(EcError) as ei:
            r.load("noregister")
        assert ei.value.errno == -18

    def test_duplicate_add(self):
        r = self.fresh_registry()
        r.load("xor")
        with pytest.raises(EcError) as ei:
            r.load("xor2_dup") if False else r.add("xor", r.get("xor"))
        assert ei.value.errno == -17  # EEXIST

    def test_preload(self):
        r = self.fresh_registry()
        r.preload("tpu,xor")
        assert r.get("tpu") is not None
        assert r.get("xor") is not None


class TestEncodePipeline:
    """The async encode hand-off (SURVEY §7): completion-queue semantics
    behind the chunk interface, byte-identical to the sync path."""

    def _codec(self):
        from ceph_tpu.codec.registry import instance

        return instance().factory("tpu", {"k": "4", "m": "2"})

    def test_pipelined_parity_matches_sync(self):
        import numpy as np

        from ceph_tpu.codec.matrix_codec import EncodePipeline

        ec = self._codec()
        rng = np.random.default_rng(7)
        chunk = 512
        stripes = []
        for _ in range(10):
            chunks = {i: rng.integers(0, 256, chunk, dtype=np.uint8)
                      if i < 4 else np.zeros(chunk, dtype=np.uint8)
                      for i in range(6)}
            stripes.append(chunks)
        want = []
        for s in stripes:
            ref = {i: s[i].copy() for i in range(6)}
            ec.encode_chunks(ref)
            want.append(ref)

        pipe = EncodePipeline(ec, depth=3)
        tickets = [pipe.submit(s) for s in stripes]
        assert tickets == list(range(1, 11))
        # EVERY ticket is reported exactly once across poll/flush — even
        # ones completed inside submit's backpressure path
        done = pipe.poll() + pipe.flush()
        assert sorted(done) == tickets and len(done) == len(set(done))
        assert pipe.poll() == [] and pipe.flush() == []
        for s, ref in zip(stripes, want):
            for i in range(4, 6):
                assert np.array_equal(s[i], ref[i])

    def test_depth_bounds_inflight(self):
        import numpy as np

        from ceph_tpu.codec.matrix_codec import EncodePipeline

        ec = self._codec()
        pipe = EncodePipeline(ec, depth=2)
        rng = np.random.default_rng(8)
        for _ in range(6):
            chunks = {i: rng.integers(0, 256, 256, dtype=np.uint8)
                      if i < 4 else np.zeros(256, dtype=np.uint8)
                      for i in range(6)}
            pipe.submit(chunks)
            assert len(pipe._inflight) <= 2  # backpressure, AIO-depth style
        pipe.flush()
        assert not pipe._inflight

    def test_bench_harness_pipelined_workload(self):
        from ceph_tpu.tools import ec_benchmark

        opts = ec_benchmark.build_parser().parse_args(
            ["-p", "tpu", "-P", "k=4", "-P", "m=2", "-S", "8192", "-i", "4"]
        )
        ec = ec_benchmark.make_codec(opts)
        elapsed = ec_benchmark.run_encode_pipelined(ec, opts, depth=2)
        assert elapsed > 0


class TestGatherZeroCopy:
    """The per-chunk normalization in MatrixCodecMixin._gather must not
    copy buffers that are already contiguous uint8 (every ECBackend call
    site hands exactly that)."""

    def test_contiguous_uint8_passthrough(self):
        from ceph_tpu.codec.matrix_codec import MatrixCodecMixin

        arr = np.arange(256, dtype=np.uint8)
        assert MatrixCodecMixin._as_u8(arr) is arr

    def test_bytes_and_bytearray_zero_copy(self):
        from ceph_tpu.codec.matrix_codec import MatrixCodecMixin

        raw = bytes(range(256))
        out = MatrixCodecMixin._as_u8(raw)
        assert out.dtype == np.uint8 and out.tobytes() == raw
        # frombuffer shares the caller's memory — no copy
        assert np.shares_memory(out, np.frombuffer(raw, dtype=np.uint8))
        ba = bytearray(raw)
        assert np.shares_memory(MatrixCodecMixin._as_u8(ba), np.frombuffer(ba, dtype=np.uint8))

    def test_non_contiguous_and_wrong_dtype_normalized(self):
        from ceph_tpu.codec.matrix_codec import MatrixCodecMixin

        # strided uint8 views pass through as views: np.stack in _gather
        # pays the gather's single copy (no double copy here)
        strided = np.arange(512, dtype=np.uint8)[::2]
        out = MatrixCodecMixin._as_u8(strided)
        assert np.array_equal(out, strided)
        assert np.shares_memory(out, strided)
        wide = np.arange(64, dtype=np.uint16)
        out = MatrixCodecMixin._as_u8(wide)
        assert out.dtype == np.uint8 and np.array_equal(out, wide.astype(np.uint8))

    def test_gather_encode_order_and_result(self):
        ec = make_rs(4, 2)
        rng = np.random.default_rng(3)
        chunks = {i: rng.integers(0, 256, 128, dtype=np.uint8) for i in range(6)}
        stacked = ec._gather(chunks)
        for i in range(4):
            assert np.array_equal(stacked[i], chunks[ec.chunk_index(i)])

    def test_gather_microbench_fast_path_wins(self):
        """Micro-bench: gathering contiguous uint8 chunks (no per-chunk
        copy) must beat gathering chunks that force normalization copies.
        Best-of-N timing on MiB-scale buffers keeps this robust."""
        import time

        ec = make_rs(8, 3)
        rng = np.random.default_rng(4)
        L = 256 * 1024
        fast_chunks = {
            i: np.ascontiguousarray(rng.integers(0, 256, L, dtype=np.uint8))
            for i in range(11)
        }
        # same values, but a wider dtype forces a per-chunk conversion
        # copy before the stack — the work the fast path skips
        slow_src = {i: fast_chunks[i].astype(np.uint16) for i in range(11)}

        def best_of(f, n=5):
            times = []
            for _ in range(n):
                t0 = time.perf_counter()
                f()
                times.append(time.perf_counter() - t0)
            return min(times)

        t_fast = best_of(lambda: ec._gather(fast_chunks))
        t_slow = best_of(lambda: ec._gather(slow_src))
        assert np.array_equal(ec._gather(fast_chunks), ec._gather(slow_src))
        # the no-copy path does strictly less work (stack only) than the
        # normalize-then-stack path (per-chunk copy + stack), so with
        # best-of-5 min timing it must win outright — a margin above 1.0
        # would let a reintroduced per-chunk copy slip through
        assert t_fast < t_slow, (t_fast, t_slow)
