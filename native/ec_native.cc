// libec_native.so — the native erasure-coding region engine, built as a
// dlopen-able plugin with the reference's entry-point ABI.
//
// Reference model: /root/reference/src/erasure-code/ErasureCodePlugin.cc
// loads `libec_<name>.so` with RTLD_NOW, requires `__erasure_code_version`
// (mismatch -> -EXDEV, :134-143) and `__erasure_code_init` (:145-163); the
// isa plugin's compute core is isa-l's `ec_encode_data` over split nibble
// tables (src/erasure-code/isa/ErasureCodeIsa.cc:129) with `region_xor`
// fast paths (isa/xor_op.cc).  This engine mirrors that compute model:
//
// - per-coefficient 2x16 nibble tables (the PSHUFB formulation isa-l's
//   assembly uses): mul(c, x) = LO[c][x & 15] ^ HI[c][x >> 4];
// - `ec_tables_apply` is the generic rows x cols region product serving
//   both encode (rows=m over the k data chunks) and decode (rows=#erased
//   over the k survivors) — the host computes the matrices, the engine
//   does the byte crunching, exactly the isa split;
// - GF(2^8) over 0x11d, matching ceph_tpu/gf/tables.py and isa-l ec_base;
// - `ec_gf_invert_matrix` mirrors isa-l's gf_invert_matrix (returns -1 on
//   a singular matrix, ErasureCodeIsa.cc:275-278);
// - vectorized with GCC vector extensions (pshufb on SSSE3), scalar
//   fallback elsewhere.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#define EC_NATIVE_VERSION "ceph-tpu-ec-1.0"

static const unsigned GF_POLY = 0x11d;

static uint8_t gf_mul_table[256][256];
static bool tables_ready = false;

static void build_gf_tables() {
  if (tables_ready) return;
  // log/exp by repeated multiplication by alpha=2 (gf/tables.py twin)
  int log_t[256];
  uint8_t exp_t[512];
  unsigned x = 1;
  for (int i = 0; i < 255; i++) {
    exp_t[i] = (uint8_t)x;
    exp_t[i + 255] = (uint8_t)x;
    log_t[x] = i;
    x <<= 1;
    if (x & 0x100) x ^= GF_POLY;
  }
  log_t[0] = -1;
  for (int a = 0; a < 256; a++)
    for (int b = 0; b < 256; b++)
      gf_mul_table[a][b] =
          (a && b) ? exp_t[log_t[a] + log_t[b]] : 0;
  tables_ready = true;
}

static inline uint8_t gf_mul(uint8_t a, uint8_t b) { return gf_mul_table[a][b]; }

extern "C" {

// ---- plugin entry points (ErasureCodePlugin.cc ABI) ------------------------

const char *__erasure_code_version(void) { return EC_NATIVE_VERSION; }

int __erasure_code_init(const char *plugin_name, const char *directory) {
  (void)plugin_name;
  (void)directory;
  build_gf_tables();
  return 0;
}

// ---- coding tables ---------------------------------------------------------

struct ec_tables {
  int rows;
  int cols;
  // per (row, col) coefficient: 16B low-nibble + 16B high-nibble products
  uint8_t *nibbles;  // rows * cols * 32
  uint8_t *matrix;   // rows * cols raw coefficients
};

void *ec_tables_new(int rows, int cols, const uint8_t *matrix) {
  build_gf_tables();
  ec_tables *t = new ec_tables;
  t->rows = rows;
  t->cols = cols;
  t->nibbles = (uint8_t *)malloc((size_t)rows * cols * 32);
  t->matrix = (uint8_t *)malloc((size_t)rows * cols);
  memcpy(t->matrix, matrix, (size_t)rows * cols);
  for (int r = 0; r < rows; r++) {
    for (int c = 0; c < cols; c++) {
      uint8_t coef = matrix[r * cols + c];
      uint8_t *lo = t->nibbles + ((size_t)r * cols + c) * 32;
      uint8_t *hi = lo + 16;
      for (int i = 0; i < 16; i++) {
        lo[i] = gf_mul(coef, (uint8_t)i);
        hi[i] = gf_mul(coef, (uint8_t)(i << 4));
      }
    }
  }
  return t;
}

void ec_tables_free(void *handle) {
  ec_tables *t = (ec_tables *)handle;
  free(t->nibbles);
  free(t->matrix);
  delete t;
}

#if defined(__SSSE3__)
typedef uint8_t v16 __attribute__((vector_size(16)));

static inline void region_mul_xor(const uint8_t *lo, const uint8_t *hi,
                                  const uint8_t *in, uint8_t *out, size_t len) {
  v16 vlo, vhi;
  memcpy(&vlo, lo, 16);
  memcpy(&vhi, hi, 16);
  const v16 mask = {15, 15, 15, 15, 15, 15, 15, 15,
                    15, 15, 15, 15, 15, 15, 15, 15};
  size_t i = 0;
  for (; i + 16 <= len; i += 16) {
    v16 x;
    memcpy(&x, in + i, 16);
    v16 lo_idx = x & mask;
    v16 hi_idx = (x >> 4) & mask;
    v16 prod = __builtin_shuffle(vlo, lo_idx) ^ __builtin_shuffle(vhi, hi_idx);
    v16 acc;
    memcpy(&acc, out + i, 16);
    acc ^= prod;
    memcpy(out + i, &acc, 16);
  }
  for (; i < len; i++)
    out[i] ^= lo[in[i] & 15] ^ hi[in[i] >> 4];
}
#else
static inline void region_mul_xor(const uint8_t *lo, const uint8_t *hi,
                                  const uint8_t *in, uint8_t *out, size_t len) {
  for (size_t i = 0; i < len; i++)
    out[i] ^= lo[in[i] & 15] ^ hi[in[i] >> 4];
}
#endif

// out[r] = sum_c matrix[r][c] * in[c]  over GF(2^8), region-wise
// (the ec_encode_data shape: serves encode AND decode).
void ec_tables_apply(void *handle, const uint8_t *const *in,
                     uint8_t *const *out, size_t len) {
  ec_tables *t = (ec_tables *)handle;
  for (int r = 0; r < t->rows; r++) {
    memset(out[r], 0, len);
    for (int c = 0; c < t->cols; c++) {
      uint8_t coef = t->matrix[r * t->cols + c];
      if (coef == 0) continue;
      const uint8_t *nib = t->nibbles + ((size_t)r * t->cols + c) * 32;
      if (coef == 1) {
        // XOR fast path (region_xor, isa/xor_op.cc)
        const uint8_t *src = in[c];
        uint8_t *dst = out[r];
        size_t i = 0;
        for (; i + 8 <= len; i += 8) {
          uint64_t a, b;
          memcpy(&a, dst + i, 8);
          memcpy(&b, src + i, 8);
          a ^= b;
          memcpy(dst + i, &a, 8);
        }
        for (; i < len; i++) dst[i] ^= src[i];
      } else {
        region_mul_xor(nib, nib + 16, in[c], out[r], len);
      }
    }
  }
}

// ---- matrix inversion (isa-l gf_invert_matrix twin) ------------------------

int ec_gf_invert_matrix(const uint8_t *in, uint8_t *out, int n) {
  build_gf_tables();
  // Gauss-Jordan over GF(2^8) on [A | I]
  uint8_t *a = (uint8_t *)malloc((size_t)n * n);
  memcpy(a, in, (size_t)n * n);
  for (int i = 0; i < n; i++)
    for (int j = 0; j < n; j++) out[i * n + j] = (i == j);
  for (int col = 0; col < n; col++) {
    int pivot = -1;
    for (int r = col; r < n; r++)
      if (a[r * n + col]) { pivot = r; break; }
    if (pivot < 0) { free(a); return -1; }  // singular
    if (pivot != col) {
      for (int j = 0; j < n; j++) {
        uint8_t tmp = a[col * n + j];
        a[col * n + j] = a[pivot * n + j];
        a[pivot * n + j] = tmp;
        tmp = out[col * n + j];
        out[col * n + j] = out[pivot * n + j];
        out[pivot * n + j] = tmp;
      }
    }
    // normalize the pivot row: multiply by inverse of pivot
    uint8_t piv = a[col * n + col];
    uint8_t inv = 1;
    for (int x = 1; x < 256; x++)
      if (gf_mul(piv, (uint8_t)x) == 1) { inv = (uint8_t)x; break; }
    for (int j = 0; j < n; j++) {
      a[col * n + j] = gf_mul(a[col * n + j], inv);
      out[col * n + j] = gf_mul(out[col * n + j], inv);
    }
    for (int r = 0; r < n; r++) {
      if (r == col) continue;
      uint8_t f = a[r * n + col];
      if (!f) continue;
      for (int j = 0; j < n; j++) {
        a[r * n + j] ^= gf_mul(f, a[col * n + j]);
        out[r * n + j] ^= gf_mul(f, out[col * n + j]);
      }
    }
  }
  free(a);
  return 0;
}

// ---- plain region xor (m==1 encode fast path) ------------------------------

void ec_region_xor(const uint8_t *const *in, int n, uint8_t *out, size_t len) {
  memset(out, 0, len);
  for (int c = 0; c < n; c++) {
    const uint8_t *src = in[c];
    size_t i = 0;
    for (; i + 8 <= len; i += 8) {
      uint64_t a, b;
      memcpy(&a, out + i, 8);
      memcpy(&b, src + i, 8);
      a ^= b;
      memcpy(out + i, &a, 8);
    }
    for (; i < len; i++) out[i] ^= src[i];
  }
}

}  // extern "C"
