// CRUSH hashing + straw2 selection, native twin of ceph_tpu/crush/.
//
// The reference keeps CRUSH in C (src/crush/mapper.c) because placement is
// branchy integer hashing — a CPU workload (SURVEY.md §2.3).  This file
// implements the same fixed-point math as ceph_tpu/crush/crush.py; the
// Python side hands over its log2 table at init so both languages pick
// identical winners (verified by tests/test_crush.py).

#include <cstdint>
#include <cstring>

namespace {

constexpr uint32_t kHashSeed = 1315423911u;

// Jenkins 96-bit mix (public domain lookup2 mixing step).
inline void mix(uint32_t& a, uint32_t& b, uint32_t& c) {
  a -= b; a -= c; a ^= c >> 13;
  b -= c; b -= a; b ^= a << 8;
  c -= a; c -= b; c ^= b >> 13;
  a -= b; a -= c; a ^= c >> 12;
  b -= c; b -= a; b ^= a << 16;
  c -= a; c -= b; c ^= b >> 5;
  a -= b; a -= c; a ^= c >> 3;
  b -= c; b -= a; b ^= a << 10;
  c -= a; c -= b; c ^= b >> 15;
}

int32_t g_ln16[65536];
bool g_ln16_set = false;

}  // namespace

extern "C" {

uint32_t ceph_tpu_crush_hash32(uint32_t a) {
  uint32_t h = kHashSeed ^ a;
  uint32_t x = 231232, y = 1232;
  mix(a, x, h);
  mix(y, a, h);
  return h;
}

uint32_t ceph_tpu_crush_hash32_2(uint32_t a, uint32_t b) {
  uint32_t h = kHashSeed ^ a ^ b;
  uint32_t x = 231232, y = 1232;
  mix(a, b, h);
  mix(x, a, h);
  mix(b, y, h);
  return h;
}

uint32_t ceph_tpu_crush_hash32_3(uint32_t a, uint32_t b, uint32_t c) {
  uint32_t h = kHashSeed ^ a ^ b ^ c;
  uint32_t x = 231232, y = 1232;
  mix(a, b, h);
  mix(c, x, h);
  mix(y, a, h);
  mix(b, x, h);
  return h;
}

// Install the Python-generated fixed-point log2 table (65536 entries).
void ceph_tpu_crush_set_ln_table(const int32_t* table) {
  std::memcpy(g_ln16, table, sizeof(g_ln16));
  g_ln16_set = true;
}

int ceph_tpu_crush_ln_table_set(void) { return g_ln16_set ? 1 : 0; }

// straw2 winner among n items: largest ln(hash16)/weight draw
// (mapper.c bucket_straw2_choose semantics; fixed-point as in Python).
// Returns CRUSH_ITEM_NONE (0x7fffffff) when no item has positive weight.
int32_t ceph_tpu_straw2_choose(uint32_t x, uint32_t r, const int32_t* items,
                               const int32_t* weights, int32_t n) {
  int32_t best_item = 0x7fffffff;
  int64_t best_draw = 0;
  bool have_best = false;
  for (int32_t i = 0; i < n; i++) {
    if (weights[i] <= 0) continue;
    uint32_t u =
        ceph_tpu_crush_hash32_3(x, static_cast<uint32_t>(items[i]), r) & 0xffff;
    // multiply, not <<: left-shifting a negative int64 is UB pre-C++20
    int64_t draw = (static_cast<int64_t>(g_ln16[u]) * 65536) / weights[i];
    if (!have_best || draw > best_draw) {
      have_best = true;
      best_draw = draw;
      best_item = items[i];
    }
  }
  return best_item;
}

}  // extern "C"
