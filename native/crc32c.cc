// crc32c (Castagnoli) — native runtime piece of the TPU erasure framework.
//
// The reference keeps per-shard cumulative crc32c digests in the `hinfo`
// xattr (/root/reference/src/osd/ECUtil.h:101-160) and computes them on the
// CPU next to the coding loop.  This is the equivalent native path: SSE4.2
// hardware crc32 when available (runtime-probed), with a software
// slicing-by-8 fallback; exported with a plain C ABI for the ctypes binding
// in ceph_tpu/utils/crc32c.py.
//
// Build: see native/Makefile (g++ -O3, no external deps).

#include <cstddef>
#include <cstdint>

#if defined(__x86_64__) || defined(_M_X64)
#include <cpuid.h>
#include <nmmintrin.h>
#define HAVE_X86 1
#endif

namespace {

constexpr uint32_t kPoly = 0x82f63b78u;  // reflected Castagnoli

uint32_t g_table[8][256];
bool g_table_ready = false;

void build_tables() {
  for (int i = 0; i < 256; i++) {
    uint32_t c = static_cast<uint32_t>(i);
    for (int j = 0; j < 8; j++) {
      c = (c & 1) ? (c >> 1) ^ kPoly : c >> 1;
    }
    g_table[0][i] = c;
  }
  for (int i = 0; i < 256; i++) {
    uint32_t c = g_table[0][i];
    for (int s = 1; s < 8; s++) {
      c = g_table[0][c & 0xff] ^ (c >> 8);
      g_table[s][i] = c;
    }
  }
  g_table_ready = true;
}

uint32_t crc32c_sw(uint32_t crc, const uint8_t* data, size_t len) {
  if (!g_table_ready) build_tables();
  crc = ~crc;
  // Slicing-by-8 over aligned 8-byte blocks.
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, data, 8);
    word ^= crc;
    crc = g_table[7][word & 0xff] ^ g_table[6][(word >> 8) & 0xff] ^
          g_table[5][(word >> 16) & 0xff] ^ g_table[4][(word >> 24) & 0xff] ^
          g_table[3][(word >> 32) & 0xff] ^ g_table[2][(word >> 40) & 0xff] ^
          g_table[1][(word >> 48) & 0xff] ^ g_table[0][(word >> 56) & 0xff];
    data += 8;
    len -= 8;
  }
  while (len--) {
    crc = g_table[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  }
  return ~crc;
}

#ifdef HAVE_X86
bool have_sse42() {
  unsigned eax, ebx, ecx, edx;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
  return (ecx & bit_SSE4_2) != 0;
}

uint32_t crc32c_hw(uint32_t crc, const uint8_t* data, size_t len) {
  uint64_t c = ~crc;
  while (len >= 8) {
    uint64_t word;
    __builtin_memcpy(&word, data, 8);
    c = _mm_crc32_u64(c, word);
    data += 8;
    len -= 8;
  }
  uint32_t c32 = static_cast<uint32_t>(c);
  while (len--) {
    c32 = _mm_crc32_u8(c32, *data++);
  }
  return ~c32;
}
#endif

}  // namespace

extern "C" {

// Cumulative crc32c: pass the previous digest to chain blocks, matching the
// reference's append-only per-shard digests (ECUtil.h `HashInfo`).
uint32_t ceph_tpu_crc32c(uint32_t crc, const uint8_t* data, size_t len) {
#ifdef HAVE_X86
  static const bool hw = have_sse42();
  if (hw) return crc32c_hw(crc, data, len);
#endif
  return crc32c_sw(crc, data, len);
}

int ceph_tpu_crc32c_hw_available() {
#ifdef HAVE_X86
  return have_sse42() ? 1 : 0;
#else
  return 0;
#endif
}

}  // extern "C"
