/* isal_scalar — compiled foreign golden-vector generator.
 *
 * Clean-room C implementation of ISA-L's PUBLISHED scalar erasure-code
 * base semantics (isa-l ec_base.c: gf_mul / gf_inv / gf_gen_rs_matrix /
 * gf_gen_cauchy1_matrix / gf_invert_matrix / ec_encode_data), written
 * from the algorithm spec — the reference checkout vendors no isa-l
 * sources to copy (/root/reference/src/erasure-code/isa/README:1 merely
 * documents the library dependency; ErasureCodeIsa.cc:119-131 calls it).
 *
 * Purpose (round-5 verdict item 7): the byte-identity claim of the tpu
 * plugin vs the `isa` plugin must rest on COMPILED foreign code, not
 * only on the Python re-derivation in tests/isal_reference.py.  This
 * file uses log/antilog tables over the 0x11d field — ISA-L ec_base's
 * own mechanism, and a third mechanism overall (the Python oracle uses
 * peasant multiplies; production ceph_tpu.gf uses numpy mul tables), so
 * all three agreeing is a genuine cross-check.
 *
 * Protocol (stdout, binary):
 *   argv: k m technique(rs|cauchy) chunk_size seed
 *   emits: (k+m)*k matrix bytes, then k data chunks (the LCG input
 *   split), then m parity chunks from ec_encode_data — chunk_size each.
 * tests/test_isal_golden.py builds this via native/Makefile and
 * byte-compares the production plugin's chunks against the output.
 */

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define GF_POLY 0x11d /* x^8+x^4+x^3+x^2+1, the ec_base field */

static uint8_t gflog[256];
static uint8_t gfexp[256 * 2]; /* doubled so mul skips one mod-255 */

static void gf_tables_init(void) {
    /* generator 2 walks the whole multiplicative group in this field */
    unsigned v = 1;
    for (int i = 0; i < 255; i++) {
        gfexp[i] = (uint8_t)v;
        gfexp[i + 255] = (uint8_t)v;
        gflog[v] = (uint8_t)i;
        v <<= 1;
        if (v & 0x100)
            v ^= GF_POLY;
    }
    gflog[0] = 0; /* unused: mul/inv guard zero explicitly */
}

static uint8_t gf_mul(uint8_t a, uint8_t b) {
    if (a == 0 || b == 0)
        return 0;
    return gfexp[gflog[a] + gflog[b]];
}

static uint8_t gf_inv(uint8_t a) {
    if (a == 0) {
        fprintf(stderr, "gf_inv(0)\n");
        exit(3);
    }
    return gfexp[255 - gflog[a]];
}

/* gf_gen_rs_matrix: identity atop geometric rows of gen = 2^i (parity
 * row 0 all-ones). */
static void gen_rs_matrix(uint8_t *a, int k, int m) {
    memset(a, 0, (size_t)(k + m) * k);
    for (int i = 0; i < k; i++)
        a[i * k + i] = 1;
    uint8_t gen = 1;
    for (int i = 0; i < m; i++) {
        uint8_t p = 1;
        for (int j = 0; j < k; j++) {
            a[(k + i) * k + j] = p;
            p = gf_mul(p, gen);
        }
        gen = gf_mul(gen, 2);
    }
}

/* gf_gen_cauchy1_matrix: parity[i][j] = 1 / ((k+i) ^ j). */
static void gen_cauchy1_matrix(uint8_t *a, int k, int m) {
    memset(a, 0, (size_t)(k + m) * k);
    for (int i = 0; i < k; i++)
        a[i * k + i] = 1;
    for (int i = k; i < k + m; i++)
        for (int j = 0; j < k; j++)
            a[i * k + j] = gf_inv((uint8_t)(i ^ j));
}

/* ec_encode_data, scalar base: parity[p][x] = XOR_j c[p][j] * d[j][x]. */
static void encode(const uint8_t *coding, int m, int k, long len,
                   uint8_t *const *data, uint8_t *const *parity) {
    for (int p = 0; p < m; p++) {
        memset(parity[p], 0, (size_t)len);
        for (int j = 0; j < k; j++) {
            uint8_t c = coding[p * k + j];
            if (c == 0)
                continue;
            const uint8_t *d = data[j];
            uint8_t *out = parity[p];
            if (c == 1) {
                for (long x = 0; x < len; x++)
                    out[x] ^= d[x];
            } else {
                const uint8_t *row = &gfexp[gflog[c]];
                for (long x = 0; x < len; x++)
                    if (d[x])
                        out[x] ^= row[gflog[d[x]]];
            }
        }
    }
}

/* Deterministic input: the SAME musl LCG as tests/isal_reference.py
 * lcg_bytes, so Python and C generate identical data streams. */
static void lcg_fill(uint8_t *buf, long n, uint32_t seed) {
    uint32_t state = seed;
    for (long i = 0; i < n; i++) {
        state = state * 1103515245u + 12345u;
        buf[i] = (uint8_t)(state >> 16);
    }
}

int main(int argc, char **argv) {
    if (argc != 6) {
        fprintf(stderr,
                "usage: %s k m rs|cauchy chunk_size seed\n", argv[0]);
        return 2;
    }
    int k = atoi(argv[1]);
    int m = atoi(argv[2]);
    const char *tech = argv[3];
    long chunk = atol(argv[4]);
    uint32_t seed = (uint32_t)strtoul(argv[5], NULL, 0);
    if (k <= 0 || m <= 0 || k + m > 255 || chunk <= 0) {
        fprintf(stderr, "bad geometry\n");
        return 2;
    }
    gf_tables_init();

    uint8_t *matrix = malloc((size_t)(k + m) * k);
    if (strcmp(tech, "cauchy") == 0)
        gen_cauchy1_matrix(matrix, k, m);
    else
        gen_rs_matrix(matrix, k, m);

    uint8_t *raw = malloc((size_t)k * chunk);
    lcg_fill(raw, (long)k * chunk, seed);
    uint8_t **data = malloc(sizeof(uint8_t *) * k);
    for (int j = 0; j < k; j++)
        data[j] = raw + (size_t)j * chunk;
    uint8_t **parity = malloc(sizeof(uint8_t *) * m);
    for (int p = 0; p < m; p++)
        parity[p] = malloc((size_t)chunk);

    encode(matrix + (size_t)k * k, m, k, chunk, data, parity);

    fwrite(matrix, 1, (size_t)(k + m) * k, stdout);
    fwrite(raw, 1, (size_t)k * chunk, stdout);
    for (int p = 0; p < m; p++)
        fwrite(parity[p], 1, (size_t)chunk, stdout);
    fflush(stdout);
    return 0;
}
