"""Pod-scale data distribution: mesh construction, sharded stripe
pipelines, and the live sharded-dispatch policy (parallel.dispatch)."""

from .mesh import make_mesh
from .sharded import sharded_decode, sharded_encode, scrub_step

__all__ = ["make_mesh", "sharded_encode", "sharded_decode", "scrub_step"]
