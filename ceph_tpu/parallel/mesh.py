"""Device mesh construction for stripe-parallel erasure coding.

The TPU-native mapping of the reference's data-distribution layer (SURVEY.md
§2.4): the stripe-batch axis plays the role PGs play (independent shards of
work, data-parallel across the pod over ICI) and the intra-chunk byte axis is
the "sequence" axis — GF coding is bytewise-independent, so chunk length can
be split across devices with zero communication, the storage analog of
sequence parallelism.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

STRIPE_AXIS = "stripe"  # data-parallel over stripe batches (PG analog)
LANE_AXIS = "lane"  # intra-chunk byte-range parallelism (SP analog)


def make_mesh(
    n_devices: int | None = None,
    lane_parallelism: int | None = None,
) -> Mesh:
    """Build a (stripe, lane) 2-D mesh over the first n_devices.

    lane_parallelism defaults to the largest power-of-two <= sqrt(n) that
    divides n, keeping both axes useful without fragmenting either.
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    if lane_parallelism is None:
        lane_parallelism = 1
        while (
            lane_parallelism * 2 <= math.isqrt(n)
            and n % (lane_parallelism * 2) == 0
        ):
            lane_parallelism *= 2
    assert n % lane_parallelism == 0
    import numpy as np

    grid = np.empty(n, dtype=object)
    for i, d in enumerate(devices):
        grid[i] = d
    grid = grid.reshape(n // lane_parallelism, lane_parallelism)
    return Mesh(grid, (STRIPE_AXIS, LANE_AXIS))
