"""Device mesh construction for stripe-parallel erasure coding.

The TPU-native mapping of the reference's data-distribution layer (SURVEY.md
§2.4): the stripe-batch axis plays the role PGs play (independent shards of
work, data-parallel across the pod over ICI) and the intra-chunk byte axis is
the "sequence" axis — GF coding is bytewise-independent, so chunk length can
be split across devices with zero communication, the storage analog of
sequence parallelism.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

POD_AXIS = "pod"  # DCN boundary: pods are islands of fast ICI
STRIPE_AXIS = "stripe"  # data-parallel over stripe batches (PG analog)
LANE_AXIS = "lane"  # intra-chunk byte-range parallelism (SP analog)


def make_mesh(
    n_devices: int | None = None,
    lane_parallelism: int | None = None,
    pods: int = 1,
) -> Mesh:
    """Build a (stripe, lane) 2-D mesh — or (pod, stripe, lane) 3-D with
    `pods` > 1 — over the first n_devices.

    lane_parallelism defaults to the largest power-of-two <= sqrt(n/pods)
    that divides n/pods, keeping both intra-pod axes useful without
    fragmenting either.

    The pod axis is the DCN boundary (multi-pod deployments: devices within
    a pod share ICI; pods talk over data-center network).  Shardings place
    stripes over ('pod', 'stripe') jointly, so bulk chunk bytes NEVER cross
    the pod boundary — only scalar scrub reductions do (see
    sharded.scrub_step), which is the right DCN design: ICI carries tiles,
    DCN carries verdicts.  Device order follows jax.devices(), which enumerates
    ICI-adjacent devices contiguously, so a contiguous slice per pod row
    matches the physical topology.
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    assert n % pods == 0, (n, pods)
    per_pod = n // pods
    if lane_parallelism is None:
        lane_parallelism = 1
        while (
            lane_parallelism * 2 <= math.isqrt(per_pod)
            and per_pod % (lane_parallelism * 2) == 0
        ):
            lane_parallelism *= 2
    assert per_pod % lane_parallelism == 0
    import numpy as np

    grid = np.empty(n, dtype=object)
    for i, d in enumerate(devices):
        grid[i] = d
    if pods > 1:
        grid = grid.reshape(pods, per_pod // lane_parallelism, lane_parallelism)
        return Mesh(grid, (POD_AXIS, STRIPE_AXIS, LANE_AXIS))
    grid = grid.reshape(per_pod // lane_parallelism, lane_parallelism)
    return Mesh(grid, (STRIPE_AXIS, LANE_AXIS))
