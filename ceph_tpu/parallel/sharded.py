"""Sharded stripe-batch pipelines — pjit/shard_map over a (stripe, lane) mesh.

The bulk scrub/rebuild data path (SURVEY.md §7 step 6; BASELINE config
"RS(10,4) batched encode, 64K stripes in flight"): stripe batches are sharded
data-parallel over the mesh's `stripe` axis, chunk bytes over `lane` (GF
coding is bytewise independent, so both axes need no communication for
encode/decode).  Cross-device work appears only in verification/scrub
reductions (psum over both axes) — those are the collectives that ride ICI,
playing the role the reference's messenger fan-out plays for `ECSubWrite`
(/root/reference/src/osd/ECBackend.cc:2071-2120).

Multi-pod meshes (mesh.make_mesh(pods=N)) add a leading DCN axis: stripes
shard over ('pod', 'stripe') jointly, so chunk bytes stay inside their pod
and only the scalar scrub verdict reduces across DCN.

Two encode paths:
- `sharded_encode(bit_matrix, ...)` — the jnp XOR-matmul partitioned by
  XLA's sharding propagation; runs on any backend.
- `sharded_plan_encode(plan, ...)` — shard_map: every device runs the fused
  Pallas SWAR kernel (ops.pallas_gf.CodingPlan) on its local tile.  This is
  the production TPU path, the same kernel `encode_chunks` ships; XLA can't
  partition a pallas_call automatically, so the per-device view is explicit.
"""

from __future__ import annotations

import functools
from collections import OrderedDict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import numpy as np

from ceph_tpu.common.mempool import track_buffer
from ceph_tpu.ops.dispatch import record_launch
from ceph_tpu.ops.packed_gf import PackedPlan, _packed_code_impl
from ceph_tpu.ops.pallas_gf import CodingPlan
from ceph_tpu.ops.xor_mm import xor_matmul

from .mesh import LANE_AXIS, POD_AXIS, STRIPE_AXIS


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """jax.shard_map across jax versions: top-level with `check_vma` on
    new jax, `jax.experimental.shard_map` with the old `check_rep`
    spelling on 0.4.x (which has no `jax.shard_map` at all)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def _stripe_axes(mesh: Mesh):
    """Mesh axes the stripe dim shards over: pods join the stripe axis so
    bulk bytes never cross the DCN boundary."""
    if POD_AXIS in mesh.axis_names:
        return (POD_AXIS, STRIPE_AXIS)
    return STRIPE_AXIS


def _stripe_spec(mesh: Mesh) -> P:
    # (S, k, L): shard stripes over `(pod,) stripe`, chunk bytes over `lane`.
    return P(_stripe_axes(mesh), None, LANE_AXIS)


def _stripe_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, _stripe_spec(mesh))


def _stripe_shards(mesh: Mesh) -> int:
    n = mesh.shape[STRIPE_AXIS]
    if POD_AXIS in mesh.axis_names:
        n *= mesh.shape[POD_AXIS]
    return n


def shard_batch(data: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a (S, k, L) stripe batch with stripe+lane sharding.

    Batches that don't divide the mesh are zero-padded up to the next
    divisible (S, L) — exact for GF coding (zero stripes encode to zero
    parity, and scrub sees matching zeros), so callers slice results back to
    their logical shape with `result[:S, ..., :L]`.
    """
    S, _, L = data.shape
    pad_s = -S % _stripe_shards(mesh)
    pad_l = -L % mesh.shape[LANE_AXIS]
    if pad_s or pad_l:
        data = jnp.pad(data, ((0, pad_s), (0, 0), (0, pad_l)))
    # HBM ledger (ISSUE 13): the placement is resident until the launch
    # retires and the caller drops it — GC-tracked, not hand-released
    return track_buffer(
        jax.device_put(data, _stripe_sharding(mesh)), "sharded_placement"
    )


@functools.cache
def _encode_executable(mesh: Mesh):
    """One held jit wrapper per mesh.

    Building `jax.jit(...)` inside every call would discard its trace cache
    each time; holding the wrapper makes steady-state launches (the 64K
    stripes-in-flight bulk-rebuild config, BASELINE config 3) pure cache
    hits — the device analog of the reference's precomputed-table reuse
    (isa/ErasureCodeIsaTableCache.h:48).
    """
    return jax.jit(
        xor_matmul,
        in_shardings=(NamedSharding(mesh, P()), _stripe_sharding(mesh)),
        out_shardings=_stripe_sharding(mesh),
    )


def sharded_encode(bit_matrix: jax.Array, data: jax.Array, mesh: Mesh) -> jax.Array:
    """(S, k, L) uint8 -> (S, m, L) parity, fully sharded, no collectives.

    XLA partitions the XOR-matmul per shard; each device encodes its own
    stripe/lane tile — the embarrassingly-parallel layout that turns a pod
    into one wide encoder for bulk rebuild.
    """
    return _encode_executable(mesh)(bit_matrix, data)


def sharded_decode(
    decode_bit_matrix: jax.Array, survivors: jax.Array, mesh: Mesh
) -> jax.Array:
    """(S, k, L) survivors (decode_index order) -> (S, nerrs, L) rebuilt."""
    return sharded_encode(decode_bit_matrix, survivors, mesh)


# Content-keyed LRU of shard_map executables: keyed by the plan's schedule
# (not object identity, so equal matrices reuse one executable) and bounded
# like the codec's decode-coder LRU (matrix_codec.DECODE_LRU_CAPACITY) so
# long-running rebuild services cycling through erasure signatures don't pin
# compiled executables forever.
_PLAN_EXEC_CAPACITY = 256
_plan_execs: "OrderedDict[tuple, object]" = OrderedDict()


def _cached_exec(key: tuple, build):
    """One LRU for every shard_map executable: get-or-build with
    move-to-front and bounded eviction."""
    exe = _plan_execs.get(key)
    if exe is not None:
        _plan_execs.move_to_end(key)
        return exe
    exe = build()
    _plan_execs[key] = exe
    while len(_plan_execs) > _PLAN_EXEC_CAPACITY:
        _plan_execs.popitem(last=False)
    return exe


def _plan_encode_executable(mesh: Mesh, plan: CodingPlan):
    """shard_map wrapper: the fused Pallas kernel on each device's tile.

    The per-device chunk-length tile (L / lane shards) must keep a kernel
    geometry (128-aligned); CodingPlan itself falls back to the jnp matmul
    for tiles that don't, so this is total either way.
    """
    spec = _stripe_spec(mesh)

    def build():
        # check_vma=False: the body is a pallas_call, which can't declare
        # its varying-mesh-axes; operands/results are explicitly sharded.
        local = _shard_map(
            plan, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
        )
        return jax.jit(local)

    return _cached_exec(
        (mesh, plan.sched, plan.m, plan.k, plan.interpret), build
    )


def sharded_plan_encode(plan: CodingPlan, data: jax.Array, mesh: Mesh) -> jax.Array:
    """(S, k, L) uint8 -> (S, m, L) parity via the production Pallas kernel.

    Identical sharding layout to `sharded_encode`, but each device executes
    the compiled SWAR XOR-schedule kernel on its local (S/ns, k, L/nl) tile
    — the multi-chip fan-out of the exact kernel the codec's
    `encode_chunks`/`encode_array` path ships (VERDICT r3 item: the sharded
    path must shard the fast kernel, not the reference matmul).
    """
    return _plan_encode_executable(mesh, plan)(data)


def sharded_plan_decode(
    plan: CodingPlan, survivors: jax.Array, mesh: Mesh
) -> jax.Array:
    """Survivors (decode_index order) -> rebuilt chunks via the Pallas plan
    built from a decode matrix (codec.matrix_codec decode_plan/LRU)."""
    return sharded_plan_encode(plan, survivors, mesh)


def _scrub_impl(bit_matrix, chunks, k):
    data = chunks[:, :k, :]
    stored_parity = chunks[:, k:, :]
    recomputed = xor_matmul(bit_matrix, data)
    # Per-stripe mismatch flag, reduced over the lane axis automatically by
    # XLA's partitioner (psum over lane shards under the hood).
    mismatch = jnp.any(recomputed != stored_parity, axis=(1, 2))
    return jnp.sum(mismatch.astype(jnp.int32)), mismatch


@functools.cache
def _scrub_executable(mesh: Mesh, k: int):
    return jax.jit(
        functools.partial(_scrub_impl, k=k),
        in_shardings=(NamedSharding(mesh, P()), _stripe_sharding(mesh)),
        out_shardings=(
            NamedSharding(mesh, P()),
            NamedSharding(mesh, P(_stripe_axes(mesh))),
        ),
    )


def _plan_scrub_executable(mesh: Mesh, plan: CodingPlan, k: int):
    # k is a real key component (the closure slices with it); it must
    # also agree with the plan's geometry or the compiled executable
    # would be poisoned for later correct calls
    assert k == plan.k, (k, plan.k)
    spec = _stripe_spec(mesh)

    def local(chunks):
        data = chunks[:, :k, :]
        stored_parity = chunks[:, k:, :]
        recomputed = plan(data)  # the production Pallas kernel, per tile
        local_mismatch = jnp.any(recomputed != stored_parity, axis=(1, 2))
        # lane shards each hold a byte-range verdict: OR across the lane
        # axis; the total count sums across every stripe shard (the only
        # cross-pod traffic on a DCN mesh)
        mismatch = jax.lax.pmax(
            local_mismatch.astype(jnp.int32), LANE_AXIS
        ).astype(jnp.bool_)
        # after the lane pmax every lane shard holds identical verdicts,
        # so summing across stripe shards only (no lane sum) counts each
        # stripe exactly once
        count = jax.lax.psum(
            jnp.sum(mismatch.astype(jnp.int32)), _stripe_axes(mesh)
        )
        return count, mismatch

    def build():
        local_sm = _shard_map(
            local,
            mesh=mesh,
            in_specs=spec,
            out_specs=(P(), P(_stripe_axes(mesh))),
            check_vma=False,
        )
        return jax.jit(local_sm)

    return _cached_exec(
        ("scrub", mesh, plan.sched, plan.m, plan.k, k, plan.interpret), build
    )


def plan_scrub_step(
    plan: CodingPlan, chunks: jax.Array, k: int, mesh: Mesh
) -> tuple[jax.Array, jax.Array]:
    """scrub_step with the recompute running the production Pallas kernel
    on each device's tile (shard_map) — the multi-chip scrub ships the
    same kernel as encode_chunks."""
    return _plan_scrub_executable(mesh, plan, k)(chunks)


def _packed_shard_executable(mesh: Mesh, packed: PackedPlan, donate: bool):
    """shard_map wrapper of the packed-plane kernel: each device runs the
    fused plane-tower/XOR-schedule program (ops/packed_gf.py) on its own
    (S/n, k, L) stripe tile — the multi-chip fan-out of the exact kernel
    the aggregated single-device launch ships.

    `donate=True` builds the `_packed_code_into` twin: a dead output
    buffer (already sharded with the output's NamedSharding from a prior
    launch at this geometry) is threaded through as a donated first
    argument, so recurring aggregated launches recycle the allocation on
    every device instead of growing each device's heap."""
    spec = _stripe_spec(mesh)

    def build():
        if donate:
            local = _shard_map(
                # `out` is dead — it exists only to be donated; XLA
                # aliases each device's result tile into it
                lambda out, data: _packed_code_impl(
                    data, packed.sched, packed.k, packed.m
                ),
                mesh=mesh, in_specs=(spec, spec), out_specs=spec,
                check_vma=False,
            )
            return jax.jit(local, donate_argnums=(0,))
        local = _shard_map(
            lambda data: _packed_code_impl(data, packed.sched, packed.k, packed.m),
            mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False,
        )
        return jax.jit(local)

    return _cached_exec(
        ("packed", mesh, packed.sched, packed.k, packed.m, donate), build
    )


def sharded_coder_code(coder, data, mesh: Mesh, out=None) -> jax.Array:
    """One (S, k, L) uint8 coding launch, data-parallel over the mesh's
    stripe axis — the sharded dispatch mode of codec/matrix_codec.py's
    `_DeviceCoder` (ISSUE 6 tentpole).

    `coder` duck-types _DeviceCoder: `.plan` (Pallas CodingPlan or None),
    `.packed` (PackedPlan), `.decode` (kind flag).  The batch is padded
    to a stripe-shard multiple (zero stripes code to zero output — exact
    for GF maps), placed with a NamedSharding over `stripe` (ONE sharded
    H2D instead of a single-device put plus a reshard), run per-device
    via the cached shard_map executable, and sliced back to the logical
    stripe count.  Kernel choice per device mirrors the single-device
    dispatch: the fused Pallas kernel on TPU-aligned chunk lengths
    (lane_parallelism is 1, so the per-device tile keeps L), the packed
    plane kernel otherwise — bytes are identical either way.

    `out`: optional dead device buffer from a prior sharded launch at
    this exact geometry AND sharding; consumed (donated) only on the
    packed path with no remainder padding, ignored otherwise."""
    S, _, L = data.shape
    n = _stripe_shards(mesh)
    pad = -S % n
    record_launch(
        S, int(np.prod(data.shape)), decode=coder.decode, devices=n
    )
    if pad:
        if isinstance(data, np.ndarray):
            data = np.concatenate(
                [data, np.zeros((pad, *data.shape[1:]), dtype=np.uint8)]
            )
        else:
            data = jnp.pad(data, ((0, pad), (0, 0), (0, 0)))
    # HBM ledger (ISSUE 13): the sharded H2D placement is device-resident
    # for the life of the launch — tracked so dump_mempools shows bulk
    # launches' staging alongside the cache/donation/in-flight pools
    placed = track_buffer(
        jax.device_put(data, _stripe_sharding(mesh)), "sharded_placement"
    )
    if coder.plan is not None and L % 128 == 0:
        # trace-time caveat: the CodingPlan wrapper records its own
        # (single) launch while the shard_map body is first traced; the
        # per-dispatch accounting above is the authoritative count
        result = _plan_encode_executable(mesh, coder.plan)(placed)
    else:
        packed = coder.packed
        want = (S + pad, packed.m, L)
        if (
            not pad
            and out is not None
            and tuple(getattr(out, "shape", ())) == want
            and getattr(out, "dtype", None) == jnp.uint8
            and getattr(out, "sharding", None) == _stripe_sharding(mesh)
        ):
            result = _packed_shard_executable(mesh, packed, donate=True)(
                out, placed
            )
        else:
            result = _packed_shard_executable(mesh, packed, donate=False)(placed)
    return result[:S] if pad else result


def scrub_step(
    bit_matrix: jax.Array, chunks: jax.Array, k: int, mesh: Mesh
) -> tuple[jax.Array, jax.Array]:
    """Deep-scrub analog: recompute parity for a (S, k+m, L) batch, compare.

    Returns (total mismatching stripe count, per-stripe mismatch mask) — the
    device-side equivalent of `ECBackend::be_deep_scrub` chunk verification
    (/root/reference/src/osd/ECBackend.cc:2518), with the mismatch count
    produced by cross-device reduction instead of primary-gathered maps.
    On a multi-pod mesh the only DCN traffic is this scalar verdict psum —
    tiles and parity stay inside their pods.
    """
    return _scrub_executable(mesh, k)(bit_matrix, chunks)
