"""Sharded stripe-batch pipelines — pjit over a (stripe, lane) mesh.

The bulk scrub/rebuild data path (SURVEY.md §7 step 6; BASELINE config
"RS(10,4) batched encode, 64K stripes in flight"): stripe batches are sharded
data-parallel over the mesh's `stripe` axis, chunk bytes over `lane` (GF
coding is bytewise independent, so both axes need no communication for
encode/decode).  Cross-device work appears only in verification/scrub
reductions (psum over both axes) — those are the collectives that ride ICI,
playing the role the reference's messenger fan-out plays for `ECSubWrite`
(/root/reference/src/osd/ECBackend.cc:2071-2120).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ceph_tpu.ops.xor_mm import xor_matmul

from .mesh import LANE_AXIS, STRIPE_AXIS


def _stripe_sharding(mesh: Mesh) -> NamedSharding:
    # (S, k, L): shard stripes over `stripe`, chunk bytes over `lane`.
    return NamedSharding(mesh, P(STRIPE_AXIS, None, LANE_AXIS))


def shard_batch(data: jax.Array, mesh: Mesh) -> jax.Array:
    """Place a (S, k, L) stripe batch with stripe+lane sharding.

    Batches that don't divide the mesh are zero-padded up to the next
    divisible (S, L) — exact for GF coding (zero stripes encode to zero
    parity, and scrub sees matching zeros), so callers slice results back to
    their logical shape with `result[:S, ..., :L]`.
    """
    S, _, L = data.shape
    pad_s = -S % mesh.shape[STRIPE_AXIS]
    pad_l = -L % mesh.shape[LANE_AXIS]
    if pad_s or pad_l:
        data = jnp.pad(data, ((0, pad_s), (0, 0), (0, pad_l)))
    return jax.device_put(data, _stripe_sharding(mesh))


@functools.cache
def _encode_executable(mesh: Mesh):
    """One held jit wrapper per mesh.

    Building `jax.jit(...)` inside every call would discard its trace cache
    each time; holding the wrapper makes steady-state launches (the 64K
    stripes-in-flight bulk-rebuild config, BASELINE config 3) pure cache
    hits — the device analog of the reference's precomputed-table reuse
    (isa/ErasureCodeIsaTableCache.h:48).
    """
    return jax.jit(
        xor_matmul,
        in_shardings=(NamedSharding(mesh, P()), _stripe_sharding(mesh)),
        out_shardings=_stripe_sharding(mesh),
    )


def sharded_encode(bit_matrix: jax.Array, data: jax.Array, mesh: Mesh) -> jax.Array:
    """(S, k, L) uint8 -> (S, m, L) parity, fully sharded, no collectives.

    XLA partitions the XOR-matmul per shard; each device encodes its own
    stripe/lane tile — the embarrassingly-parallel layout that turns a pod
    into one wide encoder for bulk rebuild.
    """
    return _encode_executable(mesh)(bit_matrix, data)


def sharded_decode(
    decode_bit_matrix: jax.Array, survivors: jax.Array, mesh: Mesh
) -> jax.Array:
    """(S, k, L) survivors (decode_index order) -> (S, nerrs, L) rebuilt."""
    return sharded_encode(decode_bit_matrix, survivors, mesh)


def _scrub_impl(bit_matrix, chunks, k):
    data = chunks[:, :k, :]
    stored_parity = chunks[:, k:, :]
    recomputed = xor_matmul(bit_matrix, data)
    # Per-stripe mismatch flag, reduced over the lane axis automatically by
    # XLA's partitioner (psum over lane shards under the hood).
    mismatch = jnp.any(recomputed != stored_parity, axis=(1, 2))
    return jnp.sum(mismatch.astype(jnp.int32)), mismatch


@functools.cache
def _scrub_executable(mesh: Mesh, k: int):
    sharding = NamedSharding(mesh, P(STRIPE_AXIS, None, LANE_AXIS))
    return jax.jit(
        functools.partial(_scrub_impl, k=k),
        in_shardings=(NamedSharding(mesh, P()), sharding),
        out_shardings=(NamedSharding(mesh, P()), NamedSharding(mesh, P(STRIPE_AXIS))),
    )


def scrub_step(
    bit_matrix: jax.Array, chunks: jax.Array, k: int, mesh: Mesh
) -> tuple[jax.Array, jax.Array]:
    """Deep-scrub analog: recompute parity for a (S, k+m, L) batch, compare.

    Returns (total mismatching stripe count, per-stripe mismatch mask) — the
    device-side equivalent of `ECBackend::be_deep_scrub` chunk verification
    (/root/reference/src/osd/ECBackend.cc:2518), with the mismatch count
    produced by cross-device reduction instead of primary-gathered maps.
    """
    return _scrub_executable(mesh, k)(bit_matrix, chunks)
