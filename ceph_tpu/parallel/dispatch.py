"""Sharded-dispatch policy: when a coding launch spans the device mesh.

The PR 2/3 aggregators produce exactly the right input for multi-chip
data parallelism — large padded `(batch, k, L)` encode/decode launches —
and this module is the policy layer that decides, per launch, whether
that batch is placed on ONE device (the single-chip path) or sharded
over the `stripe` axis of a device mesh and run per-device via shard_map
(parallel/sharded.py executables).  The decision is the storage analog
of a training stack's data-parallel switch: XOR-based coding is
stripe-wise independent (arXiv:2108.02692), so splitting the batch axis
is communication-free and turns the pod into one wide encoder for bulk
rebuild/backfill.

Two runtime knobs ride `common/options.py` and the OSD's config
observers, mirroring the aggregation knobs:

- `ec_tpu_shard_min_batch`: batches with at least this many stripes
  shard; smaller launches stay single-device (a sharded dispatch pays a
  resharding device_put and a per-mesh compile — pure overhead for the
  few-stripe writes the aggregator window already coalesces).
- `ec_tpu_shard_devices`: mesh width; 0 = every visible device, 1
  disables sharding entirely.

Mesh construction is lazy and cached per width: querying jax.devices()
initializes the backend (expensive, and on the axon tunnel historically
hazardous), so nothing here touches jax until the first launch actually
crosses the threshold.
"""

from __future__ import annotations

import threading

from ceph_tpu.common.lockdep import make_lock

# Defaults mirror common/options.py (the option table is the source of
# truth for daemons; library users get the same numbers without a Config).
DEFAULT_MIN_BATCH = 32
DEFAULT_DEVICES = 0  # 0 = all visible

_lock = make_lock("shard_dispatch_policy")
_min_batch = DEFAULT_MIN_BATCH
_devices = DEFAULT_DEVICES
_mesh_cache: dict[int, object] = {}  # resolved width -> Mesh
_visible: int | None = None  # len(jax.devices()), queried once


def configure(min_batch: int | None = None, devices: int | None = None) -> None:
    """Apply live config (the OSD wires its Config + runtime observers
    here, so the ec_tpu_shard_* settings reach the process-wide policy)."""
    global _min_batch, _devices
    with _lock:
        if min_batch is not None:
            _min_batch = int(min_batch)
        if devices is not None:
            _devices = int(devices)


def settings() -> tuple[int, int]:
    """(min_batch, devices) as currently configured."""
    with _lock:
        return _min_batch, _devices


def _visible_devices() -> int:
    """Device count of the default backend, cached once it is KNOWN
    (like matrix_codec._on_tpu: the answer cannot change within one
    process).  A failed query is NOT cached — a transient backend-init
    fault at the first bulk launch must not silently pin the process to
    single-device coding forever; the next launch retries."""
    global _visible
    if _visible is None:
        try:
            import jax

            _visible = len(jax.devices())
        except Exception as e:
            from ceph_tpu.common.log import dout

            dout("ec", 1, f"sharded dispatch: device query failed "
                          f"(single-device coding this launch): {e!r}")
            return 1
    return _visible


def _mesh_for_width(width: int):
    """Stripe-only mesh over the first `width` devices, cached per width.

    lane_parallelism is pinned to 1: the dispatch path shards the BATCH
    axis only (PartitionSpec over `stripe`), keeping per-device chunk
    length — and therefore kernel geometry — identical to the
    single-device launch, so bytes cannot drift with mesh shape."""
    mesh = _mesh_cache.get(width)
    if mesh is None:
        from .mesh import make_mesh

        mesh = make_mesh(width, lane_parallelism=1)
        _mesh_cache[width] = mesh
    return mesh


def shard_mesh(stripes: int):
    """The mesh a `stripes`-wide launch should shard over, or None for
    the single-device path (the byte floor is the caller's
    PACKED_MIN_BYTES gate; this policy is stripe-count-only).

    None when: sharding is disabled (`ec_tpu_shard_devices` = 1), the
    batch is under `ec_tpu_shard_min_batch`, the batch has fewer stripes
    than the mesh has shards (a device with zero real stripes is pure
    padding waste), or the mesh is degenerate (one visible device — the
    single-device fallback the tests pin)."""
    with _lock:
        min_batch, devices = _min_batch, _devices
    if devices == 1 or stripes < min_batch:
        return None
    width = _visible_devices()
    if devices > 0:
        width = min(width, devices)
    if width < 2 or stripes < width:
        return None
    with _lock:
        return _mesh_for_width(width)


def reset_for_tests() -> None:
    """Drop cached meshes and restore default knobs (test isolation)."""
    global _min_batch, _devices, _visible
    with _lock:
        _min_batch = DEFAULT_MIN_BATCH
        _devices = DEFAULT_DEVICES
        _visible = None
        _mesh_cache.clear()
