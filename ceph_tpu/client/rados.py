"""librados-style client API — mirror of src/librados / src/include/rados.

The reference's C++ `librados::Rados` / `IoCtx` surface
(/root/reference/src/include/rados/librados.hpp), async-native: connect,
mon commands, pool-scoped I/O contexts with object read/write/stat/
xattr/remove, all flowing through the Objecter op engine exactly as the
reference's IoCtxImpl does (src/librados/IoCtxImpl.cc → Objecter).
"""

from __future__ import annotations

import json

from ..common.errs import ENOENT
from ..mon.monmap import MonMap
from ..msg.messages import OSDOp
from .objecter import Objecter


class RadosError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


def _check(result: int, what: str) -> None:
    if result < 0:
        raise RadosError(result, what)


class Rados:
    """Cluster handle (librados::Rados)."""

    def __init__(
        self,
        monmap: MonMap,
        name: str = "client.admin",
        secret: bytes | None = None,  # cephx key (rados_conf key equivalent)
        secure: bool = False,
        compress: bool = False,
        stack: str = "posix",  # ms_type (msg/stack.py)
    ):
        self.name = name
        auth = None
        if secret is not None:
            from ..auth.cephx import CephxAuth

            auth = CephxAuth.for_client(name, secret)
        self.objecter = Objecter(
            name, monmap, auth=auth, secure=secure, compress=compress,
            stack=stack,
        )
        self._connected = False

    async def connect(self, timeout: float = 5.0) -> None:
        await self.objecter.start(timeout)
        self._connected = True

    async def shutdown(self) -> None:
        await self.objecter.stop()
        self._connected = False

    async def mon_command(self, cmd: dict, timeout: float = 5.0):
        """JSON command to the mon cluster (rados_mon_command)."""
        return await self.objecter.monc.command(cmd, timeout)

    async def pool_create(
        self, name: str, pool_type: str = "replicated", profile: str = "", **kw
    ) -> None:
        cmd = {"prefix": "osd pool create", "pool": name, "pool_type": pool_type}
        if profile:
            cmd["erasure_code_profile"] = profile
        cmd.update(kw)
        retval, rs, _ = await self.mon_command(cmd)
        _check(retval, rs)

    async def pool_list(self) -> list[str]:
        retval, rs, outbl = await self.mon_command({"prefix": "osd pool ls"})
        _check(retval, rs)
        return json.loads(outbl.decode() or "[]")

    async def selfmanaged_snap_create(self, pool_name: str) -> int:
        """Allocate a self-managed snapshot id (rados_ioctx_
        selfmanaged_snap_create): durable via paxos before first use."""
        retval, rs, outbl = await self.mon_command(
            {"prefix": "osd pool selfmanaged-snap-create", "pool": pool_name}
        )
        _check(retval, rs)
        return int(json.loads(outbl.decode())["snap_id"])

    async def open_ioctx(self, pool_name: str, timeout: float = 5.0) -> "IoCtx":
        """Pool handle (rados_ioctx_create); waits for the pool to appear
        in our map (pool creation is a paxos round away)."""
        import asyncio
        import time

        deadline = time.monotonic() + timeout
        while True:
            pool = self.objecter.osdmap.get_pool(pool_name)
            if pool is not None:
                return IoCtx(self, pool.id)
            if time.monotonic() > deadline:
                raise RadosError(ENOENT, f"pool {pool_name!r} not found")
            await asyncio.sleep(0.05)
            await self.objecter.monc.resubscribe()


class IoCtx:
    """Pool-scoped I/O context (librados::IoCtx).

    Snapshots follow librados' self-managed model: the caller sets a
    SnapContext (`set_snap_context`) that rides every write so the OSD
    clones on first-write-after-snap; reads address a snapshot with the
    `snap=` parameter (rados_ioctx_snap_set_read)."""

    def __init__(self, rados: Rados, pool_id: int):
        self.rados = rados
        self.pool_id = pool_id
        self.snap_seq = 0
        self.snaps: list[int] = []  # descending, newest first

    def set_snap_context(self, snap_seq: int, snaps: list[int]) -> None:
        """rados_ioctx_selfmanaged_snap_set_write_ctx."""
        self.snap_seq = snap_seq
        self.snaps = sorted(snaps, reverse=True)

    async def _op(
        self,
        oid: str,
        ops: list[OSDOp],
        timeout: float = 10.0,
        snap: int = 0,
        snapc: tuple[int, list[int]] | None = None,
    ):
        # A per-call snapc (librados' write_op snapc) overrides the handle's
        # ambient context — concurrent writers on one shared IoCtx must not
        # race each other's SnapContext.
        seq, snaps = snapc if snapc is not None else (self.snap_seq, self.snaps)
        return await self.rados.objecter.op_submit(
            self.pool_id,
            oid,
            ops,
            timeout=timeout,
            snap_seq=seq,
            snaps=snaps,
            snap_id=snap,
        )

    # -- writes ---------------------------------------------------------------

    async def write(self, oid: str, data: bytes, off: int = 0, snapc=None) -> None:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.WRITE, off=off, data=bytes(data))], snapc=snapc
        )
        _check(rep.result, f"write {oid}")

    async def write_full(self, oid: str, data: bytes, snapc=None) -> None:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.WRITEFULL, data=bytes(data))], snapc=snapc
        )
        _check(rep.result, f"write_full {oid}")

    async def append(self, oid: str, data: bytes, snapc=None) -> None:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.APPEND, data=bytes(data))], snapc=snapc
        )
        _check(rep.result, f"append {oid}")

    async def zero(self, oid: str, off: int, length: int, snapc=None) -> None:
        """rados_write zero extent (CEPH_OSD_OP_ZERO): reads as zeros."""
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.ZERO, off=off, len=length)], snapc=snapc
        )
        _check(rep.result, f"zero {oid}")

    async def writesame(
        self, oid: str, data: bytes, off: int, length: int, snapc=None
    ) -> None:
        """rados_writesame: tile `data` across [off, off+length)."""
        rep = await self._op(
            oid,
            [OSDOp(op=OSDOp.WRITESAME, off=off, len=length, data=bytes(data))],
            snapc=snapc,
        )
        _check(rep.result, f"writesame {oid}")

    async def truncate(self, oid: str, size: int, snapc=None) -> None:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.TRUNCATE, off=size)], snapc=snapc
        )
        _check(rep.result, f"truncate {oid}")

    async def remove(self, oid: str, snapc=None) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.DELETE)], snapc=snapc)
        _check(rep.result, f"remove {oid}")

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.SETXATTR, name=name, data=bytes(value))]
        )
        _check(rep.result, f"setxattr {oid}:{name}")

    async def rmxattr(self, oid: str, name: str) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.RMXATTR, name=name)])
        _check(rep.result, f"rmxattr {oid}:{name}")

    CMPXATTR_OPS = {"eq": 1, "ne": 2, "gt": 3, "gte": 4, "lt": 5, "lte": 6}

    def cmpxattr_op(self, name: str, value: bytes, op: str = "eq") -> OSDOp:
        """Build a CMPXATTR guard sub-op for a compound `operate` call:
        the transaction aborts with -ECANCELED unless the xattr compares
        true (rados_cmpxattr / ObjectOperation::cmpxattr)."""
        return OSDOp(
            op=OSDOp.CMPXATTR, name=name, data=bytes(value),
            off=self.CMPXATTR_OPS[op],
        )

    async def cmpxattr(
        self, oid: str, name: str, value: bytes, op: str = "eq"
    ) -> None:
        rep = await self._op(oid, [self.cmpxattr_op(name, value, op)])
        _check(rep.result, f"cmpxattr {oid}:{name}")

    async def operate(self, oid: str, ops: list[OSDOp], snapc=None):
        """Compound object operation, applied ATOMICALLY in order — the
        ObjectWriteOperation/ObjectReadOperation surface.  Returns the
        reply's per-op outdata list; raises on a nonzero result (a failed
        guard aborts the whole compound with -ECANCELED)."""
        rep = await self._op(oid, ops, snapc=snapc)
        _check(rep.result, f"operate {oid}")
        return list(rep.outdata)

    # -- omap (rados_omap_* / ObjectOperation omap ops; replicated pools
    # only — EC pools answer -EOPNOTSUPP exactly like the reference) -----------

    async def omap_set(self, oid: str, kv: dict[str, bytes]) -> None:
        from ..common.encoding import encode_kv_map

        rep = await self._op(
            oid, [OSDOp(op=OSDOp.OMAPSETVALS, data=encode_kv_map(kv))]
        )
        _check(rep.result, f"omap_set {oid}")

    async def omap_get_vals(self, oid: str) -> dict[str, bytes]:
        from ..common.encoding import decode_kv_map

        rep = await self._op(oid, [OSDOp(op=OSDOp.OMAPGETVALS)])
        _check(rep.result, f"omap_get_vals {oid}")
        return decode_kv_map(rep.outdata[0])

    async def omap_get_keys(self, oid: str) -> list[str]:
        from ..common.encoding import decode_str_list

        rep = await self._op(oid, [OSDOp(op=OSDOp.OMAPGETKEYS)])
        _check(rep.result, f"omap_get_keys {oid}")
        return decode_str_list(rep.outdata[0])

    async def omap_rm_keys(self, oid: str, keys: list[str]) -> None:
        from ..common.encoding import encode_str_list

        rep = await self._op(
            oid, [OSDOp(op=OSDOp.OMAPRMKEYS, data=encode_str_list(keys))]
        )
        _check(rep.result, f"omap_rm_keys {oid}")

    async def omap_clear(self, oid: str) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.OMAPCLEAR)])
        _check(rep.result, f"omap_clear {oid}")

    # -- snapshots -------------------------------------------------------------

    async def rollback(self, oid: str, snap_id: int, snapc=None) -> None:
        """rados_ioctx_selfmanaged_snap_rollback: head := state at snap.
        Rollback is a write: the snapc clones the pre-rollback head for
        any newer snapshot first."""
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.ROLLBACK, off=snap_id)], snapc=snapc
        )
        _check(rep.result, f"rollback {oid}@{snap_id}")

    async def list_snaps(self, oid: str) -> dict:
        """The object's SnapSet ({'seq', 'clones'}; rados listsnaps)."""
        rep = await self._op(oid, [OSDOp(op=OSDOp.LIST_SNAPS)])
        _check(rep.result, f"list_snaps {oid}")
        return json.loads(rep.outdata[0].decode())

    async def snap_trim(self, oid: str, snap_id: int) -> None:
        """Remove one snap from the object, deleting its clone when no
        snap references it (the snap-trimmer's per-object step)."""
        rep = await self._op(oid, [OSDOp(op=OSDOp.DELETE)], snap=snap_id)
        _check(rep.result, f"snap_trim {oid}@{snap_id}")

    # -- copy-from -------------------------------------------------------------

    async def copy_from(
        self, oid: str, src_oid: str, src_snap: int = 0, snapc=None
    ) -> None:
        """Server-side object copy (rados_copy_from / CEPH_OSD_OP_COPY_FROM):
        bytes move OSD->OSD, never through this client.  A write-class op:
        the snap context rides along so the destination's pre-copy head
        clones for new snapshots like any other mutation."""
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.COPY_FROM, name=src_oid, off=src_snap)],
            snapc=snapc,
        )
        _check(rep.result, f"copy_from {src_oid} -> {oid}")

    # -- object classes --------------------------------------------------------

    async def exec(self, oid: str, cls: str, method: str, data: bytes = b"") -> bytes:
        """Run an object-class method server-side (rados_exec /
        CEPH_OSD_OP_CALL): returns the method's output bytes; negative
        method results raise."""
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.CALL, name=f"{cls}.{method}", data=bytes(data))]
        )
        _check(rep.result, f"exec {cls}.{method} on {oid}")
        return rep.outdata[0]

    # -- cache tiering ---------------------------------------------------------

    async def cache_flush(self, oid: str) -> None:
        """Write a dirty cache-tier object back to its base pool
        (rados cache-flush / CEPH_OSD_OP_CACHE_FLUSH)."""
        rep = await self._op(oid, [OSDOp(op=OSDOp.CACHE_FLUSH)])
        _check(rep.result, f"cache_flush {oid}")

    async def cache_evict(self, oid: str) -> None:
        """Drop a clean object from the cache tier (rados cache-evict);
        -EBUSY while dirty."""
        rep = await self._op(oid, [OSDOp(op=OSDOp.CACHE_EVICT)])
        _check(rep.result, f"cache_evict {oid}")

    # -- watch / notify --------------------------------------------------------

    async def watch(self, oid: str, callback) -> int:
        """Register a watch (rados_watch2): `callback(notify_id, payload)`
        runs on every notify; its return bytes (if any) ride the ack back
        to the notifier.  Returns the watch cookie."""
        obj = self.rados.objecter
        obj._next_cookie += 1  # process-wide: no collisions across handles
        cookie = obj._next_cookie
        obj._watches[(self.pool_id, oid, cookie)] = callback
        rep = await self._op(oid, [OSDOp(op=OSDOp.WATCH, off=cookie, len=1)])
        if rep.result < 0:
            obj._watches.pop((self.pool_id, oid, cookie), None)
        _check(rep.result, f"watch {oid}")
        return cookie

    async def unwatch(self, oid: str, cookie: int) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.WATCH, off=cookie, len=0)])
        self.rados.objecter._watches.pop((self.pool_id, oid, cookie), None)
        _check(rep.result, f"unwatch {oid}")

    async def list_watchers(self, oid: str) -> list[dict]:
        """rados listwatchers: [{watcher, cookie}] on the object's head."""
        import json as _json

        rep = await self._op(oid, [OSDOp(op=OSDOp.LIST_WATCHERS)])
        _check(rep.result, f"list_watchers {oid}")
        return _json.loads(rep.outdata[0].decode() or "[]")

    async def notify(
        self, oid: str, payload: bytes = b"", timeout_ms: int = 3000
    ) -> dict:
        """rados_notify2: returns {'acks': {cookie: reply-bytes-hex},
        'timeouts': [cookies that never acked]}."""
        rep = await self._op(
            oid,
            [OSDOp(op=OSDOp.NOTIFY, off=timeout_ms, data=bytes(payload))],
            timeout=max(10.0, timeout_ms / 1000 + 5),
        )
        _check(rep.result, f"notify {oid}")
        return json.loads(rep.outdata[0].decode())

    # -- reads ----------------------------------------------------------------

    async def read(
        self, oid: str, length: int = 0, off: int = 0, snap: int = 0
    ) -> bytes:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.READ, off=off, len=length)], snap=snap
        )
        _check(rep.result, f"read {oid}")
        return rep.outdata[0] if rep.outdata else b""

    async def stat(self, oid: str, snap: int = 0) -> int:
        """Object size (rados_stat)."""
        rep = await self._op(oid, [OSDOp(op=OSDOp.STAT)], snap=snap)
        _check(rep.result, f"stat {oid}")
        return int.from_bytes(rep.outdata[0], "little")

    async def getxattr(self, oid: str, name: str) -> bytes:
        rep = await self._op(oid, [OSDOp(op=OSDOp.GETXATTR, name=name)])
        _check(rep.result, f"getxattr {oid}:{name}")
        return rep.outdata[0]

    async def list_objects(self) -> list[str]:
        """Pool-wide object enumeration (rados ls): PGLS against every
        PG's primary, in parallel (Objecter pg-targeted NLIST ops)."""
        import asyncio

        pool = self.rados.objecter.osdmap.get_pool(self.pool_id)
        replies = await asyncio.gather(
            *(
                self.rados.objecter.op_submit(
                    self.pool_id, "", [OSDOp(op=OSDOp.PGLS)], ps=ps
                )
                for ps in range(pool.pg_num)
            )
        )
        out: set[str] = set()
        for ps, rep in enumerate(replies):
            _check(rep.result, f"pgls {self.pool_id}.{ps}")
            out.update(json.loads(rep.outdata[0].decode()))
        return sorted(out)
