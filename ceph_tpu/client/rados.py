"""librados-style client API — mirror of src/librados / src/include/rados.

The reference's C++ `librados::Rados` / `IoCtx` surface
(/root/reference/src/include/rados/librados.hpp), async-native: connect,
mon commands, pool-scoped I/O contexts with object read/write/stat/
xattr/remove, all flowing through the Objecter op engine exactly as the
reference's IoCtxImpl does (src/librados/IoCtxImpl.cc → Objecter).
"""

from __future__ import annotations

import json

from ..common.errs import ENOENT
from ..mon.monmap import MonMap
from ..msg.messages import OSDOp
from .objecter import Objecter


class RadosError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


def _check(result: int, what: str) -> None:
    if result < 0:
        raise RadosError(result, what)


class Rados:
    """Cluster handle (librados::Rados)."""

    def __init__(self, monmap: MonMap, name: str = "client.admin"):
        self.name = name
        self.objecter = Objecter(name, monmap)
        self._connected = False

    async def connect(self, timeout: float = 5.0) -> None:
        await self.objecter.start(timeout)
        self._connected = True

    async def shutdown(self) -> None:
        await self.objecter.stop()
        self._connected = False

    async def mon_command(self, cmd: dict, timeout: float = 5.0):
        """JSON command to the mon cluster (rados_mon_command)."""
        return await self.objecter.monc.command(cmd, timeout)

    async def pool_create(
        self, name: str, pool_type: str = "replicated", profile: str = "", **kw
    ) -> None:
        cmd = {"prefix": "osd pool create", "pool": name, "pool_type": pool_type}
        if profile:
            cmd["erasure_code_profile"] = profile
        cmd.update(kw)
        retval, rs, _ = await self.mon_command(cmd)
        _check(retval, rs)

    async def pool_list(self) -> list[str]:
        retval, rs, outbl = await self.mon_command({"prefix": "osd pool ls"})
        _check(retval, rs)
        return json.loads(outbl.decode() or "[]")

    async def open_ioctx(self, pool_name: str, timeout: float = 5.0) -> "IoCtx":
        """Pool handle (rados_ioctx_create); waits for the pool to appear
        in our map (pool creation is a paxos round away)."""
        import asyncio
        import time

        deadline = time.monotonic() + timeout
        while True:
            pool = self.objecter.osdmap.get_pool(pool_name)
            if pool is not None:
                return IoCtx(self, pool.id)
            if time.monotonic() > deadline:
                raise RadosError(ENOENT, f"pool {pool_name!r} not found")
            await asyncio.sleep(0.05)
            await self.objecter.monc.resubscribe()


class IoCtx:
    """Pool-scoped I/O context (librados::IoCtx)."""

    def __init__(self, rados: Rados, pool_id: int):
        self.rados = rados
        self.pool_id = pool_id

    async def _op(self, oid: str, ops: list[OSDOp], timeout: float = 10.0):
        return await self.rados.objecter.op_submit(
            self.pool_id, oid, ops, timeout=timeout
        )

    # -- writes ---------------------------------------------------------------

    async def write(self, oid: str, data: bytes, off: int = 0) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.WRITE, off=off, data=bytes(data))])
        _check(rep.result, f"write {oid}")

    async def write_full(self, oid: str, data: bytes) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.WRITEFULL, data=bytes(data))])
        _check(rep.result, f"write_full {oid}")

    async def append(self, oid: str, data: bytes) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.APPEND, data=bytes(data))])
        _check(rep.result, f"append {oid}")

    async def truncate(self, oid: str, size: int) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.TRUNCATE, off=size)])
        _check(rep.result, f"truncate {oid}")

    async def remove(self, oid: str) -> None:
        rep = await self._op(oid, [OSDOp(op=OSDOp.DELETE)])
        _check(rep.result, f"remove {oid}")

    async def setxattr(self, oid: str, name: str, value: bytes) -> None:
        rep = await self._op(
            oid, [OSDOp(op=OSDOp.SETXATTR, name=name, data=bytes(value))]
        )
        _check(rep.result, f"setxattr {oid}:{name}")

    # -- reads ----------------------------------------------------------------

    async def read(self, oid: str, length: int = 0, off: int = 0) -> bytes:
        rep = await self._op(oid, [OSDOp(op=OSDOp.READ, off=off, len=length)])
        _check(rep.result, f"read {oid}")
        return rep.outdata[0] if rep.outdata else b""

    async def stat(self, oid: str) -> int:
        """Object size (rados_stat)."""
        rep = await self._op(oid, [OSDOp(op=OSDOp.STAT)])
        _check(rep.result, f"stat {oid}")
        return int.from_bytes(rep.outdata[0], "little")

    async def getxattr(self, oid: str, name: str) -> bytes:
        rep = await self._op(oid, [OSDOp(op=OSDOp.GETXATTR, name=name)])
        _check(rep.result, f"getxattr {oid}:{name}")
        return rep.outdata[0]

    async def list_objects(self) -> list[str]:
        """Pool-wide object enumeration (rados ls): PGLS against every
        PG's primary, in parallel (Objecter pg-targeted NLIST ops)."""
        import asyncio

        pool = self.rados.objecter.osdmap.get_pool(self.pool_id)
        replies = await asyncio.gather(
            *(
                self.rados.objecter.op_submit(
                    self.pool_id, "", [OSDOp(op=OSDOp.PGLS)], ps=ps
                )
                for ps in range(pool.pg_num)
            )
        )
        out: set[str] = set()
        for ps, rep in enumerate(replies):
            _check(rep.result, f"pgls {self.pool_id}.{ps}")
            out.update(json.loads(rep.outdata[0].decode()))
        return sorted(out)
