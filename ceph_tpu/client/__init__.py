"""Client I/O library — librados + Objecter analogs (SURVEY.md §2.7)."""

from .objecter import Objecter
from .rados import IoCtx, Rados, RadosError

__all__ = ["Objecter", "Rados", "IoCtx", "RadosError"]
