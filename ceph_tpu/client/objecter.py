"""Objecter — the client op engine, mirror of src/osdc/Objecter.{h,cc}.

Reference call stack (SURVEY.md §3.1):

- `op_submit` (/root/reference/src/osdc/Objecter.cc:2268) registers the
  op, computes its target, and sends.
- `_calc_target` (:2775): object name → PG (OSDMap::object_locator_to_pg)
  → acting primary via CRUSH; recomputed whenever a new osdmap arrives,
  and ops whose target changed are **resent** (handle_osd_map →
  _scan_requests).
- Replies arrive as MOSDOpReply (`handle_osd_op_reply`, :989) and
  complete the registered op by tid.

This client keeps that loop: an op stays registered until a final reply;
map updates (via the MonClient osdmap subscription) wake every pending op
to re-target and resend.  A primary that is not yet peered answers
-EAGAIN with its epoch — the op waits for a newer map (or a short delay)
and resends, which is the same convergence the reference gets from
requeueing + map subscriptions.
"""

from __future__ import annotations

import asyncio
import time

from ..common import tracer as tracer_mod
from ..common.errs import EAGAIN, ENOENT, ETIMEDOUT
from ..common.log import dout
from ..mon.client import MonClient
from ..mon.monmap import MonMap
from ..msg.messages import (
    MOSDMap,
    MOSDOp,
    MOSDOpReply,
    MWatchNotify,
    OSDOp,
    PgId,
    ReqId,
)
from ..msg.messenger import Connection, Dispatcher, Messenger
from ..osd.osdmap import PG_NONE, OSDMap, advance_map


class Objecter(Dispatcher):
    def __init__(
        self,
        name: str,
        monmap: MonMap,
        auth=None,
        secure: bool = False,
        compress: bool = False,
        stack: str = "posix",
    ):
        self.name = name
        # Per-INSTANCE identity for osd_reqid_t: the reference's clients
        # carry a mon-assigned global_id in entity_name_t, so two
        # processes (or sequential runs) named "client.foo" never share
        # reqids.  Without the nonce, a second process reusing the name
        # restarts tids at 1 and the PG's dup detection would serve it
        # the FIRST process's remembered replies instead of applying.
        import random
        import secrets

        self.reqid_name = f"{name}.{secrets.token_hex(4)}"
        # resend pacing: per-instance rng so many clients retrying through
        # the same map churn spread out instead of thundering in lockstep
        self._backoff_rng = random.Random(secrets.randbits(32))
        from ..common.perf_counters import PerfCountersBuilder

        b = PerfCountersBuilder(name)
        for c in ("op", "op_resend", "op_reply", "op_timeout"):
            b.add_u64_counter(c)
        self.perf = b.create_perf_counters()
        self.msgr = Messenger(
            name, auth=auth, secure=secure, compress=compress, stack=stack
        )
        # client end of the op trace (Objecter::op_submit's osd_trace root):
        # disabled by default; bench/diag flips .enabled and every op's
        # context rides the MOSDOp envelope so the OSD-side spans join it
        self.tracer = tracer_mod.Tracer(service=name, enabled=False)
        self.msgr.tracer = self.tracer
        self.monc = MonClient(name, monmap, msgr=self.msgr)
        self.msgr.add_dispatcher_head(self)
        self.osdmap = OSDMap()
        self._tid = 0
        self._replies: dict[int, asyncio.Future] = {}
        self._map_changed = asyncio.Event()
        # (pool, oid, cookie) -> callback(notify_id, payload) -> optional
        # reply bytes; pushes arrive on the session the WATCH op registered
        # on (Objecter::handle_watch_notify).  Cookies are allocated
        # process-wide so handles can never collide.
        self._watches: dict[tuple[int, str, int], object] = {}
        self._next_cookie = 0

    # -- lifecycle -------------------------------------------------------------

    async def start(self, timeout: float = 5.0) -> None:
        self.monc.on_osdmap = self._on_osdmap
        await self.monc.subscribe("osdmap")
        deadline = time.monotonic() + timeout
        while self.osdmap.epoch == 0:
            if time.monotonic() > deadline:
                raise TimeoutError("no osdmap from mons")
            await asyncio.sleep(0.02)
            # subscriptions can race mon elections; renew until a map lands
            await self.monc.resubscribe()

    async def stop(self) -> None:
        await self.msgr.shutdown()

    def _on_osdmap(self, msg: MOSDMap) -> None:
        """handle_osd_map: advance, then wake pending ops to re-target
        (_scan_requests analog — ops re-send themselves)."""
        self.osdmap = advance_map(self.osdmap, msg)
        self._map_changed.set()
        self._map_changed = asyncio.Event()

    # -- dispatch --------------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MOSDOpReply):
            fut = self._replies.pop(msg.reqid.tid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, MWatchNotify) and not msg.is_ack:
            cb = self._watches.get((msg.pgid.pool, msg.oid, msg.cookie))
            ack_payload = b""
            if cb is not None:
                try:
                    ack_payload = cb(msg.notify_id, msg.payload) or b""
                except Exception as e:  # a watcher bug must not kill dispatch
                    dout("objecter", 1, f"{self.name}: watch cb raised {e!r}")
            ack = MWatchNotify(
                oid=msg.oid,
                pgid=msg.pgid,
                notify_id=msg.notify_id,
                cookie=msg.cookie,
                payload=bytes(ack_payload),
                is_ack=1,
                # the instance identity the watch REGISTERED under
                # (reqid.client): the PG's pending-ack set is keyed on it
                watcher=self.reqid_name,
            )

            async def _send_ack() -> None:
                try:
                    await conn.send_message(ack)
                except ConnectionError:
                    pass

            asyncio.get_event_loop().create_task(_send_ack())
            return True
        return False

    # -- targeting -------------------------------------------------------------

    def _effective_pool(self, pool_id: int) -> int:
        """Cache-tier overlay redirect (Objecter.cc _calc_target honoring
        pg_pool_t.read_tier): ops targeting a base pool with an overlay go
        to the cache pool; the cache PG promotes/flushes against the base
        (PrimaryLogPG promote_object / agent).  Re-evaluated every resend,
        so adding/removing an overlay retargets in-flight retries."""
        pool = self.osdmap.pools.get(pool_id)
        if pool is not None and pool.read_tier >= 0 and pool.read_tier in self.osdmap.pools:
            return pool.read_tier
        return pool_id

    def _calc_target(self, pool_id: int, oid: str) -> tuple[PgId, int]:
        """_calc_target (Objecter.cc:2775): (pgid, acting_primary)."""
        pool_id = self._effective_pool(pool_id)
        _pool, ps = self.osdmap.object_to_pg(pool_id, oid)
        _up, _upp, _acting, primary = self.osdmap.pg_to_up_acting_osds(pool_id, ps)
        return PgId(pool_id, ps, -1), primary

    # -- op submission ---------------------------------------------------------

    async def op_submit(
        self,
        pool_id: int,
        oid: str,
        ops: list[OSDOp],
        timeout: float = 10.0,
        ps: int | None = None,
        snap_seq: int = 0,
        snaps: list[int] | None = None,
        snap_id: int = 0,
    ) -> MOSDOpReply:
        """op_submit (Objecter.cc:2268): send + resend until a final
        reply.  Raises TimeoutError past `timeout`.  `ps` targets a
        specific PG instead of hashing `oid` (pg ops like PGLS)."""
        self._tid += 1
        reqid = ReqId(client=self.reqid_name, tid=self._tid)
        # trace root: ONE span per client op; every (re)send injects its
        # context into the MOSDOp envelope, so the messenger/OSD/EC/codec
        # spans downstream all share this trace id
        span = self.tracer.start_span("client:op")
        span.keyval("oid", oid)
        span.keyval("reqid", lambda: reqid.key())
        try:
            return await self._op_submit(
                pool_id, oid, ops, timeout, ps, snap_seq, snaps, snap_id,
                reqid, span,
            )
        except TimeoutError:
            # tail-based always-keep (ISSUE 10): a timed-out op keeps
            # its trace even when head sampling dropped it
            self.tracer.mark_keep(span)
            raise
        finally:
            span.finish()

    def _backoff_delay(self, attempt: int, base: float = 0.05,
                       cap: float = 1.0) -> float:
        """Bounded exponential backoff with jitter for op resends: many
        clients retrying through the same osdmap churn must NOT
        synchronize into resend storms, so each retry waits
        base * 2^attempt (capped at ~1 s) scaled by a uniform [0.5, 1.0)
        jitter — the classic decorrelated-retry shape."""
        return min(cap, base * (1 << min(attempt, 16))) * (
            0.5 + self._backoff_rng.random() / 2.0
        )

    def _backoff_or_timeout(self, deadline, attempt, reqid, oid,
                            span) -> float:
        """Resend pacing with fail-fast (ISSUE 17 bugfix): returns the
        backoff delay to sleep before the retry, or raises TimeoutError
        NOW when the op's deadline lands inside that backoff — the old
        `min(remaining, delay)` shape slept the deadline away and only
        noticed at the top of the loop, turning a doomed op's last
        moments into a pointless wait for a retry it could not use."""
        delay = self._backoff_delay(attempt)
        if deadline - time.monotonic() <= delay:
            span.event("deadline exhausted mid-backoff: fail fast")
            self.perf.inc("op_timeout")
            raise TimeoutError(
                f"op {reqid.key()} on {oid} timed out "
                "(deadline inside resend backoff)"
            )
        return delay

    async def _op_submit(
        self, pool_id, oid, ops, timeout, ps, snap_seq, snaps, snap_id,
        reqid, span,
    ) -> MOSDOpReply:
        deadline = time.monotonic() + timeout
        self.perf.inc("op")
        attempt = 0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.perf.inc("op_timeout")
                raise TimeoutError(f"op {reqid.key()} on {oid} timed out")
            if ps is not None:
                _up, _upp, _acting, primary = self.osdmap.pg_to_up_acting_osds(
                    pool_id, ps
                )
                pgid = PgId(pool_id, ps, -1)
            else:
                pgid, primary = self._calc_target(pool_id, oid)
            if primary == PG_NONE:
                # No live primary in this interval: wait for the map to move
                await self._wait_map_change(min(remaining, 0.5))
                continue
            info = self.osdmap.osds.get(primary)
            if info is None or not info.addr:
                await self._wait_map_change(min(remaining, 0.5))
                continue
            msg = MOSDOp(
                reqid=reqid,
                pgid=pgid,
                oid=oid,
                ops=ops,
                epoch=self.osdmap.epoch,
                snap_seq=snap_seq,
                snaps=list(snaps or []),
                snap_id=snap_id,
            )
            tracer_mod.inject(span, msg)
            # end-to-end deadline propagation (ISSUE 17): the op's
            # remaining budget rides the envelope so the OSD can shed
            # already-expired work at admission and EC sub-reads inherit
            # the budget instead of pinning shard sources for a reply
            # nobody is waiting for
            msg.deadline = deadline
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._replies[reqid.tid] = fut
            try:
                span.event(lambda: f"sent to osd.{primary}")
                await self.msgr.send_to(info.addr, msg)
                reply: MOSDOpReply = await asyncio.wait_for(
                    fut, min(remaining, 2.0)
                )
            except (ConnectionError, asyncio.TimeoutError):
                # Peer died or reply lost: re-target after a map change
                # (or a backoff delay) and resend — Objecter's resend
                # loop, paced so client fleets don't retry in lockstep.
                span.event("resend: connection lost or reply timed out")
                self._replies.pop(reqid.tid, None)
                delay = self._backoff_or_timeout(deadline, attempt, reqid,
                                                 oid, span)
                self.perf.inc("op_resend")
                await self._wait_map_change(delay)
                attempt += 1
                continue
            if reply.result == -EAGAIN:
                # Not primary / not yet active: refresh + retry.
                span.event("resend: target not active (-EAGAIN)")
                delay = self._backoff_or_timeout(deadline, attempt, reqid,
                                                 oid, span)
                self.perf.inc("op_resend")
                await self._wait_map_change(delay)
                attempt += 1
                continue
            if reply.result == -ETIMEDOUT:
                # the OSD shed this op at admission (ISSUE 17): its
                # deadline expired in flight/queue, so it was never
                # executed — surface the same TimeoutError a local
                # expiry raises instead of handing back a corpse
                span.event("osd shed op at admission (-ETIMEDOUT)")
                self.perf.inc("op_timeout")
                raise TimeoutError(
                    f"op {reqid.key()} on {oid} timed out "
                    "(shed at osd admission)"
                )
            span.event("reply received")
            self.perf.inc("op_reply")
            return reply

    async def _wait_map_change(self, timeout: float) -> None:
        ev = self._map_changed
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            pass
        # nudge subscriptions in case our mon connection reset
        try:
            await self.monc.resubscribe()
        except ConnectionError:
            pass
