"""Compressor plugin family — mirror of src/compressor.

The reference's third dlopen plugin family beside erasure-code and the
object classes: `Compressor::create(type)` resolves a named algorithm
plugin (zlib/snappy/lz4/zstd/brotli) used by BlueStore blob compression
and msgr2 on-wire compression.  Same shape here: a registry of named
compressors (zlib and zstd from the environment, plus passthrough
"none"), consumed by the BlueStore block path.  The on-wire session
(msg/crypto.py) deliberately keeps its own zlib with a bounded inflate:
a deflate bomb from a hostile peer must not OOM the daemon, a guard the
generic interface doesn't carry.
"""

from .registry import Compressor, CompressorRegistry, get_compressor

__all__ = ["Compressor", "CompressorRegistry", "get_compressor"]
