"""Compressor registry (src/compressor/Compressor.{h,cc}).

`Compressor::create(cct, alg)` analog: get_compressor(name) returns a
cached instance implementing compress/decompress over bytes.  Unknown
names raise (the reference returns a null CompressorRef and callers
error out) — no silent fallback to a different algorithm, since both
sides of a wire or a disk format must agree.
"""

from __future__ import annotations

import zlib


class Compressor:
    """One algorithm (CompressionPlugin instance)."""

    name = "none"

    def compress(self, data: bytes) -> bytes:
        return bytes(data)

    def decompress(self, data: bytes) -> bytes:
        return bytes(data)


class ZlibCompressor(Compressor):
    name = "zlib"

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return zlib.decompress(data)


class ZstdCompressor(Compressor):
    name = "zstd"

    def __init__(self):
        import zstandard

        self._c = zstandard.ZstdCompressor()
        self._d = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def decompress(self, data: bytes) -> bytes:
        return self._d.decompress(data)


class CompressorRegistry:
    """Named get-or-create cache (Compressor::create's static registry)."""

    _PLUGINS = {
        "none": Compressor,
        "zlib": ZlibCompressor,
        "zstd": ZstdCompressor,
    }

    def __init__(self):
        self._instances: dict[str, Compressor] = {}

    def get(self, name: str) -> Compressor:
        inst = self._instances.get(name)
        if inst is not None:
            return inst
        cls = self._PLUGINS.get(name)
        if cls is None and name == "device":
            # the device plugin self-registers on import; loaded lazily
            # so the registry stays importable without jax on the path
            from . import device  # noqa: F401

            cls = self._PLUGINS.get(name)
        if cls is None:
            raise ValueError(
                f"unknown compressor {name!r} (have {sorted(self._PLUGINS)})"
            )
        inst = self._instances[name] = cls()
        return inst


_REGISTRY = CompressorRegistry()


def get_compressor(name: str) -> Compressor:
    return _REGISTRY.get(name)
