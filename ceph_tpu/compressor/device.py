"""Device compressor plugin — the second offload-runtime rider (ISSUE 20).

A registry plugin (`bluestore_compression_algorithm = device`) whose
transform is chosen for the device, not for entropy coding: a byte-plane
transpose (stride 64 — each plane gathers byte p of every 64-byte row,
so columnar/record-structured block images concentrate their zero bytes
into whole planes) followed by zero-run elision at 64-byte cell
granularity over the transposed stream.  Both steps are pure data
movement + an any-nonzero reduce, so the batched form runs as ONE device
launch per aggregation window through the shared offload runtime
(`CompressAggregator`, background lane), and the host fallback computes
the *identical* stored form in numpy — byte-identity through the whole
fault/DEGRADED matrix is structural, not probabilistic.

Stored blob format (self-framing, verified on decompress):

    b"TZD1" | <u32 LE orig_len> | cell bitmap (LSB-first) | nonzero cells

BlueStore's required-ratio gate is unchanged: a block image is stored
in this form only when the blob beats
``bluestore_compression_required_ratio`` — high-entropy blocks fail the
ratio and land raw, exactly like zlib/zstd.
"""

from __future__ import annotations

import struct

import numpy as np

from .registry import Compressor

MAGIC = b"TZD1"
TR = 64    # transpose stride: plane p = byte p of each TR-byte row
CELL = 64  # zero-elision granularity over the transposed stream

# Below this many total bytes a batch skips the offload runtime (host
# transform directly): dispatch + window latency beats the win.
COMPRESS_OFFLOAD_MIN_BYTES = 32 * 1024


def _padded_len(n: int) -> int:
    return -(-max(n, 1) // TR) * TR


def transform_rows(rows: np.ndarray) -> np.ndarray:
    """The host-oracle device transform: (S, Lp) uint8 (Lp % 64 == 0)
    -> (S, Lp + Lp//CELL) uint8 — transposed bytes followed by the 0/1
    nonzero-cell flags.  The device kernel computes the same array."""
    S, Lp = rows.shape
    t = rows.reshape(S, Lp // TR, TR).transpose(0, 2, 1).reshape(S, Lp)
    flags = t.reshape(S, Lp // CELL, CELL).any(axis=2).astype(np.uint8)
    return np.concatenate([t, flags], axis=1)


def transform_rows_device(rows: np.ndarray):
    """One batched device launch of the transform; returns a device
    array shaped like `transform_rows` (np.asarray forces it)."""
    import jax.numpy as jnp

    from ceph_tpu.ops.dispatch import record_launch

    S, Lp = rows.shape
    d = jnp.asarray(rows)
    t = d.reshape(S, Lp // TR, TR).transpose(0, 2, 1).reshape(S, Lp)
    flags = (
        t.reshape(S, Lp // CELL, CELL).max(axis=2) > 0
    ).astype(jnp.uint8)
    record_launch(S, rows.nbytes)
    return jnp.concatenate([t, flags], axis=1)


def assemble_blob(transformed: np.ndarray, orig_len: int) -> bytes:
    """(Lp + Lp//CELL,) transform output row -> the stored blob."""
    Lp = _padded_len(orig_len)
    ncells = Lp // CELL
    t = transformed[:Lp]
    mask = transformed[Lp : Lp + ncells].astype(bool)
    bitmap = np.packbits(mask, bitorder="little").tobytes()
    payload = np.ascontiguousarray(t).reshape(ncells, CELL)[mask].tobytes()
    return MAGIC + struct.pack("<I", orig_len) + bitmap + payload


class DeviceCompressor(Compressor):
    name = "device"

    def compress(self, data: bytes) -> bytes:
        data = bytes(data)
        Lp = _padded_len(len(data))
        row = np.zeros((1, Lp), dtype=np.uint8)
        row[0, : len(data)] = np.frombuffer(data, dtype=np.uint8)
        return assemble_blob(transform_rows(row)[0], len(data))

    def decompress(self, data: bytes) -> bytes:
        blob = bytes(data)
        if blob[:4] != MAGIC or len(blob) < 8:
            raise ValueError("not a device-compressor blob")
        (orig_len,) = struct.unpack_from("<I", blob, 4)
        Lp = _padded_len(orig_len)
        ncells = Lp // CELL
        nbitmap = (ncells + 7) // 8
        mask = np.unpackbits(
            np.frombuffer(blob[8 : 8 + nbitmap], dtype=np.uint8),
            bitorder="little",
        )[:ncells].astype(bool)
        payload = np.frombuffer(blob[8 + nbitmap :], dtype=np.uint8)
        if payload.size != int(mask.sum()) * CELL:
            raise ValueError("device-compressor blob truncated")
        cells = np.zeros((ncells, CELL), dtype=np.uint8)
        if payload.size:
            cells[mask] = payload.reshape(-1, CELL)
        # inverse transpose: flat transposed stream -> original order
        out = (
            cells.reshape(Lp)
            .reshape(TR, Lp // TR)
            .transpose()
            .reshape(Lp)
        )
        return out.tobytes()[:orig_len]

    def compress_batch(self, blocks: list[bytes]) -> list[bytes]:
        """Compress many block images with their transforms batched into
        shared offload-runtime launches (same-length groups coalesce
        across concurrent callers through the aggregation window); small
        batches and the fault/DEGRADED matrix take the byte-identical
        host transform."""
        if not blocks:
            return []
        total = sum(len(b) for b in blocks)
        if total < COMPRESS_OFFLOAD_MIN_BYTES:
            return [self.compress(b) for b in blocks]
        agg = default_compress_aggregator()
        by_len: dict[int, list[int]] = {}
        for i, b in enumerate(blocks):
            by_len.setdefault(len(b), []).append(i)
        out: list[bytes] = [b""] * len(blocks)
        tickets = []
        for n, idxs in by_len.items():
            Lp = _padded_len(n)
            rows = np.zeros((len(idxs), Lp), dtype=np.uint8)
            for r, i in enumerate(idxs):
                rows[r, :n] = np.frombuffer(blocks[i], dtype=np.uint8)
            tickets.append((n, idxs, agg.submit_rows(rows)))
        for n, idxs, ticket in tickets:
            transformed = ticket.result()
            for r, i in enumerate(idxs):
                out[i] = assemble_blob(transformed[r], n)
        return out


# registry entry: resolved by get_compressor("device") exactly like the
# zlib/zstd plugins (BlueStore's bluestore_compression_algorithm knob)
from .registry import CompressorRegistry

CompressorRegistry._PLUGINS.setdefault("device", DeviceCompressor)


from ceph_tpu.ops.offload_runtime import (  # noqa: E402
    AggTicket,
    LaunchAggregator,
    _AggGroup,
    register_service,
)


class CompressAggregator(LaunchAggregator):
    """Cross-block / cross-object compressor-transform aggregation:
    same-padded-length block images submitted inside one window ride ONE
    device transpose+elide launch (background lane).  Tickets resolve to
    (stripes, Lp + Lp//CELL) transform rows; `assemble_blob` turns each
    row into the stored form."""

    PERF_NAME = "compress_aggregator"
    WHAT = "compress"
    SCHED_CLASS = "background"
    MEM_POOL = "offload_inflight"

    def submit_rows(self, rows: np.ndarray) -> AggTicket:
        """Queue one (S, Lp) uint8 padded block batch (Lp % 64 == 0)."""
        shaped = np.ascontiguousarray(rows, dtype=np.uint8)
        if shaped.ndim != 2 or shaped.shape[1] % TR:
            raise ValueError(f"expected (S, 64k) rows, got {shaped.shape}")
        return self._submit(
            ("#compress", shaped.shape[1]), None, None, shaped[:, None, :]
        )

    def _dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        S = data.shape[0]
        return transform_rows_device(data.reshape(S, -1))

    def _dispatch_host(self, g: _AggGroup, data: np.ndarray) -> np.ndarray:
        return transform_rows(data.reshape(data.shape[0], -1))

    def _out_shape(self, g: _AggGroup, data_shape) -> tuple:
        Lp = data_shape[1] * data_shape[2]
        return (data_shape[0], Lp + Lp // CELL)

    def _donate_ok(self, g: _AggGroup, data_shape) -> bool:
        return False  # output shape differs from input; no buffer reuse


_DEFAULT_COMPRESS_AGGREGATOR: CompressAggregator | None = None


def default_compress_aggregator() -> CompressAggregator:
    """Process-wide compressor aggregator shared by every BlueStore in
    the process (one per OSD harness), so concurrent writers' block
    transforms coalesce exactly like their encodes do."""
    global _DEFAULT_COMPRESS_AGGREGATOR
    if _DEFAULT_COMPRESS_AGGREGATOR is None:
        from ceph_tpu.common.options import OPTIONS

        _DEFAULT_COMPRESS_AGGREGATOR = CompressAggregator(
            window=int(OPTIONS["bluestore_csum_offload_window"].default),
            max_bytes=int(
                OPTIONS["bluestore_csum_offload_max_bytes"].default
            ),
        )
    return _DEFAULT_COMPRESS_AGGREGATOR


register_service(
    "compress", default_compress_aggregator, lane="background",
    oracle="compressor/device.transform_rows",
    doc="batched byte-plane transpose + zero-run elision compressor",
)
