"""MDSMonitor — the PaxosService owning the FSMap (src/mon/MDSMonitor.cc,
src/mds/FSMap.h).

Mirrored behaviors:
- MDS daemons announce themselves with beacons (MMDSBeacon →
  MDSMonitor::prepare_beacon) and pool as STANDBYS until a filesystem
  wants a rank; each `fs new` filesystem gets its own **rank 0** daemon
  assigned from the standby pool (FSMap::promote — the reference's
  multi-filesystem map, one MDSMap per fs inside the FSMap envelope).
- A missed beacon window fails a filesystem's active rank over to a
  standby (`mds_beacon_grace`, MDSMonitor::tick → maybe_replace_gid),
  bumping the map epoch; the promoted standby sees its assignment in
  the next MMDSMap and runs journal replay for THAT filesystem before
  serving.
- The map publishes to "mdsmap" subscribers (clients resolving their
  filesystem's active MDS; standbys learning of promotion) — check_sub.
- Commands: `fs new <name> <meta> <data>`, `fs rm <name>`, `fs status`.

Rank scope per filesystem: one ACTIVE rank (0); multi-rank subtree
partitioning is out of scope in ceph_tpu.mds and therefore here.
"""

from __future__ import annotations

import json
import time

from ..common.log import dout
from ..msg.messages import MMDSBeacon, MMDSMap
from .paxos_service import ProposalQueue

BEACON_GRACE = 6.0  # mds_beacon_grace (scaled down like mgr's)


class FSMap:
    """The multi-filesystem FSMap: per-fs rank-0 holder + a shared
    standby pool (FSMap.h filesystems + standby_daemons)."""

    def __init__(self) -> None:
        self.epoch = 0
        # fs name -> {meta_pool, data_pool, active_name, active_addr}
        self.filesystems: dict[str, dict] = {}
        self.standbys: dict[str, str] = {}  # daemon name -> addr
        # daemon name -> RADOS client instance id (objecter reqid),
        # learned from beacons.  COMMITTED state, not leader-local: the
        # fence on failover needs the failed daemon's client id, and the
        # failed daemon by definition never beacons the new leader —
        # keeping this in the map is what lets a post-election leader
        # still fence it.
        self.clients: dict[str, str] = {}
        # daemon name -> client id WE blocklisted on failover/fs-rm.
        # Committed alongside the mutation that moved the rank, so (a) a
        # post-election leader can still lift the fence when the daemon
        # demotes, and (b) the unfence path never touches blocklist
        # entries an admin added manually (it only lifts ids recorded
        # here).
        self.fenced: dict[str, str] = {}

    # -- queries ---------------------------------------------------------------

    def fs_of_daemon(self, daemon: str) -> str:
        """Filesystem this daemon holds rank 0 of ('' = none)."""
        for name, fs in self.filesystems.items():
            if fs["active_name"] == daemon:
                return name
        return ""

    def actives(self) -> dict[str, str]:
        return {
            name: fs["active_name"]
            for name, fs in self.filesystems.items()
            if fs["active_name"]
        }

    def to_msg(self) -> MMDSMap:
        return MMDSMap(
            epoch=self.epoch,
            fsmap=json.dumps(
                {"filesystems": self.filesystems, "standbys": self.standbys}
            ).encode(),
        )

    def to_blob(self, epoch: int) -> bytes:
        return json.dumps(
            {
                "epoch": epoch,
                "filesystems": self.filesystems,
                "standbys": self.standbys,
                "clients": self.clients,
                "fenced": self.fenced,
            }
        ).encode()

    @staticmethod
    def scratch(m: "FSMap") -> "FSMap":
        s = FSMap()
        s.epoch = m.epoch
        s.filesystems = {k: dict(v) for k, v in m.filesystems.items()}
        s.standbys = dict(m.standbys)
        s.clients = dict(m.clients)
        s.fenced = dict(m.fenced)
        return s

    def status(self) -> dict:
        """`ceph fs status` / `ceph status` fsmap line."""
        return {
            "epoch": self.epoch,
            "filesystems": [
                {
                    "name": name,
                    "metadata_pool": fs["meta_pool"],
                    "data_pool": fs["data_pool"],
                    "rank0": fs["active_name"] or None,
                    "state": "up:active" if fs["active_name"] else "down",
                }
                for name, fs in sorted(self.filesystems.items())
            ],
            "standbys": sorted(self.standbys),
        }


def _eligible(m: FSMap, daemon: str) -> bool:
    """A standby is promotable unless its CURRENT client instance is the
    one we fenced (blocklisted): promoting it would hand a filesystem to
    a client whose every write bounces, with no unfence path (the
    unfence requires a rank-less `standby` beacon).  A replacement
    daemon reusing the name carries a fresh client id, so it stays
    eligible while the zombie's fence stands."""
    fenced = m.fenced.get(daemon, "")
    return not fenced or fenced != m.clients.get(daemon, "")


def _assign_standbys(m: FSMap) -> bool:
    """Give every active-less filesystem an eligible standby
    (deterministic order); True when anything changed (FSMap::promote)."""
    changed = False
    for name in sorted(m.filesystems):
        fs = m.filesystems[name]
        if fs["active_name"]:
            continue
        for daemon in sorted(m.standbys):
            if not _eligible(m, daemon):
                continue
            fs["active_name"] = daemon
            fs["active_addr"] = m.standbys.pop(daemon)
            changed = True
            break
    return changed


class MDSMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.map = FSMap()
        self._last_beacon: dict[str, float] = {}
        # daemon name -> RADOS client instance id, learned from beacons
        # (leader-local, like _last_beacon; repopulated within one beacon
        # interval after an election)
        self._clients: dict[str, str] = {}
        # fences whose blocklist proposal is COMMITTED (leader-local
        # fast path; the committed FSMap `fenced` record is what
        # survives elections) and fences still in flight — a tick firing
        # while a fence is mid-paxos must neither re-fence nor promote
        # ahead of it
        self._fenced: dict[str, str] = {}
        self._fence_inflight: dict[str, str] = {}
        self._props = ProposalQueue(mon, "mds")

    # -- fencing ---------------------------------------------------------------

    def _client_of(self, daemon: str) -> str:
        """The daemon's RADOS client instance id: freshest beacon first,
        then the COMMITTED FSMap record — the latter is what survives a
        mon election, where the failed daemon never beacons the new
        leader ('' for embedded daemons without a client)."""
        return self._clients.get(daemon) or self.map.clients.get(daemon, "")

    def _fence(self, daemon: str, why: str, then=None) -> bool:
        """Blocklist `daemon`'s RADOS client instance via the OSDMonitor
        BEFORE its rank moves (MDSMonitor::fail_mds_gid blocklisting the
        gid's addrs; same pattern as rbd/mirror.py promote(fence=True)).
        A stalled-but-alive old active keeps running its flush loop, and
        without the fence its writes race the promoted standby's journal
        — split-brain metadata corruption.

        Returns True when a fence proposal was queued; `then` (if given)
        runs from the blocklist proposal's commit callback, which is how
        callers guarantee the fence EPOCH commits strictly before the
        promotion epoch (queuing both fire-and-forget would let an
        unrelated in-flight osdmap round reorder them)."""
        client = self._client_of(daemon)
        if not client:
            return False  # embedded daemon: nothing to fence

        def mutate(m) -> str:
            m.blocklist.add(client)
            return f"blocklisting {client}"

        def on_committed(retval: int, _rs: str) -> None:
            self._fence_inflight.pop(daemon, None)
            if retval == 0:
                self._fenced[daemon] = client
                if then is not None:
                    then()
            # non-zero: leadership lost mid-propose — the new leader's
            # tick re-detects the stale beacon and redoes the failover

        self.mon.osdmon._queue(mutate, on_committed)
        self._fence_inflight[daemon] = client
        dout("mon", 1, f"mds {daemon}: fencing client {client} ({why})")
        return True

    def _unfence(self, daemon: str, client: str) -> None:
        """Lift a fence once the daemon has provably demoted (it beacons
        `standby` with the SAME client instance — its active-instance
        flush loop is stopped), so the instance can serve again as a
        standby.  A zombie never demotes and therefore stays fenced."""
        self._fenced.pop(daemon, None)

        def mutate(m) -> str:
            m.blocklist.discard(client)
            return f"un-blocklisting {client}"

        self.mon.osdmon._queue(mutate, None)

        def drop_record(m: FSMap):
            if m.fenced.get(daemon) != client:
                return None
            del m.fenced[daemon]
            # now-eligible again: an active-less filesystem waiting on
            # this standby gets it in the same commit
            _assign_standbys(m)
            return m

        self._queue(drop_record)
        dout("mon", 1, f"mds {daemon}: unfenced client {client} (demoted)")

    def on_election_changed(self) -> None:
        self._props.reset()
        # Re-baseline beacons: a fresh leader judging against 0.0 would
        # instantly fail a healthy active (same as MgrMonitor).
        now = time.monotonic()
        for name in [*self.map.actives().values(), *self.map.standbys]:
            self._last_beacon[name] = now
        # Drop leader-local fence state: a stale _fenced entry on a
        # re-elected leader would skip a NEEDED re-fence (the daemon was
        # unfenced by another leader in between), and an orphaned
        # in-flight entry (its commit callback died with the old
        # leadership) would block that daemon's failover forever.  The
        # committed FSMap `fenced` record is the authority that
        # survives; these are only caches/latches of this leadership.
        self._fenced.clear()
        self._fence_inflight.clear()

    # -- beacons ---------------------------------------------------------------

    def prepare_beacon(self, msg: MMDSBeacon) -> None:
        """Leader-only (MDSMonitor::prepare_beacon)."""
        self._last_beacon[msg.name] = time.monotonic()
        client = getattr(msg, "client", "") or ""
        if client:
            self._clients[msg.name] = client
        if (
            client
            and msg.state == "standby"
            and self.map.fs_of_daemon(msg.name) == ""
            and (
                self._fenced.get(msg.name) == client
                or self.map.fenced.get(msg.name) == client
            )
        ):
            # THIS instance (client id must match — a replacement daemon
            # reusing the name must not lift a live zombie's fence)
            # demoted itself after losing its rank: safe to unfence and
            # let it pool.  The committed `fenced` record covers fences
            # placed by a pre-election leader; blocklist entries an
            # admin added manually are never recorded there and so are
            # never lifted here.
            self._unfence(msg.name, client)

        def mutate(m: FSMap):
            changed = False
            held = m.fs_of_daemon(msg.name)
            if held:
                fs = m.filesystems[held]
                if fs["active_addr"] != msg.addr:
                    fs["active_addr"] = msg.addr
                    changed = True
            elif m.standbys.get(msg.name) != msg.addr:
                m.standbys[msg.name] = msg.addr
                changed = True
            if client and m.clients.get(msg.name) != client:
                # commit the client id: a post-election leader must be
                # able to fence a daemon that will never beacon it
                m.clients[msg.name] = client
                changed = True
            changed |= _assign_standbys(m)
            return m if changed else None

        self._queue(mutate)

    def tick(self) -> None:
        """Fail expired actives over (MDSMonitor::tick →
        maybe_replace_gid; driven by the monitor's periodic tick)."""
        if not self.mon.is_leader():
            return
        now = time.monotonic()
        failed = [
            daemon
            for daemon in self.map.actives().values()
            if now - self._last_beacon.get(daemon, 0.0) > BEACON_GRACE
        ]
        # daemons whose fence proposal is still mid-paxos are skipped
        # outright: their promotion is already chained to that fence's
        # commit callback, and handling them again here would queue a
        # promotion AHEAD of the uncommitted fence
        failed = [d for d in failed if d not in self._fence_inflight]
        if not failed:
            return
        for daemon in failed:
            # re-baseline rather than pop: a tick firing between the
            # fence proposal and its commit must NOT re-detect this
            # daemon and queue the promotion ahead of the fence; if the
            # failover somehow doesn't commit (lost leadership), the
            # stale beacon re-trips one grace period later and retries
            self._last_beacon[daemon] = now
        # client ids we will have blocklisted by the time the promotion
        # commits — recorded in the SAME FSMap mutation, so a
        # post-election leader can still lift the fence when the daemon
        # demotes (and the unfence path never touches admin blocklists)
        fence_clients = {
            d: self._client_of(d) for d in failed if self._client_of(d)
        }

        def mutate(m: FSMap):
            changed = False
            for daemon in failed:
                held = m.fs_of_daemon(daemon)
                if not held:
                    continue  # already replaced
                fs = m.filesystems[held]
                fs["active_name"] = fs["active_addr"] = ""
                changed = True
                dout("mon", 1, f"mds {daemon} failed; fs {held} rank 0 vacated")
            for daemon, client in fence_clients.items():
                if m.fenced.get(daemon) != client:
                    m.fenced[daemon] = client
                    changed = True
            changed |= _assign_standbys(m)
            return m if changed else None

        # fence BEFORE the FSMap mutation promotes a standby, and queue
        # the promotion from the LAST fence's commit callback: the
        # blocklist epoch is committed before the promotion proposal even
        # enters paxos, so by the time the standby replays the journal
        # the zombie's writes already bounce at every OSD that applied
        # the epoch (fire-and-forget queuing could reorder behind an
        # unrelated in-flight osdmap round)
        fences = [
            d for d, client in fence_clients.items()
            if self._fenced.get(d) != client and self.map.fenced.get(d) != client
        ]
        if not fences:
            self._queue(mutate)
            return
        remaining = {"n": len(fences)}

        def after_fence() -> None:
            remaining["n"] -= 1
            if remaining["n"] == 0:
                self._queue(mutate)

        for daemon in fences:
            self._fence(daemon, "beacon timeout failover", then=after_fence)

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        if prefix == "fs new":
            def handler(cmd, reply):
                name = cmd.get("fs_name", "")
                meta, data = cmd.get("metadata", ""), cmd.get("data", "")
                if not name or not meta or not data:
                    reply(-22, "usage: fs new <fs_name> <metadata> <data>")
                    return
                if name in self.map.filesystems:
                    reply(-17, f"filesystem {name!r} already exists")
                    return
                osdmap = self.mon.osdmon.osdmap
                pools = {p.name for p in osdmap.pools.values()}
                for pool in (meta, data):
                    if pool not in pools:
                        reply(-2, f"pool {pool!r} does not exist")
                        return

                def mutate(m: FSMap):
                    if name in m.filesystems:
                        return None
                    m.filesystems[name] = {
                        "meta_pool": meta,
                        "data_pool": data,
                        "active_name": "",
                        "active_addr": "",
                    }
                    _assign_standbys(m)
                    return m

                def on_committed(version: int) -> None:
                    if version < 0 and name not in self.map.filesystems:
                        reply(-17, f"filesystem {name!r} already exists")
                    else:
                        reply(
                            0,
                            f"new fs with metadata pool {meta} and data pool {data}",
                        )

                self._queue(mutate, on_committed)

            handler.mutating = True
            return handler
        if prefix == "fs rm":
            def handler(cmd, reply):
                name = cmd.get("fs_name", "")
                if not name:
                    reply(-22, "usage: fs rm <fs_name>")
                    return
                if name not in self.map.filesystems:
                    # a typo'd name must not remove a real filesystem
                    reply(-2, f"filesystem {name!r} does not exist")
                    return
                # fs rm of a still-beaconing active: fence its RADOS
                # client FIRST (rm commits from the fence's commit
                # callback) — queued flushes must not land in the
                # removed filesystem's pools after the map drops the
                # rank.  The fence lifts when the daemon demotes (its
                # `standby` beacon) and it rejoins the pool cleanly.
                active = self.map.filesystems[name]["active_name"]
                live = active and (
                    time.monotonic() - self._last_beacon.get(active, 0.0)
                    <= BEACON_GRACE
                )
                fence_client = self._client_of(active) if live else ""

                def mutate(m: FSMap):
                    fs = m.filesystems.pop(name, None)
                    if fs is None:
                        return None
                    # its active returns to the standby pool (the daemon
                    # demotes itself when the map stops naming it)
                    if fs["active_name"]:
                        m.standbys[fs["active_name"]] = fs["active_addr"]
                        if fence_client:
                            # committed fence record: survives elections
                            # and scopes the unfence to exactly this id
                            m.fenced[fs["active_name"]] = fence_client
                    _assign_standbys(m)
                    return m

                def queue_rm() -> None:
                    self._queue(
                        mutate, lambda v: reply(0, f"fs {name!r} removed")
                    )

                if fence_client and self._fence(
                    active, "fs rm of live active", then=queue_rm
                ):
                    return
                queue_rm()

            handler.mutating = True
            return handler
        if prefix == "fs status":
            def handler(cmd, reply):
                reply(0, "", json.dumps(self.map.status()).encode())

            return handler
        return None

    # -- paxos -----------------------------------------------------------------

    def _queue(self, mutate, on_committed=None) -> None:
        def make_blob():
            scratch = FSMap.scratch(self.map)
            result = mutate(scratch)
            if result is None:
                return None
            return result.to_blob(self.map.epoch + 1)

        self._props.queue(make_blob, on_committed)

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        m = self.map
        m.epoch = info["epoch"]
        m.filesystems = info["filesystems"]
        m.standbys = dict(info["standbys"])
        m.clients = dict(info.get("clients", {}))
        m.fenced = dict(info.get("fenced", {}))
        dout(
            "mon", 10,
            f"fsmap e{m.epoch}: {sorted(m.actives().items())} "
            f"standbys={sorted(m.standbys)}",
        )
        self.mon.publish_mdsmap()

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        if self.map.epoch == 0 or subs.get("mdsmap", 0) > self.map.epoch:
            return
        subs["mdsmap"] = self.map.epoch + 1
        self.mon.send_to_conn(conn, self.map.to_msg())
