"""MDSMonitor — the PaxosService owning the FSMap (src/mon/MDSMonitor.cc,
src/mds/FSMap.h).

Mirrored behaviors:
- MDS daemons announce themselves with beacons (MMDSBeacon →
  MDSMonitor::prepare_beacon) and pool as STANDBYS until a filesystem
  wants a rank; each `fs new` filesystem gets its own **rank 0** daemon
  assigned from the standby pool (FSMap::promote — the reference's
  multi-filesystem map, one MDSMap per fs inside the FSMap envelope).
- A missed beacon window fails a filesystem's active rank over to a
  standby (`mds_beacon_grace`, MDSMonitor::tick → maybe_replace_gid),
  bumping the map epoch; the promoted standby sees its assignment in
  the next MMDSMap and runs journal replay for THAT filesystem before
  serving.
- The map publishes to "mdsmap" subscribers (clients resolving their
  filesystem's active MDS; standbys learning of promotion) — check_sub.
- Commands: `fs new <name> <meta> <data>`, `fs rm <name>`, `fs status`.

Rank scope per filesystem: one ACTIVE rank (0); multi-rank subtree
partitioning is out of scope in ceph_tpu.mds and therefore here.
"""

from __future__ import annotations

import json
import time

from ..common.log import dout
from ..msg.messages import MMDSBeacon, MMDSMap
from .paxos_service import ProposalQueue

BEACON_GRACE = 6.0  # mds_beacon_grace (scaled down like mgr's)


class FSMap:
    """The multi-filesystem FSMap: per-fs rank-0 holder + a shared
    standby pool (FSMap.h filesystems + standby_daemons)."""

    def __init__(self) -> None:
        self.epoch = 0
        # fs name -> {meta_pool, data_pool, active_name, active_addr}
        self.filesystems: dict[str, dict] = {}
        self.standbys: dict[str, str] = {}  # daemon name -> addr

    # -- queries ---------------------------------------------------------------

    def fs_of_daemon(self, daemon: str) -> str:
        """Filesystem this daemon holds rank 0 of ('' = none)."""
        for name, fs in self.filesystems.items():
            if fs["active_name"] == daemon:
                return name
        return ""

    def actives(self) -> dict[str, str]:
        return {
            name: fs["active_name"]
            for name, fs in self.filesystems.items()
            if fs["active_name"]
        }

    def to_msg(self) -> MMDSMap:
        return MMDSMap(
            epoch=self.epoch,
            fsmap=json.dumps(
                {"filesystems": self.filesystems, "standbys": self.standbys}
            ).encode(),
        )

    def to_blob(self, epoch: int) -> bytes:
        return json.dumps(
            {
                "epoch": epoch,
                "filesystems": self.filesystems,
                "standbys": self.standbys,
            }
        ).encode()

    @staticmethod
    def scratch(m: "FSMap") -> "FSMap":
        s = FSMap()
        s.epoch = m.epoch
        s.filesystems = {k: dict(v) for k, v in m.filesystems.items()}
        s.standbys = dict(m.standbys)
        return s

    def status(self) -> dict:
        """`ceph fs status` / `ceph status` fsmap line."""
        return {
            "epoch": self.epoch,
            "filesystems": [
                {
                    "name": name,
                    "metadata_pool": fs["meta_pool"],
                    "data_pool": fs["data_pool"],
                    "rank0": fs["active_name"] or None,
                    "state": "up:active" if fs["active_name"] else "down",
                }
                for name, fs in sorted(self.filesystems.items())
            ],
            "standbys": sorted(self.standbys),
        }


def _assign_standbys(m: FSMap) -> bool:
    """Give every active-less filesystem a standby (deterministic order);
    True when anything changed (FSMap::promote)."""
    changed = False
    for name in sorted(m.filesystems):
        fs = m.filesystems[name]
        if fs["active_name"] or not m.standbys:
            continue
        daemon = sorted(m.standbys)[0]
        fs["active_name"] = daemon
        fs["active_addr"] = m.standbys.pop(daemon)
        changed = True
    return changed


class MDSMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.map = FSMap()
        self._last_beacon: dict[str, float] = {}
        self._props = ProposalQueue(mon, "mds")

    def on_election_changed(self) -> None:
        self._props.reset()
        # Re-baseline beacons: a fresh leader judging against 0.0 would
        # instantly fail a healthy active (same as MgrMonitor).
        now = time.monotonic()
        for name in [*self.map.actives().values(), *self.map.standbys]:
            self._last_beacon[name] = now

    # -- beacons ---------------------------------------------------------------

    def prepare_beacon(self, msg: MMDSBeacon) -> None:
        """Leader-only (MDSMonitor::prepare_beacon)."""
        self._last_beacon[msg.name] = time.monotonic()

        def mutate(m: FSMap):
            changed = False
            held = m.fs_of_daemon(msg.name)
            if held:
                fs = m.filesystems[held]
                if fs["active_addr"] != msg.addr:
                    fs["active_addr"] = msg.addr
                    changed = True
            elif m.standbys.get(msg.name) != msg.addr:
                m.standbys[msg.name] = msg.addr
                changed = True
            changed |= _assign_standbys(m)
            return m if changed else None

        self._queue(mutate)

    def tick(self) -> None:
        """Fail expired actives over (MDSMonitor::tick →
        maybe_replace_gid; driven by the monitor's periodic tick)."""
        if not self.mon.is_leader():
            return
        now = time.monotonic()
        failed = [
            daemon
            for daemon in self.map.actives().values()
            if now - self._last_beacon.get(daemon, 0.0) > BEACON_GRACE
        ]
        if not failed:
            return
        for daemon in failed:
            self._last_beacon.pop(daemon, None)

        def mutate(m: FSMap):
            changed = False
            for daemon in failed:
                held = m.fs_of_daemon(daemon)
                if not held:
                    continue  # already replaced
                fs = m.filesystems[held]
                fs["active_name"] = fs["active_addr"] = ""
                changed = True
                dout("mon", 1, f"mds {daemon} failed; fs {held} rank 0 vacated")
            changed |= _assign_standbys(m)
            return m if changed else None

        self._queue(mutate)

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        if prefix == "fs new":
            def handler(cmd, reply):
                name = cmd.get("fs_name", "")
                meta, data = cmd.get("metadata", ""), cmd.get("data", "")
                if not name or not meta or not data:
                    reply(-22, "usage: fs new <fs_name> <metadata> <data>")
                    return
                if name in self.map.filesystems:
                    reply(-17, f"filesystem {name!r} already exists")
                    return
                osdmap = self.mon.osdmon.osdmap
                pools = {p.name for p in osdmap.pools.values()}
                for pool in (meta, data):
                    if pool not in pools:
                        reply(-2, f"pool {pool!r} does not exist")
                        return

                def mutate(m: FSMap):
                    if name in m.filesystems:
                        return None
                    m.filesystems[name] = {
                        "meta_pool": meta,
                        "data_pool": data,
                        "active_name": "",
                        "active_addr": "",
                    }
                    _assign_standbys(m)
                    return m

                def on_committed(version: int) -> None:
                    if version < 0 and name not in self.map.filesystems:
                        reply(-17, f"filesystem {name!r} already exists")
                    else:
                        reply(
                            0,
                            f"new fs with metadata pool {meta} and data pool {data}",
                        )

                self._queue(mutate, on_committed)

            handler.mutating = True
            return handler
        if prefix == "fs rm":
            def handler(cmd, reply):
                name = cmd.get("fs_name", "")
                if not name:
                    reply(-22, "usage: fs rm <fs_name>")
                    return
                if name not in self.map.filesystems:
                    # a typo'd name must not remove a real filesystem
                    reply(-2, f"filesystem {name!r} does not exist")
                    return

                def mutate(m: FSMap):
                    fs = m.filesystems.pop(name, None)
                    if fs is None:
                        return None
                    # its active returns to the standby pool (the daemon
                    # demotes itself when the map stops naming it)
                    if fs["active_name"]:
                        m.standbys[fs["active_name"]] = fs["active_addr"]
                    _assign_standbys(m)
                    return m

                self._queue(mutate, lambda v: reply(0, f"fs {name!r} removed"))

            handler.mutating = True
            return handler
        if prefix == "fs status":
            def handler(cmd, reply):
                reply(0, "", json.dumps(self.map.status()).encode())

            return handler
        return None

    # -- paxos -----------------------------------------------------------------

    def _queue(self, mutate, on_committed=None) -> None:
        def make_blob():
            scratch = FSMap.scratch(self.map)
            result = mutate(scratch)
            if result is None:
                return None
            return result.to_blob(self.map.epoch + 1)

        self._props.queue(make_blob, on_committed)

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        m = self.map
        m.epoch = info["epoch"]
        m.filesystems = info["filesystems"]
        m.standbys = dict(info["standbys"])
        dout(
            "mon", 10,
            f"fsmap e{m.epoch}: {sorted(m.actives().items())} "
            f"standbys={sorted(m.standbys)}",
        )
        self.mon.publish_mdsmap()

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        if self.map.epoch == 0 or subs.get("mdsmap", 0) > self.map.epoch:
            return
        subs["mdsmap"] = self.map.epoch + 1
        self.mon.send_to_conn(conn, self.map.to_msg())
