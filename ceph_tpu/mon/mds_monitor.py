"""MDSMonitor — the PaxosService owning the FSMap (src/mon/MDSMonitor.cc,
src/mds/FSMap.h).

Mirrored behaviors:
- MDS daemons announce themselves with beacons (MMDSBeacon →
  MDSMonitor::prepare_beacon); once a filesystem exists (`fs new`), the
  first daemon takes **rank 0 (active)** and later ones queue as
  **standbys** (FSMap::promote / assign_standby_replay essence).
- A missed beacon window fails the active rank over to a standby
  (`mds_beacon_grace`, MDSMonitor::tick → maybe_replace_gid), bumping the
  map epoch; the promoted standby sees itself active in the next MMDSMap
  and runs journal replay before serving.
- The map publishes to "mdsmap" subscribers (clients resolving the
  active MDS; standbys learning of promotion) — check_sub.
- Commands: `fs new <name> <meta> <data>`, `fs rm <name>`, `fs status`
  (MDSMonitor's command surface, trimmed to the single-fs scope the MDS
  daemon implements).

Single-filesystem, single-active-rank scope matching ceph_tpu.mds (rank
0 only; multi-rank subtree partitioning is out of scope there and
therefore here).
"""

from __future__ import annotations

import json
import time

from ..common.log import dout
from ..msg.messages import MMDSBeacon, MMDSMap
from .paxos_service import ProposalQueue

BEACON_GRACE = 6.0  # mds_beacon_grace (scaled down like mgr's)


class FSMap:
    """The one-filesystem FSMap: rank-0 holder + standbys."""

    def __init__(self) -> None:
        self.epoch = 0
        self.fs_name = ""  # empty until `fs new`
        self.meta_pool = ""
        self.data_pool = ""
        self.active_name = ""
        self.active_addr = ""
        self.standbys: dict[str, str] = {}  # name -> addr

    def to_msg(self) -> MMDSMap:
        return MMDSMap(
            epoch=self.epoch,
            fs_name=self.fs_name,
            active_name=self.active_name,
            active_addr=self.active_addr,
            standbys=sorted(self.standbys),
        )

    def status(self) -> dict:
        """`ceph fs status` / `ceph status` fsmap line."""
        if not self.fs_name:
            return {"epoch": self.epoch, "filesystems": []}
        return {
            "epoch": self.epoch,
            "filesystems": [
                {
                    "name": self.fs_name,
                    "metadata_pool": self.meta_pool,
                    "data_pool": self.data_pool,
                    "rank0": self.active_name or None,
                    "standbys": sorted(self.standbys),
                    "state": "up:active" if self.active_name else "down",
                }
            ],
        }


class MDSMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.map = FSMap()
        self._last_beacon: dict[str, float] = {}
        self._props = ProposalQueue(mon, "mds")

    def on_election_changed(self) -> None:
        self._props.reset()
        # Re-baseline beacons: a fresh leader judging against 0.0 would
        # instantly fail a healthy active (same as MgrMonitor).
        now = time.monotonic()
        for name in [self.map.active_name, *self.map.standbys]:
            if name:
                self._last_beacon[name] = now

    # -- beacons ---------------------------------------------------------------

    def prepare_beacon(self, msg: MMDSBeacon) -> None:
        """Leader-only (MDSMonitor::prepare_beacon)."""
        self._last_beacon[msg.name] = time.monotonic()

        def mutate(m: FSMap):
            if not m.fs_name:
                # No filesystem yet: everyone waits as a standby so
                # `fs new` can promote instantly (MDSMonitor holds boot
                # beacons in standby until a filesystem wants a rank).
                if m.standbys.get(msg.name) != msg.addr:
                    standbys = dict(m.standbys)
                    standbys[msg.name] = msg.addr
                    return ("", "", standbys)
                return None
            if m.active_name == msg.name:
                if m.active_addr != msg.addr:
                    return (msg.name, msg.addr, m.standbys)
                return None
            if not m.active_name:
                standbys = dict(m.standbys)
                standbys.pop(msg.name, None)
                return (msg.name, msg.addr, standbys)
            if m.standbys.get(msg.name) != msg.addr:
                standbys = dict(m.standbys)
                standbys[msg.name] = msg.addr
                return (m.active_name, m.active_addr, standbys)
            return None

        self._queue(mutate)

    def tick(self) -> None:
        """Fail rank 0 over when its beacons stop (MDSMonitor::tick →
        maybe_replace_gid; driven by the monitor's periodic tick)."""
        if not self.mon.is_leader() or not self.map.active_name:
            return
        last = self._last_beacon.get(self.map.active_name, 0.0)
        if time.monotonic() - last <= BEACON_GRACE:
            return
        failed = self.map.active_name
        self._last_beacon.pop(failed, None)

        def mutate(m: FSMap):
            if m.active_name != failed:
                return None  # already replaced
            standbys = dict(m.standbys)
            if standbys:
                name = sorted(standbys)[0]
                addr = standbys.pop(name)
                dout("mon", 1, f"mds {failed} failed; promoting {name} to rank 0")
                return (name, addr, standbys)
            dout("mon", 1, f"mds {failed} failed; no standby — fs degraded")
            return ("", "", {})

        self._queue(mutate)

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        if prefix == "fs new":
            def handler(cmd, reply):
                name = cmd.get("fs_name", "")
                meta, data = cmd.get("metadata", ""), cmd.get("data", "")
                if not name or not meta or not data:
                    reply(-22, "usage: fs new <fs_name> <metadata> <data>")
                    return
                osdmap = self.mon.osdmon.osdmap
                pools = {p.name for p in osdmap.pools.values()}
                for pool in (meta, data):
                    if pool not in pools:
                        reply(-2, f"pool {pool!r} does not exist")
                        return

                def mutate(m: FSMap):
                    if m.fs_name:
                        return None  # single-fs scope: already created
                    # promote the first waiting standby to rank 0
                    standbys = dict(m.standbys)
                    active_name = active_addr = ""
                    if standbys:
                        active_name = sorted(standbys)[0]
                        active_addr = standbys.pop(active_name)
                    return (active_name, active_addr, standbys, name, meta, data)

                def on_committed(version: int) -> None:
                    if version < 0 and self.map.fs_name != name:
                        reply(-17, f"filesystem {self.map.fs_name!r} already exists")
                    else:
                        reply(0, f"new fs with metadata pool {meta} and data pool {data}")

                self._queue(mutate, on_committed)

            handler.mutating = True
            return handler
        if prefix == "fs rm":
            def handler(cmd, reply):
                name = cmd.get("fs_name", "")
                if not name:
                    reply(-22, "usage: fs rm <fs_name>")
                    return
                if name != self.map.fs_name:
                    # a typo'd name must not remove the real filesystem
                    reply(-2, f"filesystem {name!r} does not exist")
                    return

                def mutate(m: FSMap):
                    if m.fs_name != name:
                        return None
                    return ("", "", dict(m.standbys), "", "", "")

                self._queue(mutate, lambda v: reply(0, f"fs {name!r} removed"))

            handler.mutating = True
            return handler
        if prefix == "fs status":
            def handler(cmd, reply):
                reply(0, "", json.dumps(self.map.status()).encode())

            return handler
        return None

    # -- paxos -----------------------------------------------------------------

    def _queue(self, mutate, on_committed=None) -> None:
        def make_blob():
            result = mutate(self.map)
            if result is None:
                return None
            if len(result) == 3:
                active_name, active_addr, standbys = result
                fs = (self.map.fs_name, self.map.meta_pool, self.map.data_pool)
            else:
                active_name, active_addr, standbys, *fs = result
            return json.dumps(
                {
                    "epoch": self.map.epoch + 1,
                    "fs_name": fs[0],
                    "meta_pool": fs[1],
                    "data_pool": fs[2],
                    "active_name": active_name,
                    "active_addr": active_addr,
                    "standbys": standbys,
                }
            ).encode()

        self._props.queue(make_blob, on_committed)

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        m = self.map
        m.epoch = info["epoch"]
        m.fs_name = info["fs_name"]
        m.meta_pool = info["meta_pool"]
        m.data_pool = info["data_pool"]
        m.active_name = info["active_name"]
        m.active_addr = info["active_addr"]
        m.standbys = dict(info["standbys"])
        dout(
            "mon", 10,
            f"fsmap e{m.epoch}: fs={m.fs_name or '(none)'} "
            f"rank0={m.active_name or '(none)'} standbys={sorted(m.standbys)}",
        )
        self.mon.publish_mdsmap()

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        if self.map.epoch == 0 or subs.get("mdsmap", 0) > self.map.epoch:
            return
        subs["mdsmap"] = self.map.epoch + 1
        self.mon.send_to_conn(conn, self.map.to_msg())
