"""LogMonitor — the PaxosService owning the cluster log.

Mirror of src/mon/LogMonitor.{h,cc}: daemons' `clog` sinks (LogClient in
the reference; OSD.clog_error here) send MLog entries to the monitors;
the leader batches them through Paxos so every quorum member holds the
same bounded, versioned log; `log last [n]` reads the tail and "log"
subscribers get committed entries pushed.  This is where the EC data
path's integrity complaints land — the reference raises
`clog->error() << "Bad hash for ..."` on chunk CRC mismatch
(src/osd/ECBackend.cc:1080); here the scrubber's clog_error ends up in
this service, queryable from any mon.
"""

from __future__ import annotations

import json
import time
from collections import deque

from ..common.log import dout
from ..msg.messages import MLog
from .paxos_service import ProposalQueue

KEEP = 500  # bounded committed tail (mon_log_max summarised)


class LogMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.version = 0
        self.entries: deque[dict] = deque(maxlen=KEEP)
        self._incoming: list[dict] = []
        self._props = ProposalQueue(mon, "logm")

    def on_election_changed(self) -> None:
        self._incoming.clear()
        self._props.reset()

    # -- daemon -> mon entries -------------------------------------------------

    def prepare_log(self, msg: MLog) -> None:
        """Leader-only (LogMonitor::prepare_log): queue incoming entries
        for the next proposal."""
        try:
            entries = json.loads(msg.entries.decode())
        except json.JSONDecodeError:
            dout("mon", 5, "logm: dropping undecodable MLog")
            return
        for e in entries:
            self._incoming.append(
                {
                    "prio": str(e.get("prio", "info")),
                    "who": str(e.get("who", "?")),
                    "stamp": float(e.get("stamp", time.time())),
                    "msg": str(e.get("msg", "")),
                }
            )
        self._props.queue(self._make_blob)

    def log(self, prio: str, who: str, message: str) -> None:
        """In-process clog entry from the mon itself (LogChannel::do_log).
        On a peon this routes like a daemon entry — forwarded to the
        leader — so it is never stranded in a local queue."""
        entry = {"prio": prio, "who": who, "stamp": time.time(), "msg": message}
        if self.mon.is_leader():
            self._incoming.append(entry)
            self._props.queue(self._make_blob)
        elif self.mon.leader_rank is not None:
            self.mon._send_mon(
                self.mon.leader_rank,
                MLog(version=0, entries=json.dumps([entry]).encode()),
            )

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        if prefix != "log last":
            return None
        fn = self._cmd_log_last
        fn.__func__.mutating = False
        return fn

    def _cmd_log_last(self, cmd, reply) -> None:
        n = int(cmd.get("num", 20))
        level = cmd.get("level")
        tail = [
            e
            for e in self.entries
            if level is None or e["prio"] == level
        ]
        # tail[-0:] would be the whole tail; n <= 0 means "no entries"
        # (version probe).
        tail = tail[-n:] if n > 0 else []
        reply(
            0,
            "",
            json.dumps({"version": self.version, "entries": tail}).encode(),
        )

    # -- paxos -----------------------------------------------------------------

    def _make_blob(self) -> bytes | None:
        """Drain everything accumulated since the last proposal; queued
        kicks whose entries were already taken become no-ops."""
        if not self._incoming:
            return None
        batch, self._incoming = self._incoming, []
        return json.dumps({"version": self.version + 1, "append": batch}).encode()

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        self.version = info["version"]
        appended = info["append"]
        self.entries.extend(appended)
        for e in appended:
            dout("mon", 10, f"clog {e['prio']} {e['who']}: {e['msg']}")
        self.mon.publish_log(appended)

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        """Initial push on subscribe: the committed tail."""
        if self.version == 0 or subs.get("log", 0) > self.version:
            return
        subs["log"] = self.version + 1
        self.mon.send_to_conn(
            conn,
            MLog(
                version=self.version,
                entries=json.dumps(list(self.entries)).encode(),
            ),
        )

    def push_new(self, conn, subs: dict[str, int], appended: list[dict]) -> None:
        """Incremental push of freshly committed entries."""
        if subs.get("log", 0) > self.version:
            return
        subs["log"] = self.version + 1
        self.mon.send_to_conn(
            conn,
            MLog(version=self.version, entries=json.dumps(appended).encode()),
        )
