"""LogMonitor — the PaxosService owning the cluster log.

Mirror of src/mon/LogMonitor.{h,cc}: daemons' clog sinks
(common/clog.py's ClusterLogClient; the reference's LogClient) send MLog
entries to the monitors; the leader batches them through Paxos so every
quorum member holds the same bounded, versioned log; `log last [n]
[channel] [level]` reads the tail and "log" subscribers get committed
entries pushed.  This is where the EC data path's integrity complaints
land — the reference raises `clog->error() << "Bad hash for ..."` on
chunk CRC mismatch (src/osd/ECBackend.cc:1080); here the scrubber's
clog_error ends up in this service, queryable from any mon.

ISSUE 16 grows this service into the cluster event timeline:

- Entries are structured: channel (`cluster` | `audit`), severity,
  entity, per-client seq, optional health-check code.  The bounded
  tail honors the runtime-mutable `mon_log_max` option.
- **Health event history**: the leader's tick diffs the mon's rendered
  health checks against the committed `active_checks` state and
  records every transition (raise / update / clear) as a timestamped
  event — queryable via `health history` — while also emitting the
  Ceph-style "Health check failed/cleared" cluster-log lines.
- **Health mute** (`health mute <code> [ttl] [--sticky]` /
  `health unmute <code>`): muted checks drop out of the health banner
  and overall_status but keep being evaluated and scraped.  TTLs
  expire, and a non-sticky mute auto-clears when the check worsens
  (its detail-line count exceeds the count at mute time) — Ceph's
  HealthMonitor mute semantics.  Mutes, events, and the active-check
  map all ride the same paxos blobs, so they are identical across the
  quorum and survive elections.
"""

from __future__ import annotations

import json
import time
from collections import deque

from ..common.health import check_severity
from ..common.log import dout
from ..msg.messages import MLog
from .paxos_service import ProposalQueue

KEEP_DEFAULT = 500  # mon_log_max default (bound re-read per commit)

# health-event history bound: transitions are far rarer than log
# entries, so a fixed generous cap keeps the state small without
# another option
EVENTS_KEEP = 200


def _parse_ttl(spec) -> float | None:
    """Mute TTL: seconds as a number, or '30s' / '5m' / '2h' strings
    (the reference's `ceph health mute <code> <ttl>` accepts the same
    suffixed durations).  None / empty = no expiry."""
    if spec is None or spec == "":
        return None
    if isinstance(spec, (int, float)):
        return float(spec)
    s = str(spec).strip().lower()
    mult = 1.0
    if s and s[-1] in "smh":
        mult = {"s": 1.0, "m": 60.0, "h": 3600.0}[s[-1]]
        s = s[:-1]
    return float(s) * mult


class LogMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.version = 0
        self.entries: deque[dict] = deque(maxlen=self._keep())
        # committed health-event history + lifetime counter
        self.health_events: deque[dict] = deque(maxlen=EVENTS_KEEP)
        self.events_total = 0
        # committed rendered-check state the leader's tick diffs against
        # (code -> {severity, summary, count}); committed so a NEW
        # leader after an election diffs against the same state and
        # does not re-raise events for checks that never transitioned
        self.active_checks: dict[str, dict] = {}
        # committed mutes: code -> {sticky, ttl_expires|None, count, stamp}
        self.mutes: dict[str, dict] = {}
        self._incoming: list[dict] = []
        self._mute_ops: list[dict] = []
        self._pending_events: list[dict] = []
        self._props = ProposalQueue(mon, "logm")

    def _keep(self) -> int:
        try:
            return max(1, int(self.mon.conf.get("mon_log_max")))
        except KeyError:
            return KEEP_DEFAULT

    def on_election_changed(self) -> None:
        self._incoming.clear()
        self._mute_ops.clear()
        self._pending_events.clear()
        self._props.reset()

    # -- daemon -> mon entries -------------------------------------------------

    @staticmethod
    def _coerce(e: dict) -> dict:
        """Normalize one wire entry: legacy senders (no channel/seq)
        still land as cluster-channel entries."""
        out = {
            "prio": str(e.get("prio", "info")),
            "channel": str(e.get("channel", "cluster")),
            "who": str(e.get("who", "?")),
            "stamp": float(e.get("stamp", time.time())),
            "msg": str(e.get("msg", "")),
        }
        if e.get("seq") is not None:
            out["seq"] = int(e["seq"])
        if e.get("code"):
            out["code"] = str(e["code"])
        return out

    def prepare_log(self, msg: MLog) -> None:
        """Leader-only (LogMonitor::prepare_log): queue incoming entries
        for the next proposal."""
        try:
            entries = json.loads(msg.entries.decode())
        except json.JSONDecodeError:
            dout("mon", 5, "logm: dropping undecodable MLog")
            return
        for e in entries:
            self._incoming.append(self._coerce(e))
        self._props.queue(self._make_blob)

    def log(
        self,
        prio: str,
        who: str,
        message: str,
        channel: str = "cluster",
        code: str | None = None,
    ) -> None:
        """In-process clog entry from the mon itself (LogChannel::do_log).
        On a peon this routes like a daemon entry — forwarded to the
        leader — so it is never stranded in a local queue."""
        entry = {
            "prio": prio,
            "channel": channel,
            "who": who,
            "stamp": time.time(),
            "msg": message,
        }
        if code is not None:
            entry["code"] = code
        if self.mon.is_leader():
            self._incoming.append(entry)
            self._props.queue(self._make_blob)
        elif self.mon.leader_rank is not None:
            self.mon._send_mon(
                self.mon.leader_rank,
                MLog(version=0, entries=json.dumps([entry]).encode()),
            )

    # -- health events + mutes (leader tick) -----------------------------------

    def tick(self) -> None:
        """Leader-only, from Monitor's tick loop: diff the rendered
        health checks against committed state, recording transitions as
        events + clog lines, and expire / auto-clear mutes."""
        if not self.mon.is_leader():
            return
        now = time.time()
        checks, details = self.mon.health_checks()
        current = {
            code: {
                "severity": check_severity(code),
                "summary": summary,
                "count": len(details.get(code, ())) or 1,
            }
            for code, summary in checks.items()
        }
        events: list[dict] = []
        for code, cur in sorted(current.items()):
            prev = self.active_checks.get(code)
            if prev is None:
                events.append({"type": "raise", "code": code, **cur})
            elif prev["summary"] != cur["summary"] or prev["count"] != cur["count"]:
                events.append({"type": "update", "code": code, **cur})
        for code, prev in sorted(self.active_checks.items()):
            if code not in current:
                events.append(
                    {
                        "type": "clear",
                        "code": code,
                        "severity": prev["severity"],
                        "summary": prev["summary"],
                        "count": 0,
                    }
                )
        for ev in events:
            ev["stamp"] = now
            # the Ceph cluster-log lines health transitions produce
            if ev["type"] == "clear":
                prio, text = "info", f"Health check cleared: {ev['code']}"
            elif ev["type"] == "raise":
                prio = "error" if ev["severity"] == "HEALTH_ERR" else "warn"
                text = f"Health check failed: {ev['summary']} ({ev['code']})"
            else:
                prio = "error" if ev["severity"] == "HEALTH_ERR" else "warn"
                text = f"Health check update: {ev['summary']} ({ev['code']})"
            self._incoming.append(
                {
                    "prio": prio,
                    "channel": "cluster",
                    "who": f"mon.{self.mon.name}",
                    "stamp": now,
                    "msg": text,
                    "code": ev["code"],
                }
            )
        self._pending_events.extend(events)
        # mute maintenance: expire TTLs; auto-clear non-sticky mutes
        # whose check worsened past the mute-time count
        for code, m in sorted(self.mutes.items()):
            exp = m.get("ttl_expires")
            if exp is not None and now >= exp:
                self._queue_mute_op({"op": "unmute", "code": code}, None)
                self.log(
                    "info", f"mon.{self.mon.name}",
                    f"health mute {code} expired", code=code,
                )
            elif (
                not m.get("sticky")
                and code in current
                and current[code]["count"] > m.get("count", 0)
            ):
                self._queue_mute_op({"op": "unmute", "code": code}, None)
                self.log(
                    "warn", f"mon.{self.mon.name}",
                    f"health mute {code} cleared: check worsened "
                    f"({m.get('count', 0)} -> {current[code]['count']})",
                    code=code,
                )
        if events or self._incoming:
            self._props.queue(self._make_blob)

    def _queue_mute_op(self, op: dict, on_committed) -> None:
        self._mute_ops.append(op)
        self._props.queue(self._make_blob, on_committed)

    # -- render-time mute filtering --------------------------------------------

    def muted_codes(self, now: float | None = None) -> set[str]:
        """Codes whose mute is live right now.  TTL expiry is honored at
        render time on every member — a peon serving `health` does not
        wait for the leader's tick to commit the unmute."""
        now = time.time() if now is None else now
        return {
            code
            for code, m in self.mutes.items()
            if m.get("ttl_expires") is None or now < m["ttl_expires"]
        }

    def filter_muted(
        self, checks: dict[str, str], details: dict[str, list[str]]
    ) -> tuple[dict[str, str], dict[str, list[str]], list[str]]:
        """(visible checks, visible details, muted codes that are both
        muted and currently raised) — the banner drops muted checks but
        names them, the reference's `(muted: CODE)` status suffix."""
        muted = self.muted_codes()
        vis = {c: s for c, s in checks.items() if c not in muted}
        vdet = {c: d for c, d in details.items() if c not in muted}
        return vis, vdet, sorted(c for c in checks if c in muted)

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        table = {
            "log last": (self._cmd_log_last, False),
            "health history": (self._cmd_health_history, False),
            "health mute": (self._cmd_health_mute, True),
            "health unmute": (self._cmd_health_unmute, True),
        }
        entry = table.get(prefix)
        if entry is None:
            return None
        fn, mutating = entry
        fn.__func__.mutating = mutating
        return fn

    def _cmd_log_last(self, cmd, reply) -> None:
        n = int(cmd.get("num", 20))
        level = cmd.get("level")
        channel = cmd.get("channel")
        tail = [
            e
            for e in self.entries
            if (level is None or e["prio"] == level)
            and (channel is None or e.get("channel", "cluster") == channel)
        ]
        # tail[-0:] would be the whole tail; n <= 0 means "no entries"
        # (version probe).
        tail = tail[-n:] if n > 0 else []
        reply(
            0,
            "",
            json.dumps({"version": self.version, "entries": tail}).encode(),
        )

    def _cmd_health_history(self, cmd, reply) -> None:
        n = int(cmd.get("num", 50))
        events = list(self.health_events)
        reply(
            0,
            "",
            json.dumps(
                {
                    "version": self.version,
                    "events": events[-n:] if n > 0 else [],
                    "events_total": self.events_total,
                    "mutes": self.mutes,
                    "active": self.active_checks,
                }
            ).encode(),
        )

    def _cmd_health_mute(self, cmd, reply) -> None:
        code = str(cmd.get("code", "")).strip()
        if not code:
            reply(-22, "health mute: a check code is required")
            return
        try:
            ttl = _parse_ttl(cmd.get("ttl"))
        except ValueError:
            reply(-22, f"health mute: invalid ttl {cmd.get('ttl')!r}")
            return
        checks, details = self.mon.health_checks()
        op = {
            "op": "mute",
            "code": code,
            "sticky": bool(cmd.get("sticky")),
            "ttl_expires": None if ttl is None else time.time() + ttl,
            "count": len(details.get(code, ())) or (1 if code in checks else 0),
            "stamp": time.time(),
        }
        self._queue_mute_op(
            op, lambda _v: reply(0, f"muted {code}")
        )
        self.log(
            "info", f"mon.{self.mon.name}",
            f"health mute {code}"
            + (f" ttl={cmd.get('ttl')}" if cmd.get("ttl") else "")
            + (" sticky" if cmd.get("sticky") else ""),
            channel="audit", code=code,
        )

    def _cmd_health_unmute(self, cmd, reply) -> None:
        code = str(cmd.get("code", "")).strip()
        if not code:
            reply(-22, "health unmute: a check code is required")
            return
        if code not in self.mutes:
            reply(-2, f"{code} is not muted")
            return
        self._queue_mute_op(
            {"op": "unmute", "code": code},
            lambda _v: reply(0, f"unmuted {code}"),
        )
        self.log(
            "info", f"mon.{self.mon.name}",
            f"health unmute {code}", channel="audit", code=code,
        )

    # -- paxos -----------------------------------------------------------------

    def _make_blob(self) -> bytes | None:
        """Drain everything accumulated since the last proposal; queued
        kicks whose payload was already taken become no-ops."""
        if not (self._incoming or self._pending_events or self._mute_ops):
            return None
        blob: dict = {"version": self.version + 1}
        if self._incoming:
            blob["append"], self._incoming = self._incoming, []
        if self._pending_events:
            blob["events"], self._pending_events = self._pending_events, []
        if self._mute_ops:
            blob["mute_ops"], self._mute_ops = self._mute_ops, []
        return json.dumps(blob).encode()

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        self.version = info["version"]
        keep = self._keep()
        if keep != self.entries.maxlen:
            self.entries = deque(self.entries, maxlen=keep)
        appended = info.get("append", [])
        self.entries.extend(appended)
        for e in appended:
            dout("mon", 10, f"clog {e['prio']} {e['who']}: {e['msg']}")
        for ev in info.get("events", []):
            self.health_events.append(ev)
            self.events_total += 1
            code = ev["code"]
            if ev["type"] == "clear":
                self.active_checks.pop(code, None)
            else:
                self.active_checks[code] = {
                    "severity": ev["severity"],
                    "summary": ev["summary"],
                    "count": ev["count"],
                }
        for op in info.get("mute_ops", []):
            if op["op"] == "mute":
                self.mutes[op["code"]] = {
                    "sticky": op.get("sticky", False),
                    "ttl_expires": op.get("ttl_expires"),
                    "count": op.get("count", 0),
                    "stamp": op.get("stamp", 0.0),
                }
            else:
                self.mutes.pop(op["code"], None)
        if appended:
            self.mon.publish_log(appended)

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        """Initial push on subscribe: the committed tail."""
        if self.version == 0 or subs.get("log", 0) > self.version:
            return
        subs["log"] = self.version + 1
        self.mon.send_to_conn(
            conn,
            MLog(
                version=self.version,
                entries=json.dumps(list(self.entries)).encode(),
            ),
        )

    def push_new(self, conn, subs: dict[str, int], appended: list[dict]) -> None:
        """Incremental push of freshly committed entries."""
        if subs.get("log", 0) > self.version:
            return
        subs["log"] = self.version + 1
        self.mon.send_to_conn(
            conn,
            MLog(version=self.version, entries=json.dumps(appended).encode()),
        )
