"""Monitor control plane — mirror of src/mon/.

Paxos-replicated cluster maps, mon elections, EC-profile administration,
and map publication to subscribers (SURVEY.md §2.7).
"""

from .elector import Elector
from .monmap import MonMap
from .monitor import Monitor
from .paxos import Paxos

__all__ = ["Elector", "MonMap", "Monitor", "Paxos"]
