"""Monitor daemon — mirror of src/mon/Monitor.{h,cc}.

One Monitor per configured name; an elected leader drives Paxos proposals
for every PaxosService (here: OSDMonitor).  Mirrored structure:

- Elections (Elector) -> leader_init/peon_init on Paxos
  (Monitor::win_election / lose_election).
- Services propose encoded pending state through Paxos; every quorum member
  applies commits in order and publishes to its own subscribers
  (PaxosService::propose_pending / refresh).
- Subscriptions (MMonSubscribe): "osdmap" subscribers get the current full
  map immediately and incrementals as they commit
  (Monitor::handle_subscribe, OSDMonitor::check_osdmap_sub).
- Commands (MMonCommand, JSON like the reference's cmdmap): queries are
  answered by any quorum member from committed state; mutations on a peon
  return -EAGAIN naming the leader so clients re-target (the reference
  forwards instead; re-targeting keeps the same consistency).
- OSD boot/failure reports: OSDs broadcast to all mons; only the leader
  acts (prepare_boot / prepare_failure).
"""

from __future__ import annotations

import asyncio
import json

from ..common.health import overall_status as health_status
from ..common.log import dout
from ..msg.messages import (
    MLog,
    MMDSBeacon,
    MMgrBeacon,
    MMonMgrReport,
    MMonCommand,
    MMonCommandAck,
    MMonElection,
    MMonPaxos,
    MMonSubscribe,
    MOSDBoot,
    MOSDFailure,
    MOSDMap,
)
from ..msg.messenger import Connection, Dispatcher, Messenger, Policy
from .auth_monitor import AuthMonitor
from .config_monitor import ConfigMonitor
from .elector import Elector
from .log_monitor import LogMonitor
from .monmap import MonMap
from .mds_monitor import MDSMonitor
from .mgr_monitor import MgrMonitor
from .osd_monitor import OSDMonitor
from .paxos import Paxos
from ..common.errs import EAGAIN, EINVAL


class Monitor(Dispatcher):
    def __init__(
        self,
        name: str,
        monmap: MonMap,
        election_timeout: float = 0.5,
        conf=None,  # common.config.Config; None = option-table defaults
        keyring=None,  # KeyRing enabling cephx on this mon's sessions
        secure: bool = False,
        compress: bool = False,
        stack: str = "posix",  # ms_type (msg/stack.py)
        admin_socket: str = "",  # unix socket path; empty disables
    ):
        self._admin_socket_path = admin_socket
        self.admin_socket = None
        self.name = name
        self.monmap = monmap
        self.rank = monmap.rank_of(name)
        if conf is None:
            from ..common.config import Config

            conf = Config({"name": name})
        self.conf = conf
        auth = None
        if keyring is not None:
            from ..auth.cephx import CephxAuth

            auth = CephxAuth.for_daemon(f"mon.{name}", keyring)
        self.msgr = Messenger(
            f"mon.{name}", auth=auth, secure=secure, compress=compress,
            stack=stack,
        )
        self.msgr.default_policy = Policy.lossless_peer()
        self.elector = Elector(
            self.rank,
            monmap.size(),
            self._send_mon_election,
            on_win=self._win_election,
            on_lose=self._lose_election,
            timeout=election_timeout,
        )
        self.paxos = Paxos(self.rank, self._send_mon_paxos, self._apply_commit)
        self.quorum: list[int] = []
        self.leader_rank: int | None = None
        self.osdmon = OSDMonitor(
            self,
            min_down_reporters=int(
                self.conf.get("mon_osd_min_down_reporters")
            ),
        )
        self.mgrmon = MgrMonitor(self)
        self.mdsmon = MDSMonitor(self)
        self.configmon = ConfigMonitor(self)
        self.logmon = LogMonitor(self)
        self.authmon = AuthMonitor(self)
        # conn -> {what -> next epoch}
        self.subs: dict[Connection, dict[str, int]] = {}
        # latest PGMap digest from the active mgr (MMonMgrReport);
        # volatile health data, re-sent every mgr beacon interval
        self.pg_digest: dict = {}
        self._started = asyncio.Event()
        self._tick_task: asyncio.Task | None = None

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        await self.msgr.bind(self.monmap.addrs[self.name])
        self.msgr.add_dispatcher_head(self)
        self.elector.start()
        self._tick_task = asyncio.create_task(self._tick_loop())
        await self._start_admin_socket()
        self._started.set()

    async def _start_admin_socket(self) -> None:
        """Mon admin socket (Monitor::_add_admin_socket_commands):
        mon_status / quorum_status / paxos introspection."""
        if not self._admin_socket_path:
            return
        from ..common.admin_socket import AdminSocket

        sock = AdminSocket(self._admin_socket_path)
        sock.register("mon_status", lambda cmd: self.mon_status(),
                      "this monitor's state")
        # same payload as the MMonCommand quorum_status handler, so the
        # two views of the quorum can never drift apart
        sock.register("quorum_status", lambda cmd: self.quorum_status(),
                      "current quorum + leader")
        sock.register("paxosinfo", lambda cmd: {
            "last_committed": self.paxos.last_committed,
            "accepted_pn": self.paxos.accepted_pn,
            "leading": self.paxos.leading,
            "store_versions": len(self.paxos.store),
        }, "paxos engine state (Paxos::dump_info)")
        await sock.start()
        self.admin_socket = sock

    def quorum_status(self) -> dict:
        """Shared quorum view (the MMonCommand handler and the admin
        socket both serve this shape)."""
        return {
            "quorum": sorted(self.quorum),
            "leader": self.leader_rank,
            "epoch": self.elector.epoch,
        }

    def mon_status(self) -> dict:
        """`ceph tell mon.x mon_status` payload."""
        return {
            "name": self.name,
            "rank": self.rank,
            "state": (
                "leader" if self.is_leader()
                else "peon" if self.rank in self.quorum
                else "electing"
            ),
            "quorum": sorted(self.quorum),
            "monmap": dict(self.monmap.addrs),
        }

    async def stop(self) -> None:
        self.elector.cancel()
        if self._tick_task is not None:
            self._tick_task.cancel()
            self._tick_task = None
        if self.admin_socket is not None:
            await self.admin_socket.stop()
            self.admin_socket = None
        await self.msgr.shutdown()

    async def _tick_loop(self) -> None:
        """Monitor::tick: periodic service timers (mgr beacon grace,
        future health checks) on the leader."""
        while True:
            await asyncio.sleep(self.conf.get("mon_tick_interval"))
            if self.is_leader():
                self.mgrmon.tick()
                self.mdsmon.tick()
                self.osdmon.tick()
                # health-event history + mute maintenance (ISSUE 16):
                # diffs rendered checks against committed state
                self.logmon.tick()

    async def wait_for_quorum(self, timeout: float = 5.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while self.leader_rank is None:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError("no quorum")
            await asyncio.sleep(0.01)

    def is_leader(self) -> bool:
        return self.leader_rank == self.rank

    # -- transport helpers -----------------------------------------------------

    def _send_mon(self, rank: int, msg) -> None:
        if rank == self.rank:
            return
        addr = self.monmap.addr_of_rank(rank)

        async def _send():
            try:
                await self.msgr.send_to(addr, msg)
            except ConnectionError:
                dout("mon", 10, f"mon.{self.name}: send to rank {rank} failed")

        asyncio.get_event_loop().create_task(_send())

    def _send_mon_election(self, rank: int, msg: MMonElection) -> None:
        self._send_mon(rank, msg)

    def _send_mon_paxos(self, rank: int, msg: MMonPaxos) -> None:
        self._send_mon(rank, msg)

    # -- election outcomes -----------------------------------------------------

    def _win_election(self, epoch: int, quorum: list[int]) -> None:
        self.quorum = quorum
        self.leader_rank = self.rank
        self.paxos.leader_init(quorum)
        self.osdmon.on_active()
        for svc in (self.mgrmon, self.mdsmon, self.configmon, self.logmon,
                    self.authmon):
            svc.on_election_changed()

    def _lose_election(
        self, epoch: int, leader: int, quorum: list[int] | None = None
    ) -> None:
        # Peons DO know the quorum: the victory message carries it
        # (previously reset to [], which made every healthy peon report
        # "electing" with an empty quorum through mon_status).
        self.quorum = list(quorum or [])
        self.leader_rank = leader
        self.paxos.peon_init(leader)
        self.osdmon.on_election_lost()
        for svc in (self.mgrmon, self.mdsmon, self.configmon, self.logmon,
                    self.authmon):
            svc.on_election_changed()

    # -- commit application ----------------------------------------------------

    def _apply_commit(self, version: int, value: bytes) -> None:
        """Every quorum member applies committed service transactions in
        order (PaxosService::refresh)."""
        service, _, blob = value.partition(b"\x00")
        if service == b"osd":
            self.osdmon.apply_commit(blob)
        elif service == b"mgr":
            self.mgrmon.apply_commit(blob)
        elif service == b"mds":
            self.mdsmon.apply_commit(blob)
        elif service == b"config":
            self.configmon.apply_commit(blob)
        elif service == b"logm":
            self.logmon.apply_commit(blob)
        elif service == b"auth":
            self.authmon.apply_commit(blob)

    def propose(self, service: str, blob: bytes, on_done=None) -> None:
        self.paxos.propose(service.encode() + b"\x00" + blob, on_done)

    # -- dispatch --------------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MMonElection):
            self.elector.handle(msg)
        elif isinstance(msg, MMonPaxos):
            self.paxos.handle(msg, self._peer_rank(conn))
        elif isinstance(msg, MMonSubscribe):
            self._handle_subscribe(conn, msg)
        elif isinstance(msg, MMonCommand):
            self._handle_command(conn, msg)
        elif isinstance(msg, MOSDBoot):
            if self.is_leader():
                self.osdmon.prepare_boot(msg)
        elif isinstance(msg, MOSDFailure):
            if self.is_leader():
                self.osdmon.prepare_failure(msg, reporter=msg.src)
        elif isinstance(msg, MMgrBeacon):
            if self.is_leader():
                self.mgrmon.prepare_beacon(msg)
        elif isinstance(msg, MMDSBeacon):
            if self.is_leader():
                self.mdsmon.prepare_beacon(msg)
        elif isinstance(msg, MMonMgrReport):
            # Only the ACTIVE mgr (per the committed mgrmap) may supply the
            # digest: it drives FLAG_FULL_QUOTA and SLOW_OPS, so a stats
            # report from any other session (standby mgr, spoofed client)
            # is dropped (the reference's DaemonServer gates the same way).
            active = self.mgrmon.map.active_name
            if active and conn.peer_name == f"mgr.{active}":
                try:
                    self.pg_digest = json.loads(msg.digest.decode() or "{}")
                except json.JSONDecodeError:
                    pass
            else:
                dout(
                    "mon", 5,
                    f"mon.{self.name}: dropping MMonMgrReport from "
                    f"{conn.peer_name or msg.src!r} (active mgr: {active or 'none'})",
                )
        elif isinstance(msg, MLog):
            # Daemon clog entries: the leader proposes them; a peon forwards
            # to the leader (Monitor::forward_request_leader).
            if self.is_leader():
                self.logmon.prepare_log(msg)
            elif self.leader_rank is not None:
                self._send_mon(self.leader_rank, msg)
        else:
            return False
        return True

    def ms_handle_reset(self, conn: Connection) -> None:
        self.subs.pop(conn, None)

    def _peer_rank(self, conn: Connection) -> int:
        name = conn.peer_name.removeprefix("mon.")
        return self.monmap.rank_of(name)

    # -- subscriptions ---------------------------------------------------------

    def _handle_subscribe(self, conn: Connection, msg: MMonSubscribe) -> None:
        subs = self.subs.setdefault(conn, {})
        for what, start in msg.what.items():
            subs[what] = start
            if what == "osdmap":
                self.osdmon.check_sub(conn, subs)
            elif what == "mgrmap":
                self.mgrmon.check_sub(conn, subs)
            elif what == "mdsmap":
                self.mdsmon.check_sub(conn, subs)
            elif what == "config":
                self.configmon.check_sub(conn, subs)
            elif what == "log":
                self.logmon.check_sub(conn, subs)

    def publish_osdmap(self) -> None:
        """Push new epochs to every osdmap subscriber (on commit)."""
        for conn, subs in list(self.subs.items()):
            if "osdmap" in subs:
                self.osdmon.check_sub(conn, subs)

    def publish_mgrmap(self) -> None:
        for conn, subs in list(self.subs.items()):
            if "mgrmap" in subs:
                self.mgrmon.check_sub(conn, subs)

    def publish_mdsmap(self) -> None:
        for conn, subs in list(self.subs.items()):
            if "mdsmap" in subs:
                self.mdsmon.check_sub(conn, subs)

    def publish_config(self) -> None:
        for conn, subs in list(self.subs.items()):
            if "config" in subs:
                self.configmon.check_sub(conn, subs)

    def publish_log(self, appended: list[dict]) -> None:
        for conn, subs in list(self.subs.items()):
            if "log" in subs:
                self.logmon.push_new(conn, subs, appended)

    def send_to_conn(self, conn: Connection, msg) -> None:
        async def _send():
            try:
                await conn.send_message(msg)
            except ConnectionError:
                self.subs.pop(conn, None)

        asyncio.get_event_loop().create_task(_send())

    # -- commands --------------------------------------------------------------

    def _handle_command(self, conn: Connection, msg: MMonCommand) -> None:
        try:
            cmd = json.loads(msg.cmd)
        except json.JSONDecodeError:
            self.send_to_conn(
                conn, MMonCommandAck(tid=msg.tid, retval=-EINVAL, rs="bad json", outbl=b"")
            )
            return
        prefix = cmd.get("prefix", "")
        handler = None
        for svc in (self.osdmon, self.mdsmon, self.configmon, self.logmon,
                    self.authmon):
            handler = svc.command_handler(prefix)
            if handler is not None:
                break
        handler = handler or self._mon_command_handler(prefix)
        if handler is None:
            self.send_to_conn(
                conn,
                MMonCommandAck(
                    tid=msg.tid, retval=-EINVAL, rs=f"unknown command {prefix!r}", outbl=b""
                ),
            )
            return
        mutating = getattr(handler, "mutating", False)
        if mutating and not self.is_leader():
            leader = self.leader_rank if self.leader_rank is not None else -1
            self.send_to_conn(
                conn,
                MMonCommandAck(
                    tid=msg.tid,
                    retval=-EAGAIN,
                    rs=f"not leader; leader is rank {leader}",
                    outbl=b"",
                ),
            )
            return

        def reply(retval: int, rs: str, outbl: bytes = b"") -> None:
            self.send_to_conn(
                conn, MMonCommandAck(tid=msg.tid, retval=retval, rs=rs, outbl=outbl)
            )

        if mutating:
            # every mutating command lands on the audit channel (the
            # reference mon's `audit` LogChannel: "from='client...'
            # cmd=[...]: dispatch"), logged at dispatch on the leader
            entity = conn.peer_name or "client.?"
            self.logmon.log(
                "info",
                entity,
                f"from='{entity}' cmd={json.dumps(cmd)}: dispatch",
                channel="audit",
            )
        try:
            handler(cmd, reply)
        except Exception as e:  # command bugs must not kill the mon
            reply(-EINVAL, f"command failed: {e}")

    def health_checks(self) -> tuple[dict[str, str], dict[str, list[str]]]:
        """Mon-side cluster health (`ceph -s` HEALTH line / `ceph health
        [detail]`): (checks, detail) where checks maps code -> summary
        string and detail maps code -> per-entity breakdown lines.  Down
        OSDs, missing quorum members, and dead filesystems come from the
        mon's own committed maps; SLOW_OPS comes from the active mgr's
        digest (the OSDs' OpTracker complaint counts, the reference's
        OSDMap::check_health slow-request path)."""
        from ..common import health

        checks: dict[str, str] = {}
        details: dict[str, list[str]] = {}
        down = health.down_in_osds(self.osdmon.osdmap)
        if down:
            checks["OSD_DOWN"] = (
                f"{len(down)} osds down: "
                + ", ".join(f"osd.{o}" for o in sorted(down))
            )
            details["OSD_DOWN"] = [f"osd.{o} is down" for o in sorted(down)]
        # slow-but-alive peers (ISSUE 17): laggy evidence from the OSDs'
        # heartbeat/sub-read RTT reports (OSDMonitor.laggy).  Non-fatal
        # — the target serves I/O, slowly — so a WARN, never a markdown;
        # clears when reporters send the recovery report or their
        # evidence expires
        laggy = self.osdmon.slow_peers()
        summary = health.slow_peer_summary(laggy)
        if summary:
            checks["OSD_SLOW_PEER"] = summary
            details["OSD_SLOW_PEER"] = health.slow_peer_detail(laggy)
        if len(self.quorum) < self.monmap.size():
            out = self.monmap.size() - len(self.quorum)
            checks["MON_DOWN"] = f"{out} monitor(s) out of quorum"
            details["MON_DOWN"] = [
                f"mon rank {r} not in quorum"
                for r in range(self.monmap.size())
                if r not in self.quorum
            ]
        down_fs = [
            name
            for name, fs in self.mdsmon.map.filesystems.items()
            if not fs["active_name"]
        ]
        if down_fs:
            # a filesystem with no rank 0 serves nothing
            # (MDSMonitor MDS_ALL_DOWN health check)
            checks["MDS_ALL_DOWN"] = (
                f"fs {', '.join(sorted(down_fs))} has no active MDS"
            )
            details["MDS_ALL_DOWN"] = [
                f"fs {name} has no active MDS" for name in sorted(down_fs)
            ]
        slow = self.pg_digest.get("slow_ops") or {}
        summary = health.slow_ops_summary(slow)
        if summary:
            checks["SLOW_OPS"] = summary
            details["SLOW_OPS"] = health.slow_ops_detail(slow)
        # daemons whose EC dispatch fell back to the host oracle (device
        # backend wedged/erroring; ops/guard.py verdict via the mgr
        # digest).  Clears when the daemon's re-probe heals the backend.
        degraded = self.pg_digest.get("tpu_degraded") or {}
        summary = health.tpu_degraded_summary(degraded)
        if summary:
            checks["TPU_BACKEND_DEGRADED"] = summary
            details["TPU_BACKEND_DEGRADED"] = health.tpu_degraded_detail(
                degraded
            )
        # daemons over their HBM residency target (the mempool ledger's
        # pressure verdict via the mgr digest, ISSUE 13).  Clears when
        # the staged trims — cache, donation retention, pipeline depth
        # — bring residency back under the relief threshold, or the
        # holder frees its buffers.
        pressured = self.pg_digest.get("hbm_pressure") or {}
        summary = health.hbm_pressure_summary(pressured)
        if summary:
            checks["TPU_HBM_PRESSURE"] = summary
            details["TPU_HBM_PRESSURE"] = health.hbm_pressure_detail(
                pressured
            )
        # recovery/backfill events that stopped advancing (mgr progress
        # module digest slice, ISSUE 8); clears when progress resumes or
        # the event completes
        stalled = (self.pg_digest.get("progress") or {}).get("stalled") or {}
        summary = health.recovery_stalled_summary(stalled)
        if summary:
            checks["PG_RECOVERY_STALLED"] = summary
            details["PG_RECOVERY_STALLED"] = health.recovery_stalled_detail(
                stalled
            )
        # trend sentinels from the mgr metrics-history module (ISSUE
        # 14): throughput regression / occupancy collapse / queue-wait
        # inflation vs their trailing baselines.  The wording was built
        # mgr-side by common/health.py, so rendering the shipped
        # summary/detail verbatim keeps the two surfaces in lockstep —
        # the PG_RECOVERY_STALLED raise/clear shape.  The checks drop
        # when the trend recovers (the module clears the slice).
        sentinels = (self.pg_digest.get("history") or {}).get(
            "sentinels"
        ) or {}
        for code, rec in sorted(sentinels.items()):
            summary = rec.get("summary")
            if not summary:
                continue
            checks[code] = summary
            details[code] = list(rec.get("detail") or [])
        # pools burning their latency-SLO error budget (mgr iostat
        # module digest slice, ISSUE 10): raise/clear like
        # PG_RECOVERY_STALLED — the check drops when the load stops or
        # either burn window recovers
        breaches = (self.pg_digest.get("slo") or {}).get("breaches") or {}
        summary = health.slo_breach_summary(breaches)
        if summary:
            checks["SLO_LATENCY_BREACH"] = summary
            details["SLO_LATENCY_BREACH"] = health.slo_breach_detail(
                breaches
            )
        # scrub inconsistencies (ISSUE 9 satellite): the per-PG slice
        # the primaries reported through the mgr digest.  These are the
        # two HEALTH_ERR checks — shards disagree on user data — and
        # they clear when repair + the confirming scrub come back clean
        scrub = self.pg_digest.get("scrub_errors") or {}
        summary = health.osd_scrub_errors_summary(scrub)
        if summary:
            checks["OSD_SCRUB_ERRORS"] = summary
            checks["PG_DAMAGED"] = health.pg_damaged_summary(scrub)
            details["PG_DAMAGED"] = health.pg_damaged_detail(scrub)
            details["OSD_SCRUB_ERRORS"] = details["PG_DAMAGED"]
        return checks, details

    def _mon_command_handler(self, prefix: str):
        if prefix == "df":
            def handler(cmd, reply):
                # `ceph df`: the mgr's PGMap digest (pools' STORED /
                # OBJECTS / raw USED); empty until a mgr reports
                reply(0, "", json.dumps(self.pg_digest).encode())
            return handler
        if prefix == "osd df":
            def handler(cmd, reply):
                # `ceph osd df`: per-OSD raw usage from the same digest
                reply(
                    0, "",
                    json.dumps(self.pg_digest.get("osds", {})).encode(),
                )
            return handler
        if prefix == "health":
            def handler(cmd, reply):
                # `ceph health [detail]`: the status handler's checks,
                # served standalone (ClusterHealth essence); `detail`
                # adds the per-daemon breakdown lines.  Muted checks
                # (ISSUE 16) drop out of the banner and the overall
                # status but are named, so the operator sees what is
                # silenced — the raw checks keep being evaluated and
                # scraped underneath.
                checks, details = self.health_checks()
                checks, details, muted = self.logmon.filter_muted(
                    checks, details
                )
                payload = {
                    "status": health_status(checks),
                    "checks": checks,
                    "muted": muted,
                }
                if cmd.get("detail"):
                    payload["detail"] = details
                reply(0, "", json.dumps(payload).encode())
            return handler
        if prefix == "quorum_status":
            def handler(cmd, reply):
                reply(0, "", json.dumps(self.quorum_status()).encode())
            return handler
        if prefix == "status":
            def handler(cmd, reply):
                m = self.osdmon.osdmap
                checks, _details = self.health_checks()
                checks, _details, muted = self.logmon.filter_muted(
                    checks, _details
                )
                reply(
                    0,
                    "",
                    json.dumps(
                        {
                            "health": {
                                "status": health_status(checks),
                                "checks": checks,
                                "muted": muted,
                            },
                            "quorum": sorted(self.quorum),
                            "osdmap_epoch": m.epoch,
                            "num_osds": len(m.osds),
                            "num_up_osds": m.num_up_osds(),
                            "pools": [p.name for p in m.pools.values()],
                            "fsmap": self.mdsmon.map.status(),
                            # per-PG progress bars with rate + ETA (mgr
                            # progress module via the PGMap digest) —
                            # the `ceph -s` progress block analog
                            "progress": self.pg_digest.get(
                                "progress", {}
                            ),
                            # per-pool IO rates / windowed p99 + top
                            # clients (mgr iostat module, ISSUE 10) —
                            # who is driving the load, from `status`
                            "iostat": self.pg_digest.get("iostat", {}),
                            # per-pool SLO burn-rate slice (the health
                            # check's evidence, machine-readable)
                            "slo": self.pg_digest.get("slo", {}),
                            # trend-sentinel slice + history store
                            # meta-stats (mgr metrics-history module,
                            # ISSUE 14) — the sentinel evidence,
                            # machine-readable from `status`
                            "history": self.pg_digest.get("history", {}),
                            # cluster-log tail (ISSUE 16): the last few
                            # committed entries, `ceph -s`'s recent-
                            # events block
                            "log": list(self.logmon.entries)[-10:],
                        }
                    ).encode(),
                )
            return handler
        return None
