"""Monitor elections — mirror of src/mon/ElectionLogic.cc / Elector.cc.

Classic rank-based election: every electing mon PROPOSEs itself; a mon
seeing a proposal from a lower (better) rank ACKs and defers; the proposer
declares VICTORY once every *reachable* peer has acked (or the election
timeout passes with a majority), then leads with the acked quorum.  Epochs
are bumped on every election so stale messages are discarded; like the
reference, an even epoch means "in election", odd means "stable quorum"
(ElectionLogic.h epoch semantics).
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..common.log import dout
from ..msg.messages import MMonElection


class Elector:
    """One per monitor; drives MMonElection traffic."""

    def __init__(
        self,
        rank: int,
        n_mons: int,
        send: Callable[[int, MMonElection], None],
        on_win: Callable[[int, list[int]], None],
        on_lose: Callable[[int, int, list[int]], None],
        timeout: float = 0.5,
    ):
        self.rank = rank
        self.n_mons = n_mons
        self.send = send
        self.on_win = on_win  # (epoch, quorum ranks)
        self.on_lose = on_lose  # (epoch, leader rank, quorum ranks)
        self.timeout = timeout
        self.epoch = 1  # odd = stable, even = electing
        self.electing = False
        self.acked: set[int] = set()
        self.leader: int | None = None
        self.deferred: int | None = None  # better candidate we acked
        self._timer: asyncio.Task | None = None

    def quorum_size(self) -> int:
        return self.n_mons // 2 + 1

    # -- driving --------------------------------------------------------------

    def start(self) -> None:
        """Call an election (Elector::call_election)."""
        if self.epoch % 2 == 1:
            self.epoch += 1  # enter electing epoch
        self.electing = True
        self.leader = None
        self.deferred = None
        self.acked = {self.rank}
        dout("mon", 10, f"mon.{self.rank} starting election epoch {self.epoch}")
        for r in range(self.n_mons):
            if r != self.rank:
                self.send(
                    r,
                    MMonElection(
                        op=MMonElection.OP_PROPOSE, epoch=self.epoch, rank=self.rank
                    ),
                )
        self._arm_timer()
        self._maybe_win()

    def _arm_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()

        async def expire():
            await asyncio.sleep(self.timeout)
            # timeout: a still-standing candidate wins with a majority;
            # anyone else (including a mon whose deferred candidate went
            # silent) restarts the election
            if self.electing:
                if self.deferred is None and len(self.acked) >= self.quorum_size():
                    self._declare_victory()
                else:
                    self.start()

        self._timer = asyncio.get_event_loop().create_task(expire())

    def cancel(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    # -- message handling ------------------------------------------------------

    def handle(self, msg: MMonElection) -> None:
        if msg.op == MMonElection.OP_PROPOSE:
            self._handle_propose(msg)
        elif msg.op == MMonElection.OP_ACK:
            self._handle_ack(msg)
        elif msg.op == MMonElection.OP_VICTORY:
            self._handle_victory(msg)

    def _handle_propose(self, msg: MMonElection) -> None:
        adopted = False
        if msg.epoch > self.epoch:
            # new election round: stale deferrals (e.g. to a dead leader)
            # don't carry over
            self.epoch = msg.epoch
            self.electing = True
            self.acked = {self.rank}
            self.deferred = None
            adopted = True
        if msg.rank < self.rank:
            if self.deferred is not None and self.deferred <= msg.rank:
                return  # already deferred to an equal-or-better candidate
            # better candidate: defer (ack) and drop our own candidacy —
            # ElectionLogic::defer; acking at most one candidate per epoch
            # keeps two candidates from both assembling a majority
            self.electing = True
            self.deferred = msg.rank
            self.acked.clear()
            self.send(
                msg.rank,
                MMonElection(op=MMonElection.OP_ACK, epoch=self.epoch, rank=self.rank),
            )
            self._arm_timer()
        else:
            # we outrank them: (re)launch our own full candidacy — start()
            # broadcasts to everyone and arms the timeout so the
            # majority-at-timeout victory path works even when we entered
            # the round via someone else's proposal
            if not self.electing or adopted:
                self.start()
            else:
                self.send(
                    msg.rank,
                    MMonElection(
                        op=MMonElection.OP_PROPOSE, epoch=self.epoch, rank=self.rank
                    ),
                )

    def _handle_ack(self, msg: MMonElection) -> None:
        if msg.epoch != self.epoch or not self.electing or self.deferred is not None:
            return
        self.acked.add(msg.rank)
        self._maybe_win()

    def _maybe_win(self) -> None:
        # Immediate victory once everyone acked; majority waits for timeout
        # so slower peers can still join the quorum.
        if self.deferred is None and len(self.acked) == self.n_mons:
            self._declare_victory()

    def _declare_victory(self) -> None:
        self.cancel()
        self.electing = False
        self.epoch += 1  # stable (odd) epoch
        self.leader = self.rank
        quorum = sorted(self.acked)
        dout("mon", 5, f"mon.{self.rank} wins election epoch {self.epoch} quorum {quorum}")
        for r in quorum:
            if r != self.rank:
                self.send(
                    r,
                    MMonElection(
                        op=MMonElection.OP_VICTORY, epoch=self.epoch,
                        rank=self.rank, quorum=quorum
                    ),
                )
        self.on_win(self.epoch, quorum)

    def _handle_victory(self, msg: MMonElection) -> None:
        if msg.epoch < self.epoch:
            return
        self.cancel()
        self.epoch = msg.epoch
        self.electing = False
        self.leader = msg.rank
        self.deferred = None
        dout("mon", 5, f"mon.{self.rank} defers to leader mon.{msg.rank}")
        self.on_lose(self.epoch, msg.rank, list(msg.quorum))
