"""ConfigMonitor — the PaxosService owning the central config DB.

Mirror of src/mon/ConfigMonitor.{h,cc}: `ceph config set/rm/get/dump`
mutate a versioned key store through Paxos, and every daemon that
subscribes to "config" receives the subset relevant to it, resolved with
the reference's layering (global < daemon-type section < named daemon,
ConfigMonitor::load_config building per-entity maps).  Daemons apply the
pushed values to their runtime Config, so a `config set osd
osd_max_backfills 3` takes effect cluster-wide without restarts — the
push lands on the same observer path a local `set` uses
(common/config.py, md_config_t::apply_changes in the reference).

State is small (a few hundred options), so commits carry the full
section store rather than incrementals — same trade MgrMonitor makes.
"""

from __future__ import annotations

import json

from ..common.errs import EINVAL
from ..common.log import dout
from ..common.options import OPTIONS
from ..msg.messages import MConfig
from .paxos_service import ProposalQueue


class ConfigMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.version = 0
        # section -> {option: raw value}; sections are "global", a daemon
        # type ("osd", "mon", "client", "mgr"), or a named daemon ("osd.3").
        self.sections: dict[str, dict[str, str]] = {}
        self._props = ProposalQueue(mon, "config")

    def on_election_changed(self) -> None:
        self._props.reset()

    # -- entity resolution -----------------------------------------------------

    def config_for(self, entity: str) -> dict[str, str]:
        """Layered view for one entity (ConfigMonitor's per-daemon map):
        global < type section < named section, later layers winning."""
        layers = ["global"]
        if "." in entity:
            layers.append(entity.split(".", 1)[0])
        layers.append(entity)
        out: dict[str, str] = {}
        for sec in layers:
            out.update(self.sections.get(sec, {}))
        return out

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        handlers = {
            "config set": (self._cmd_set, True),
            "config rm": (self._cmd_rm, True),
            "config get": (self._cmd_get, False),
            "config dump": (self._cmd_dump, False),
        }
        entry = handlers.get(prefix)
        if entry is None:
            return None
        fn, mutating = entry
        fn.__func__.mutating = mutating
        return fn

    def _cmd_set(self, cmd, reply) -> None:
        who, name, value = cmd["who"], cmd["name"], str(cmd["value"])
        # Reject unknown options and type-invalid values at the command, the
        # reference's behavior (ConfigMonitor::prepare_command validates via
        # the option schema) — a committed typo that every daemon silently
        # skips would look applied while doing nothing.
        opt = OPTIONS.get(name)
        if opt is None:
            reply(-EINVAL, f"unrecognized config option '{name}'")
            return
        try:
            opt.parse(value)
        except (ValueError, TypeError) as e:
            reply(-EINVAL, f"invalid value for '{name}': {e}")
            return

        def mutate(sections):
            sec = dict(sections.get(who, {}))
            if sec.get(name) == value:
                return None
            sec[name] = value
            out = dict(sections)
            out[who] = sec
            return out

        self._queue(mutate, lambda v: reply(0, f"set {who}/{name}"))

    def _cmd_rm(self, cmd, reply) -> None:
        who, name = cmd["who"], cmd["name"]

        def mutate(sections):
            if name not in sections.get(who, {}):
                return None
            sec = dict(sections[who])
            del sec[name]
            out = dict(sections)
            if sec:
                out[who] = sec
            else:
                del out[who]
            return out

        self._queue(mutate, lambda v: reply(0, f"rm {who}/{name}"))

    def _cmd_get(self, cmd, reply) -> None:
        reply(0, "", json.dumps(self.config_for(cmd["who"])).encode())

    def _cmd_dump(self, cmd, reply) -> None:
        reply(
            0,
            "",
            json.dumps({"version": self.version, "sections": self.sections}).encode(),
        )

    # -- paxos -----------------------------------------------------------------

    def _queue(self, mutate, on_committed=None) -> None:
        def make_blob():
            new_sections = mutate(self.sections)
            if new_sections is None:
                return None
            return json.dumps(
                {"version": self.version + 1, "sections": new_sections}
            ).encode()

        self._props.queue(make_blob, on_committed)

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        self.version = info["version"]
        self.sections = {s: dict(kv) for s, kv in info["sections"].items()}
        dout("mon", 10, f"config v{self.version}: {len(self.sections)} sections")
        self.mon.publish_config()

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        """Push this entity's resolved config (MConfig) when it is behind.
        Entities are identified by the connection's hello name, e.g.
        "osd.3" (ConfigMonitor::check_sub)."""
        if self.version == 0 or subs.get("config", 0) > self.version:
            return
        subs["config"] = self.version + 1
        changes = self.config_for(conn.peer_name)
        self.mon.send_to_conn(
            conn,
            MConfig(version=self.version, changes=json.dumps(changes).encode()),
        )
