"""Paxos — the mon's replicated transaction log (src/mon/Paxos.{h,cc}).

The reference runs classic multi-Paxos with a single active proposer (the
elected leader): after each election the leader runs a COLLECT round (new
proposal number; peons report their last_committed and any accepted-but-
uncommitted value, which the leader must re-drive); values then flow
BEGIN -> ACCEPT (majority) -> COMMIT, one in flight at a time
(Paxos.h:174 state machine).  Peon reads are served under a leader lease in
the reference; here reads are simply forwarded to the leader, which is the
same consistency with one hop more latency.

State lives in a small dict store (the mon's KV analog): accepted_pn,
last_committed, and the committed value log keyed by version.
"""

from __future__ import annotations

from typing import Callable

from ..common.log import dout
from ..msg.messages import MMonPaxos


class Paxos:
    def __init__(
        self,
        rank: int,
        send: Callable[[int, MMonPaxos], None],
        on_commit: Callable[[int, bytes], None],
    ):
        self.rank = rank
        self.send = send
        self.on_commit = on_commit  # (version, value) applied in order
        self.store: dict[int, bytes] = {}  # version -> committed value
        self.last_committed = 0
        self.accepted_pn = 0
        self.quorum: list[int] = [rank]
        self.leading = True
        # leader proposal state
        self._collecting = False
        self._collect_acks: set[int] = set()
        self._uncommitted: tuple[int, int, bytes] | None = None  # (pn, v, value)
        self._pending: list[tuple[bytes, Callable[[int], None] | None]] = []
        self._active_value: tuple[int, bytes, Callable[[int], None] | None] | None = None
        self._accept_acks: set[int] = set()
        # peon state
        self._peon_uncommitted: tuple[int, int, bytes] | None = None

    # -- election hooks --------------------------------------------------------

    def leader_init(self, quorum: list[int]) -> None:
        """Election won: run the collect phase (Paxos::leader_init)."""
        self.quorum = quorum
        self.leading = True
        self._active_value = None
        self._accept_acks = set()
        if len(quorum) == 1:
            self._collecting = False
            self._drive_pending()
            return
        self._collecting = True
        self._collect_acks = {self.rank}
        self.accepted_pn = self._new_pn()
        self._uncommitted = None
        for r in self.quorum:
            if r != self.rank:
                self.send(
                    r,
                    MMonPaxos(
                        op=MMonPaxos.OP_COLLECT,
                        pn=self.accepted_pn,
                        last_committed=self.last_committed,
                        values={},
                    ),
                )

    def peon_init(self, leader: int) -> None:
        self.leading = False
        self._collecting = False
        self._pending.clear()
        self._active_value = None

    def _new_pn(self) -> int:
        # proposal numbers namespaced by rank (Paxos::get_new_proposal_number)
        base = max(self.accepted_pn, 0) // 100 + 1
        return base * 100 + self.rank

    # -- client surface --------------------------------------------------------

    def propose(self, value: bytes, on_done: Callable[[int], None] | None = None) -> None:
        """Queue a transaction; leader-only (services check is_leader)."""
        assert self.leading, "propose on a peon"
        self._pending.append((value, on_done))
        self._drive_pending()

    def is_writeable(self) -> bool:
        return self.leading and not self._collecting and self._active_value is None

    def _drive_pending(self) -> None:
        if not self.is_writeable() or not self._pending:
            return
        value, on_done = self._pending.pop(0)
        v = self.last_committed + 1
        self._active_value = (v, value, on_done)
        self._accept_acks = {self.rank}
        for r in self.quorum:
            if r != self.rank:
                self.send(
                    r,
                    MMonPaxos(
                        op=MMonPaxos.OP_BEGIN,
                        pn=self.accepted_pn,
                        last_committed=self.last_committed,
                        values={v: value},
                    ),
                )
        self._check_accepted()

    # -- message handling ------------------------------------------------------

    def handle(self, msg: MMonPaxos, from_rank: int) -> None:
        op = msg.op
        if op == MMonPaxos.OP_COLLECT:
            self._handle_collect(msg, from_rank)
        elif op == MMonPaxos.OP_LAST:
            self._handle_last(msg, from_rank)
        elif op == MMonPaxos.OP_BEGIN:
            self._handle_begin(msg, from_rank)
        elif op == MMonPaxos.OP_ACCEPT:
            self._handle_accept(msg, from_rank)
        elif op == MMonPaxos.OP_COMMIT:
            self._handle_commit(msg, from_rank)

    # peon: collect -> LAST (report state, adopt pn)
    def _handle_collect(self, msg: MMonPaxos, from_rank: int) -> None:
        if msg.pn < self.accepted_pn:
            return  # stale proposer
        self.accepted_pn = msg.pn
        values: dict[int, bytes] = {}
        # share commits the leader is missing (Paxos::share_state)
        for v in range(msg.last_committed + 1, self.last_committed + 1):
            if v in self.store:
                values[v] = self.store[v]
        uncommitted_pn = 0
        if self._peon_uncommitted is not None:
            pn, v, val = self._peon_uncommitted
            if v == self.last_committed + 1:
                values[v] = val
                uncommitted_pn = pn
        self.send(
            from_rank,
            MMonPaxos(
                op=MMonPaxos.OP_LAST,
                pn=msg.pn,
                last_committed=self.last_committed,
                values=values,
                uncommitted_pn=uncommitted_pn,
            ),
        )

    # leader: gather LASTs (collect acks AND lagging-peon catch-up requests)
    def _handle_last(self, msg: MMonPaxos, from_rank: int) -> None:
        if not self.leading or msg.pn != self.accepted_pn:
            return
        # Adopt only the peon's COMMITTED values (v <= its last_committed);
        # an accepted-but-uncommitted value (slot last_committed+1) was
        # possibly never chosen and MUST be re-proposed through a full
        # round, never committed directly (Paxos::handle_last's
        # uncommitted_v handling).
        for v in sorted(msg.values):
            if v > self.last_committed and v <= msg.last_committed:
                self._commit_value(v, msg.values[v])
        # share commits the peon is missing (Paxos::share_state)
        self._handle_last_catchup(from_rank, msg.last_committed)
        if not self._collecting:
            return
        uncommitted_v = msg.last_committed + 1
        if uncommitted_v in msg.values and uncommitted_v > self.last_committed:
            # keep the value accepted under the highest pn (Paxos invariant)
            if self._uncommitted is None or msg.uncommitted_pn > self._uncommitted[0]:
                self._uncommitted = (
                    msg.uncommitted_pn,
                    uncommitted_v,
                    msg.values[uncommitted_v],
                )
        self._collect_acks.add(from_rank)
        if len(self._collect_acks) >= len(self.quorum):
            self._collecting = False
            if self._uncommitted is not None:
                _pn, v, value = self._uncommitted
                self._uncommitted = None
                # re-propose only if the slot wasn't committed meanwhile
                if v > self.last_committed:
                    self._pending.insert(0, (value, None))
            dout("mon", 10, f"paxos.{self.rank} collect done at v{self.last_committed}")
            self._drive_pending()

    # peon: begin -> accept
    def _handle_begin(self, msg: MMonPaxos, from_rank: int) -> None:
        if msg.pn < self.accepted_pn:
            return
        self.accepted_pn = msg.pn
        (v, value), = msg.values.items()
        # catch up any commits implied by the leader's last_committed
        if msg.last_committed > self.last_committed:
            # we're behind and can't apply a value out of order; ask via LAST
            self.send(
                from_rank,
                MMonPaxos(
                    op=MMonPaxos.OP_LAST,
                    pn=msg.pn,
                    last_committed=self.last_committed,
                    values={},
                ),
            )
            return
        self._peon_uncommitted = (msg.pn, v, value)
        self.send(
            from_rank,
            MMonPaxos(
                op=MMonPaxos.OP_ACCEPT,
                pn=msg.pn,
                last_committed=self.last_committed,
                values={},
            ),
        )

    # leader: gather accepts -> commit
    def _handle_accept(self, msg: MMonPaxos, from_rank: int) -> None:
        if not self.leading or msg.pn != self.accepted_pn or self._active_value is None:
            return
        self._accept_acks.add(from_rank)
        self._check_accepted()

    def _handle_last_catchup(self, from_rank: int, their_lc: int) -> None:
        values = {
            v: self.store[v]
            for v in range(their_lc + 1, self.last_committed + 1)
            if v in self.store
        }
        if values:
            self.send(
                from_rank,
                MMonPaxos(
                    op=MMonPaxos.OP_COMMIT,
                    pn=self.accepted_pn,
                    last_committed=self.last_committed,
                    values=values,
                ),
            )

    def _check_accepted(self) -> None:
        if self._active_value is None:
            return
        majority = len(self.quorum) // 2 + 1
        if len(self._accept_acks) < majority:
            return
        v, value, on_done = self._active_value
        self._active_value = None
        self._commit_value(v, value)
        for r in self.quorum:
            if r != self.rank:
                self.send(
                    r,
                    MMonPaxos(
                        op=MMonPaxos.OP_COMMIT,
                        pn=self.accepted_pn,
                        last_committed=self.last_committed,
                        values={v: value},
                    ),
                )
        if on_done is not None:
            on_done(v)
        self._drive_pending()

    # peon: commit
    def _handle_commit(self, msg: MMonPaxos, from_rank: int) -> None:
        for v in sorted(msg.values):
            if v == self.last_committed + 1:
                self._commit_value(v, msg.values[v])
        self._peon_uncommitted = None
        if self.last_committed < msg.last_committed:
            # still behind: ask the leader for the gap
            self.send(
                from_rank,
                MMonPaxos(
                    op=MMonPaxos.OP_LAST,
                    pn=self.accepted_pn,
                    last_committed=self.last_committed,
                    values={},
                ),
            )

    def _commit_value(self, v: int, value: bytes) -> None:
        assert v == self.last_committed + 1, (v, self.last_committed)
        self.store[v] = value
        self.last_committed = v
        self.on_commit(v, value)
