"""MonClient — mirror of src/mon/MonClient.{h,cc}.

Hunts for a usable monitor, issues commands (retargeting to the leader on
-EAGAIN, the analog of the reference's request forwarding), maintains
subscriptions, and delivers map updates to its owner (OSD daemon or
librados client).
"""

from __future__ import annotations

import asyncio
import json
import re
from typing import Callable

from ..common.log import dout
from ..msg.messages import (
    MConfig,
    MLog,
    MMonCommand,
    MMonCommandAck,
    MMonSubscribe,
    MOSDMap,
)
from ..msg.messenger import Connection, Dispatcher, Messenger
from .monmap import MonMap
from ..common.errs import EAGAIN, ETIMEDOUT


class MonClient(Dispatcher):
    def __init__(
        self,
        name: str,
        monmap: MonMap,
        msgr: Messenger | None = None,
        stack: str = "posix",  # ms_type for the fallback messenger
    ):
        self.name = name
        self.monmap = monmap
        self.msgr = msgr or Messenger(name, stack=stack)
        self.msgr.add_dispatcher_tail(self)
        self._tid = 0
        self._acks: dict[int, asyncio.Future] = {}
        self.on_osdmap: Callable[[MOSDMap], None] | None = None
        self.on_config: Callable[[MConfig], None] | None = None
        self.on_log: Callable[[MLog], None] | None = None
        self._cur_rank = 0  # mon we're currently talking to
        self._subs: dict[str, int] = {}

    # -- dispatch --------------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MMonCommandAck):
            fut = self._acks.pop(msg.tid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, MOSDMap):
            if self.on_osdmap is not None:
                self.on_osdmap(msg)
            return True
        if isinstance(msg, MConfig):
            if self.on_config is not None:
                self.on_config(msg)
            return True
        if isinstance(msg, MLog):
            if self.on_log is not None:
                self.on_log(msg)
            return True
        return False

    # -- commands --------------------------------------------------------------

    async def command(
        self, cmd: dict, timeout: float = 5.0
    ) -> tuple[int, str, bytes]:
        """Send a JSON command, hunting for the leader (MonClient::
        start_mon_command + the -EAGAIN retarget loop)."""
        deadline = asyncio.get_event_loop().time() + timeout
        rank = self._cur_rank
        attempts = 0
        while True:
            if asyncio.get_event_loop().time() > deadline:
                return (-ETIMEDOUT, "timed out waiting for mon", b"")
            self._tid += 1
            tid = self._tid
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._acks[tid] = fut
            addr = self.monmap.addr_of_rank(rank % self.monmap.size())
            try:
                await self.msgr.send_to(addr, MMonCommand(tid=tid, cmd=json.dumps(cmd)))
                ack: MMonCommandAck = await asyncio.wait_for(
                    fut, max(deadline - asyncio.get_event_loop().time(), 0.05)
                )
            except (ConnectionError, asyncio.TimeoutError):
                self._acks.pop(tid, None)
                rank += 1  # hunt the next mon
                attempts += 1
                await asyncio.sleep(min(0.05 * attempts, 0.5))
                continue
            if ack.retval == -EAGAIN:
                m = re.search(r"leader is rank (-?\d+)", ack.rs)
                new_rank = int(m.group(1)) if m else -1
                if new_rank < 0:
                    await asyncio.sleep(0.05)
                else:
                    rank = new_rank
                continue
            self._cur_rank = rank % self.monmap.size()
            return (ack.retval, ack.rs, ack.outbl)

    # -- subscriptions ---------------------------------------------------------

    async def subscribe(self, what: str, start: int = 0) -> None:
        """Register interest (MonClient::sub_want + renew)."""
        self._subs[what] = start
        addr = self.monmap.addr_of_rank(self._cur_rank)
        try:
            await self.msgr.send_to(addr, MMonSubscribe(what=dict(self._subs)))
        except ConnectionError:
            dout("monc", 5, f"{self.name}: subscribe to {addr} failed")

    # -- cluster log -----------------------------------------------------------

    async def send_log(self, entries: list[dict]) -> None:
        """Ship clog entries to the current mon (LogClient::_send_to_mon);
        a peon forwards them to the leader.  Best-effort: a lost entry is
        re-reported by the next scrub, so no retry queue."""
        addr = self.monmap.addr_of_rank(self._cur_rank)
        try:
            await self.msgr.send_to(
                addr, MLog(version=0, entries=json.dumps(entries).encode())
            )
        except ConnectionError:
            dout("monc", 5, f"{self.name}: clog send to {addr} failed")

    async def resubscribe(self, rank: int | None = None) -> None:
        """Re-send subscriptions after a mon connection reset."""
        if rank is not None:
            self._cur_rank = rank % self.monmap.size()
        if self._subs:
            addr = self.monmap.addr_of_rank(self._cur_rank)
            await self.msgr.send_to(addr, MMonSubscribe(what=dict(self._subs)))
