"""AuthMonitor — the PaxosService owning the cluster keyring.

Mirror of src/mon/AuthMonitor.{h,cc}: entity keys (`client.admin`,
`osd.0`, ...) are created, fetched, and deleted through mon commands and
replicated to every quorum member through Paxos, so any monitor can
authenticate a cephx handshake (auth/cephx.py) against the same
authoritative keyring.  `auth get-or-create` replies only after its
proposal commits — key material never reaches a client before the quorum
has durably agreed on it (AuthMonitor::prepare_command's wait-for-commit).

The keyring snapshot rides each commit in the reference's own plaintext
format (KeyRing::encode_plaintext; auth/keyring.py) — small, and keeps
peons byte-identical.
"""

from __future__ import annotations

import base64
import json

from ..auth.keyring import KeyRing, generate_secret
from ..common.log import dout
from .paxos_service import ProposalQueue


class AuthMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.version = 0
        self.keyring = KeyRing()
        self._props = ProposalQueue(mon, "auth")

    def on_election_changed(self) -> None:
        self._props.reset()

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        handlers = {
            "auth add": (self._cmd_add, True),
            "auth get-or-create": (self._cmd_get_or_create, True),
            "auth del": (self._cmd_del, True),
            "auth get": (self._cmd_get, False),
            "auth ls": (self._cmd_ls, False),
        }
        entry = handlers.get(prefix)
        if entry is None:
            return None
        fn, mutating = entry
        fn.__func__.mutating = mutating
        return fn

    def _cmd_add(self, cmd, reply) -> None:
        entity = cmd["entity"]
        if entity in self.keyring:
            reply(-17, f"entity {entity} exists")  # EEXIST
            return
        secret = (
            base64.b64decode(cmd["key"]) if "key" in cmd else generate_secret()
        )

        def mutate(kr: KeyRing):
            if entity in kr:
                return None
            out = KeyRing.loads(kr.dumps())
            out.add(entity, secret)
            return out

        self._queue(mutate, lambda v: reply(0, f"added key for {entity}"))

    def _cmd_get_or_create(self, cmd, reply) -> None:
        entity = cmd["entity"]
        existing = self.keyring.get(entity)
        if existing is not None:
            reply(0, "", self._entity_blob(entity, existing))
            return
        secret = generate_secret()

        def mutate(kr: KeyRing):
            if entity in kr:
                return None
            out = KeyRing.loads(kr.dumps())
            out.add(entity, secret)
            return out

        def on_committed(_v: int) -> None:
            # Another racing proposal may have created the key first;
            # reply with whatever the committed keyring actually holds.
            key = self.keyring.get(entity) or secret
            reply(0, "", self._entity_blob(entity, key))

        self._queue(mutate, on_committed)

    def _cmd_del(self, cmd, reply) -> None:
        entity = cmd["entity"]

        def mutate(kr: KeyRing):
            if entity not in kr:
                return None
            out = KeyRing.loads(kr.dumps())
            out.remove(entity)
            return out

        self._queue(mutate, lambda v: reply(0, f"deleted {entity}"))

    def _cmd_get(self, cmd, reply) -> None:
        entity = cmd["entity"]
        key = self.keyring.get(entity)
        if key is None:
            reply(-2, f"no key for {entity}")  # ENOENT
            return
        reply(0, "", self._entity_blob(entity, key))

    def _cmd_ls(self, cmd, reply) -> None:
        reply(0, "", json.dumps(self.keyring.entities()).encode())

    @staticmethod
    def _entity_blob(entity: str, key: bytes) -> bytes:
        return json.dumps(
            {"entity": entity, "key": base64.b64encode(key).decode()}
        ).encode()

    # -- paxos -----------------------------------------------------------------

    def _queue(self, mutate, on_committed=None) -> None:
        def make_blob():
            new_kr = mutate(self.keyring)
            if new_kr is None:
                return None
            return json.dumps(
                {"version": self.version + 1, "keyring": new_kr.dumps()}
            ).encode()

        self._props.queue(make_blob, on_committed)

    def apply_commit(self, blob: bytes) -> None:
        info = json.loads(blob.decode())
        self.version = info["version"]
        self.keyring = KeyRing.loads(info["keyring"])
        dout("mon", 10, f"auth v{self.version}: {len(self.keyring)} entities")
