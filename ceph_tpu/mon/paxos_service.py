"""Shared proposal queue for Paxos services.

The analog of PaxosService::propose_pending (src/mon/PaxosService.{h,cc}):
every service (mgr, config, log, auth) funnels mutations through one
in-flight proposal at a time, with each mutation's blob computed against
the *committed* state at propose time — so concurrent mutations cannot
race to the same version and drop each other's updates.
"""

from __future__ import annotations

from typing import Callable

# make_blob() -> serialized proposal bytes, or None when the mutation is a
# no-op against the now-committed state (already true / already absent).
MakeBlob = Callable[[], "bytes | None"]
OnCommitted = Callable[[int], None]


class ProposalQueue:
    def __init__(self, mon, service: str):
        self.mon = mon
        self.service = service
        self._pending: list[tuple[MakeBlob, OnCommitted | None]] = []
        self._proposing = False

    def reset(self) -> None:
        """On election change: drop queued mutations (clients retry against
        the new leader; PaxosService::election_finished)."""
        self._proposing = False
        self._pending.clear()

    def queue(self, make_blob: MakeBlob, on_committed: OnCommitted | None = None) -> None:
        self._pending.append((make_blob, on_committed))
        self.kick()

    def kick(self) -> None:
        if self._proposing or not self._pending or not self.mon.is_leader():
            return
        make_blob, on_committed = self._pending.pop(0)
        blob = make_blob()
        if blob is None:
            if on_committed is not None:
                on_committed(-1)  # no-op: already true in committed state
            self.kick()
            return
        self._proposing = True

        def on_done(version: int) -> None:
            self._proposing = False
            if on_committed is not None:
                on_committed(version)
            self.kick()

        self.mon.propose(self.service, blob, on_done)
