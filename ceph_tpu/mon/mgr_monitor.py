"""MgrMonitor — the PaxosService owning the MgrMap (src/mon/MgrMonitor.cc).

Mirrored behaviors:
- Mgr daemons announce themselves with beacons (MMgrBeacon →
  MgrMonitor::prepare_beacon); the first becomes **active**, later ones
  queue as **standbys**.
- A missed beacon window fails over to a standby
  (`mon_mgr_beacon_grace`, MgrMonitor::tick), bumping the map epoch.
- The map publishes to "mgrmap" subscribers so daemons know where to
  send their MMgrReports (check_sub).
"""

from __future__ import annotations

import time

from ..common.log import dout
from ..msg.messages import MMgrBeacon, MMgrMap
from .paxos_service import ProposalQueue

BEACON_GRACE = 6.0  # mon_mgr_beacon_grace (scaled down)


class MgrMap:
    def __init__(self) -> None:
        self.epoch = 0
        self.active_name = ""
        self.active_addr = ""
        self.standbys: dict[str, str] = {}  # name -> addr

    def to_msg(self) -> MMgrMap:
        return MMgrMap(
            epoch=self.epoch,
            active_name=self.active_name,
            active_addr=self.active_addr,
            standbys=sorted(self.standbys),
        )


class MgrMonitor:
    def __init__(self, mon):
        self.mon = mon
        self.map = MgrMap()
        self._last_beacon: dict[str, float] = {}
        # One proposal in flight at a time, each mutation computed against
        # the committed map at propose time (PaxosService::propose_pending)
        # — concurrent beacons must not race to the same epoch and drop
        # each other's updates.
        self._props = ProposalQueue(mon, "mgr")

    def _clog(self, prio: str, msg: str) -> None:
        """Cluster-log a lifecycle transition; unit harnesses drive this
        service with a bare mon stub that has no LogMonitor."""
        logmon = getattr(self.mon, "logmon", None)
        if logmon is not None:
            logmon.log(prio, f"mon.{self.mon.name}", msg)

    def on_election_changed(self) -> None:
        self._props.reset()
        # Re-baseline beacon timestamps: a newly elected leader has an empty
        # _last_beacon map, and tick() comparing against 0.0 would instantly
        # fail over a healthy active mgr.  Give every known daemon one full
        # grace period from election before judging it (the reference
        # re-baselines beacons on election, MgrMonitor.cc).
        now = time.monotonic()
        for name in [self.map.active_name, *self.map.standbys]:
            if name:
                self._last_beacon[name] = now

    # -- beacons ---------------------------------------------------------------

    def prepare_beacon(self, msg: MMgrBeacon) -> None:
        """Leader-only (MgrMonitor::prepare_beacon)."""
        self._last_beacon[msg.name] = time.monotonic()

        def mutate(m: MgrMap):
            if m.active_name == msg.name:
                if m.active_addr != msg.addr:
                    return (msg.name, msg.addr, m.standbys)
                return None
            if not m.active_name:
                standbys = dict(m.standbys)
                standbys.pop(msg.name, None)
                return (msg.name, msg.addr, standbys)
            if m.standbys.get(msg.name) != msg.addr:
                standbys = dict(m.standbys)
                standbys[msg.name] = msg.addr
                return (m.active_name, m.active_addr, standbys)
            return None

        self._queue(mutate)

    def tick(self) -> None:
        """Fail over when the active mgr stops beaconing
        (MgrMonitor::tick; driven by the monitor's periodic tick)."""
        if not self.mon.is_leader() or not self.map.active_name:
            return
        last = self._last_beacon.get(self.map.active_name, 0.0)
        if time.monotonic() - last <= BEACON_GRACE:
            return
        failed = self.map.active_name
        self._last_beacon.pop(failed, None)

        def mutate(m: MgrMap):
            if m.active_name != failed:
                return None  # someone else already took over
            standbys = dict(m.standbys)
            if standbys:
                name = sorted(standbys)[0]
                addr = standbys.pop(name)
                dout("mon", 1, f"mgr {failed} failed; promoting {name}")
                self._clog(
                    "warn",
                    f"mgr {failed} failed (no beacon); failing over to "
                    f"standby {name}",
                )
                return (name, addr, standbys)
            dout("mon", 1, f"mgr {failed} failed; no standby")
            self._clog(
                "warn",
                f"mgr {failed} failed (no beacon); no standby available",
            )
            return ("", "", {})

        self._queue(mutate)

    # -- paxos -----------------------------------------------------------------

    def _queue(self, mutate) -> None:
        import json

        def make_blob():
            result = mutate(self.map)
            if result is None:
                return None
            active_name, active_addr, standbys = result
            return json.dumps(
                {
                    "epoch": self.map.epoch + 1,
                    "active_name": active_name,
                    "active_addr": active_addr,
                    "standbys": standbys,
                }
            ).encode()

        self._props.queue(make_blob)

    def apply_commit(self, blob: bytes) -> None:
        import json

        info = json.loads(blob.decode())
        m = self.map
        m.epoch = info["epoch"]
        m.active_name = info["active_name"]
        m.active_addr = info["active_addr"]
        m.standbys = dict(info["standbys"])
        dout("mon", 10, f"mgrmap e{m.epoch}: active={m.active_name or '(none)'}")
        self.mon.publish_mgrmap()

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        if self.map.epoch == 0 or subs.get("mgrmap", 0) > self.map.epoch:
            return
        subs["mgrmap"] = self.map.epoch + 1
        self.mon.send_to_conn(conn, self.map.to_msg())
