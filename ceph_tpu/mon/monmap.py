"""MonMap — the static monitor roster (src/mon/MonMap.h).

Ranks are assigned by sorted address order exactly like the reference
(calc_ranks); the map rarely changes, so it is plain config here rather than
a Paxos-managed map.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class MonMap:
    addrs: dict[str, str] = field(default_factory=dict)  # name -> host:port

    @property
    def ranks(self) -> list[str]:
        """Names ordered by rank (sorted by address, MonMap::calc_ranks)."""
        return [name for _addr, name in sorted((a, n) for n, a in self.addrs.items())]

    def rank_of(self, name: str) -> int:
        return self.ranks.index(name)

    def addr_of_rank(self, rank: int) -> str:
        return self.addrs[self.ranks[rank]]

    def size(self) -> int:
        return len(self.addrs)

    def quorum_size(self) -> int:
        return self.size() // 2 + 1
