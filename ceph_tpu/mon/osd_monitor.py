"""OSDMonitor — the PaxosService owning the OSDMap (src/mon/OSDMonitor.cc).

Mirrored responsibilities:
- OSD lifecycle: boot marks up (prepare_boot), failure reports are
  quorum-checked before marking down (prepare_failure, OSDMonitor.cc:2791;
  `mon_osd_min_down_reporters`).
- EC profile CRUD: `osd erasure-code-profile set/get/ls/rm`
  (OSDMonitor.cc:6859-6915) with `normalize_profile` (:7416) instantiating
  the codec through the plugin registry to validate, and the
  `chunk_size == stripe_unit` check at pool create (:7437-7455,
  prepare_pool_stripe_width :7715).
- Pool create/rm with CRUSH rule creation (`indep` for EC,
  ErasureCode.cc:64-82).
- Map publication: every committed epoch is pushed to `osdmap` subscribers
  as an Incremental (full-map epochs for structural changes).

Mutations queue as closures against a scratch copy of the committed map and
ride ONE proposal at a time (the reference's pending_inc batching).
"""

from __future__ import annotations

import json
import time
from typing import Callable

from ..codec.interface import EcError
from ..common.errs import EAGAIN, EINVAL
from ..codec.registry import ErasureCodePluginRegistry
from ..common.log import dout
from ..msg.messages import MOSDBoot, MOSDFailure, MOSDMap
from ..osd.osdmap import (
    FLAG_EC_OVERWRITES,
    FLAG_FULL_QUOTA,
    Incremental,
    OSDMap,
    POOL_TYPE_ERASURE,
    POOL_TYPE_REPLICATED,
)

DEFAULT_STRIPE_UNIT = 4096


class OSDMonitor:
    def __init__(self, mon, min_down_reporters: int = 2):
        self.mon = mon
        self.osdmap = OSDMap()
        self.inc_by_epoch: dict[int, bytes] = {}
        # target -> {reporter: report time}; entries expire (prepare_failure)
        self.failure_reports: dict[int, dict[str, float]] = {}
        self.min_down_reporters = min_down_reporters
        self.report_expiry = 20.0  # seconds a failure report stays valid
        # down-and-in OSDs awaiting auto-out (mon_osd_down_out_interval)
        self._down_since: dict[int, float] = {}
        # flap dampening (ISSUE 15): per-OSD recent markdown stamps
        # (pruned to mon_osd_flap_window); the down->out grace grows
        # mon_osd_flap_backoff^(markdowns-1) so a flapping OSD stops
        # re-triggering full peering storms on every bounce
        self._recent_markdowns: dict[int, list[float]] = {}
        self.auto_outs_total = 0  # lifetime auto-out count (the sweep's)
        self.dampened_holds = 0   # sweep passes where dampening held fire
        # OSDs whose current down episode already clog'd a dampening
        # hold (one timeline entry per episode, not one per sweep tick)
        self._hold_logged: set[int] = set()
        # OSDs the sweep auto-outed: marked back IN on reboot (the
        # reference's mon_osd_auto_mark_auto_out_in), unlike an
        # operator's explicit `osd out` which sticks
        self._auto_outed: set[int] = set()
        # laggy (slow-but-alive) OSDs (ISSUE 17): target -> {reporter:
        # {at, rtt}} evidence from MOSDFailure(laggy=1) reports, plus
        # the episode's start stamp.  NON-FATAL: no osdmap mutation, no
        # auto-out — only the OSD_SLOW_PEER health warn and a clog
        # event per episode (set and clear, dampened like ISSUE 13's
        # markdown timeline: one entry per transition, never per report)
        self.laggy: dict[int, dict] = {}
        # seconds laggy evidence stays valid without a refresh: reports
        # re-send on the reporter's heartbeat-grace cadence, so 3x the
        # failure-report expiry forgives a couple of lost beacons while
        # still self-clearing if the reporter dies mid-episode
        self.laggy_report_expiry = 3 * self.report_expiry
        # queued mutations: (mutate(map) -> rs, reply or None)
        self._pending: list[tuple[Callable, Callable | None]] = []
        self._proposing = False

    def _clog(self, prio: str, msg: str, code: str | None = None) -> None:
        """Cluster-log a lifecycle transition; unit harnesses drive this
        service with a bare mon stub that has no LogMonitor."""
        logmon = getattr(self.mon, "logmon", None)
        if logmon is not None:
            logmon.log(prio, f"mon.{self.mon.name}", msg, code=code)

    # -- paxos plumbing --------------------------------------------------------

    def on_election_lost(self) -> None:
        """Became a peon: the in-flight proposal's on_done (if any) was
        dropped by paxos peon_init; queued mutations can't commit here, so
        their callers retry against the new leader."""
        self._proposing = False
        pending, self._pending = self._pending, []
        for _mutate, reply in pending:
            if reply is not None:
                reply(-EAGAIN, "lost leadership; retry")

    def on_active(self) -> None:
        """Leader became active; bootstrap the first map epoch."""
        self._proposing = False  # a pre-election in-flight on_done is gone
        if self.osdmap.epoch == 0:
            def init(m: OSDMap) -> str:
                m.fsid = "tpu-fsid"
                m.crush.add_bucket("default", "root")
                # seed the bootstrap EC profile from
                # osd_pool_default_erasure_code_profile so
                # `pool create ... erasure` works out of the box (the
                # option existed since PR 1 but was never read — the
                # ISSUE 12 config-coherence pass caught the drift)
                try:
                    raw = self.mon.conf.get(
                        "osd_pool_default_erasure_code_profile"
                    )
                    prof = dict(
                        kv.split("=", 1) for kv in str(raw).split() if "=" in kv
                    )
                    m.erasure_code_profiles["default"] = (
                        self._normalize_profile(prof)
                    )
                except Exception as e:
                    dout("mon", 1,
                         f"default EC profile unseedable: {e!r}")
                return "created initial map"

            self._queue(init, None)
        else:
            self._try_propose()

    def apply_commit(self, blob: bytes) -> None:
        """Applied on EVERY quorum member in commit order."""
        inc = Incremental.frombytes(blob)
        self.osdmap = inc.apply_to(self.osdmap)
        self.inc_by_epoch[self.osdmap.epoch] = blob
        dout("mon", 10, f"osdmap e{self.osdmap.epoch} committed")
        self.mon.publish_osdmap()

    def _queue(self, mutate: Callable, reply: Callable | None) -> None:
        self._pending.append((mutate, reply))
        self._try_propose()

    def _try_propose(self) -> None:
        if self._proposing or not self._pending or not self.mon.is_leader():
            return
        batch, self._pending = self._pending, []
        # scratch copy of the committed map (the pending_inc)
        scratch = OSDMap.frombytes(self.osdmap.tobytes())
        results: list[tuple[Callable | None, int, str]] = []
        for mutate, reply in batch:
            try:
                rs = mutate(scratch)
                results.append((reply, 0, rs or ""))
            except EcError as e:
                results.append((reply, e.errno, str(e)))
            except (KeyError, ValueError) as e:
                results.append((reply, -EINVAL, str(e)))
        scratch.epoch = self.osdmap.epoch + 1
        inc = Incremental(epoch=scratch.epoch, full_map=scratch.tobytes())
        self._proposing = True

        def on_done(_version: int) -> None:
            self._proposing = False
            for reply, retval, rs in results:
                if reply is not None:
                    reply(retval, rs)
            self._try_propose()

        self.mon.propose("osd", inc.tobytes(), on_done)

    # -- subscriptions ---------------------------------------------------------

    def check_sub(self, conn, subs: dict[str, int]) -> None:
        """Send epochs the subscriber is missing (check_osdmap_sub)."""
        start = subs.get("osdmap", 0)
        if self.osdmap.epoch == 0 or start > self.osdmap.epoch:
            return
        incs: dict[int, bytes] = {}
        maps: dict[int, bytes] = {}
        # Delta incrementals ride as-is; full-map-backed epochs collapse to
        # ONE latest full map (sending a full map per missed epoch would be
        # strictly worse than the maps path).
        pending = [
            self.inc_by_epoch.get(e) for e in range(max(start, 1), self.osdmap.epoch + 1)
        ]
        if (
            start == 0
            or any(p is None for p in pending)
            or any(Incremental.frombytes(p).full_map for p in pending)
        ):
            maps[self.osdmap.epoch] = self.osdmap.tobytes()
        else:
            for e in range(max(start, 1), self.osdmap.epoch + 1):
                incs[e] = self.inc_by_epoch[e]
        subs["osdmap"] = self.osdmap.epoch + 1
        self.mon.send_to_conn(
            conn, MOSDMap(fsid=self.osdmap.fsid, maps=maps, incrementals=incs)
        )

    # -- OSD lifecycle ---------------------------------------------------------

    def prepare_boot(self, msg: MOSDBoot) -> None:
        osd, addr = msg.osd, msg.addr
        info = self.osdmap.osds.get(osd)
        if info is not None and info.up and info.addr == addr:
            return  # duplicate boot

        def mutate(m: OSDMap) -> str:
            if osd not in m.osds:
                # grow the crush tree: one host per osd (the standalone
                # many-OSDs-one-host topology, qa/standalone/ceph-helpers.sh)
                host = m.crush.add_bucket(f"host{osd}", "host")
                m.crush.add_item(host, osd, 1.0)
                m.crush.add_item("default", host, 1.0)
                m.add_osd(osd, addr=addr, up=True)
            else:
                m.set_osd_state(osd, True, addr)
                if osd in self._auto_outed:
                    # the down-out sweep outed it, not an operator:
                    # a reboot marks it back in so its capacity returns
                    from ..crush.crush import WEIGHT_ONE

                    self._auto_outed.discard(osd)
                    if m.osds[osd].weight == 0:
                        m.set_osd_weight(osd, WEIGHT_ONE)
            self.failure_reports.pop(osd, None)
            return f"osd.{osd} boot"

        self._queue(mutate, None)
        # lifecycle timeline (ISSUE 16): boots, markdowns and auto-outs
        # all land in the cluster log, not just dout
        self._clog("info", f"osd.{osd} boot")

    def prepare_failure(self, msg: MOSDFailure, reporter: str) -> None:
        """Quorum-check failure reports (OSDMonitor.cc:2791, :3134).
        Reports expire after `report_expiry` seconds — a stale reporter
        from a long-past blip must not combine with a fresh one to mark
        a healthy OSD down (failure_info_t's report window)."""
        target = msg.target
        if getattr(msg, "laggy", 0):
            # laggy reports branch BEFORE the is_up gate: a laggy target
            # is by definition still up (it answers heartbeats — slowly)
            self._handle_laggy_report(msg, reporter)
            return
        if not self.osdmap.is_up(target):
            return
        now = time.monotonic()
        reporters = self.failure_reports.setdefault(target, {})
        reporters[reporter] = now
        for r, ts in list(reporters.items()):
            if now - ts > self.report_expiry:
                del reporters[r]
        if len(reporters) < self.min_down_reporters:
            dout(
                "mon", 10,
                f"osd.{target} failure: {len(reporters)}/{self.min_down_reporters} reporters",
            )
            return
        nrep = len(reporters)
        self.failure_reports.pop(target, None)
        # a quorum-confirmed death retires any laggy episode: dead beats
        # slow, and OSD_DOWN must not double-bill as OSD_SLOW_PEER
        self._laggy_retire(target, reason="marked down")
        self._note_markdown(target, now)

        def mutate(m: OSDMap) -> str:
            m.set_osd_state(target, False)
            return f"osd.{target} marked down"

        self._queue(mutate, None)
        self._clog(
            "warn", f"osd.{target} marked down ({nrep} reporters)",
            code="OSD_DOWN",
        )

    # -- flap dampening (ISSUE 15) --------------------------------------------

    def _note_markdown(self, osd: int, now: float) -> None:
        """Record one markdown event in the OSD's recent-flap history
        (pruned to the window on read)."""
        self._recent_markdowns.setdefault(osd, []).append(now)

    def _recent_markdown_count(self, osd: int, now: float) -> int:
        window = float(self.mon.conf.get("mon_osd_flap_window"))
        stamps = self._recent_markdowns.get(osd)
        if not stamps:
            return 0
        if window <= 0:
            # dampening off: report 0 but KEEP the (bounded) history so
            # a runtime re-enable resumes from live data instead of
            # forgiving an active flapper
            if len(stamps) > 16:
                self._recent_markdowns[osd] = stamps[-16:]
            return 0
        live = [t for t in stamps if now - t <= window]
        if live:
            self._recent_markdowns[osd] = live
        else:
            self._recent_markdowns.pop(osd, None)
        return len(live)

    def _down_out_grace(self, osd: int, now: float) -> float:
        """Effective down->out grace for `osd`: the base interval scaled
        by backoff^(recent markdowns - 1), exponent capped at 8.  A
        first-time failure uses the base interval unchanged; every
        additional markdown inside the flap window doubles (by default)
        the time the mon waits before remapping the OSD's data."""
        base = float(self.mon.conf.get("mon_osd_down_out_interval"))
        if base <= 0:
            return base
        n = self._recent_markdown_count(osd, now)
        if n <= 1:
            return base
        backoff = max(1.0, float(self.mon.conf.get("mon_osd_flap_backoff")))
        return base * backoff ** min(n - 1, 8)

    def flap_stats(self) -> dict:
        """Dampening introspection (chaos/tests and the asok surface):
        lifetime auto-out count plus each tracked OSD's recent markdown
        count and current effective grace."""
        now = time.monotonic()
        per_osd = {}
        for osd in sorted(self._recent_markdowns):
            n = self._recent_markdown_count(osd, now)
            if n:
                per_osd[osd] = {
                    "markdowns": n,
                    "grace_sec": round(self._down_out_grace(osd, now), 3),
                }
        return {
            "auto_outs_total": self.auto_outs_total,
            "dampened_holds": self.dampened_holds,
            "osds": per_osd,
        }

    # -- laggy (slow-but-alive) OSDs (ISSUE 17) -------------------------------

    def _handle_laggy_report(self, msg: MOSDFailure, reporter: str) -> None:
        """A peer reports the target LAGGY (laggy=1, failed_for carries
        the reporter's RTT EWMA) or recovered (laggy=2).  Pure health
        state: no osdmap mutation, no markdown, no auto-out — the
        target still serves I/O, just slowly.  One clog entry per
        episode edge (set/clear), never per report."""
        target = msg.target
        now = time.monotonic()
        if msg.laggy == 2:
            ent = self.laggy.get(target)
            if ent is None:
                return
            ent["reporters"].pop(reporter, None)
            if not ent["reporters"]:
                self._laggy_retire(target, reason="recovered")
            return
        if not self.osdmap.is_up(target):
            return  # dead beats laggy
        ent = self.laggy.setdefault(
            target, {"reporters": {}, "since": now, "new": True}
        )
        ent["reporters"][reporter] = {"at": now, "rtt": float(msg.failed_for)}
        self._prune_laggy(target, now)
        if ent.get("new") and target in self.laggy:
            ent["new"] = False
            rtt_ms = max(
                r["rtt"] for r in ent["reporters"].values()
            ) * 1000.0
            self._clog(
                "warn",
                f"osd.{target} reported laggy by {reporter} "
                f"(rtt ewma {rtt_ms:.0f} ms): heartbeats answer but "
                "service is slow",
                code="OSD_SLOW_PEER",
            )

    def _prune_laggy(self, target: int, now: float) -> None:
        """Expire stale laggy evidence; retire the episode when the last
        reporter ages out (a reporter that died mid-episode must not
        pin a recovered OSD in OSD_SLOW_PEER forever)."""
        ent = self.laggy.get(target)
        if ent is None:
            return
        for r, info in list(ent["reporters"].items()):
            if now - info["at"] > self.laggy_report_expiry:
                del ent["reporters"][r]
        if not ent["reporters"]:
            self._laggy_retire(target, reason="reports expired")

    def _laggy_retire(self, target: int, reason: str) -> None:
        ent = self.laggy.pop(target, None)
        if ent is None or ent.get("new"):
            return  # never surfaced: no clear entry for an unlogged set
        self._clog(
            "info", f"osd.{target} no longer laggy ({reason})",
            code="OSD_SLOW_PEER",
        )

    def slow_peers(self) -> dict[int, dict]:
        """Current laggy OSDs for the health surface: target -> episode
        summary (reporters, worst reported RTT EWMA, age)."""
        now = time.monotonic()
        for target in list(self.laggy):
            self._prune_laggy(target, now)
        out: dict[int, dict] = {}
        for target, ent in self.laggy.items():
            out[target] = {
                "reporters": sorted(ent["reporters"]),
                "rtt_ms": round(
                    max(r["rtt"] for r in ent["reporters"].values()) * 1000.0,
                    3,
                ),
                "since_sec": round(now - ent["since"], 3),
            }
        return out

    # -- commands --------------------------------------------------------------

    def command_handler(self, prefix: str):
        handlers = {
            "osd erasure-code-profile set": (self._cmd_profile_set, True),
            "osd erasure-code-profile get": (self._cmd_profile_get, False),
            "osd erasure-code-profile ls": (self._cmd_profile_ls, False),
            "osd erasure-code-profile rm": (self._cmd_profile_rm, True),
            "osd pool create": (self._cmd_pool_create, True),
            "osd pool ls": (self._cmd_pool_ls, False),
            "osd pool get": (self._cmd_pool_get, False),
            "osd pool application enable": (self._cmd_app_enable, True),
            "osd pool application get": (self._cmd_app_get, False),
            "osd blocklist add": (self._cmd_blocklist_add, True),
            "osd blocklist rm": (self._cmd_blocklist_rm, True),
            "osd blocklist ls": (self._cmd_blocklist_ls, False),
            "osd pool rm": (self._cmd_pool_rm, True),
            "osd dump": (self._cmd_dump, False),
            "osd out": (self._cmd_out, True),
            "osd in": (self._cmd_in, True),
            "osd reweight": (self._cmd_reweight, True),
            "osd pool set": (self._cmd_pool_set, True),
            "osd pool set-quota": (self._cmd_pool_set_quota, True),
            "osd pool selfmanaged-snap-create": (self._cmd_snap_create, True),
            "osd tier add": (self._cmd_tier_add, True),
            "osd tier remove": (self._cmd_tier_remove, True),
            "osd tier cache-mode": (self._cmd_tier_cache_mode, True),
            "osd tier set-overlay": (self._cmd_tier_set_overlay, True),
            "osd tier remove-overlay": (self._cmd_tier_remove_overlay, True),
        }
        entry = handlers.get(prefix)
        if entry is None:
            return None
        fn, mutating = entry
        fn.__func__.mutating = mutating
        return fn

    # normalize_profile (OSDMonitor.cc:7416): instantiate through the
    # registry so plugin defaults land in the stored profile.
    @staticmethod
    def _normalize_profile(profile: dict[str, str]) -> dict[str, str]:
        profile = dict(profile)
        plugin = profile.setdefault("plugin", "tpu")
        work = {k: v for k, v in profile.items() if not k.startswith("crush-") and k != "stripe_unit"}
        ec = ErasureCodePluginRegistry.instance().factory(plugin, work)
        out = dict(ec.get_profile())
        for k, v in profile.items():
            if k.startswith("crush-") or k == "stripe_unit":
                out[k] = v
        return out

    def _cmd_profile_set(self, cmd, reply) -> None:
        name = cmd["name"]
        profile_kv = dict(kv.split("=", 1) for kv in cmd.get("profile", []))
        normalized = self._normalize_profile(profile_kv)
        force = bool(cmd.get("force"))

        def mutate(m: OSDMap) -> str:
            existing = m.erasure_code_profiles.get(name)
            if existing is not None and existing != normalized and not force:
                raise ValueError(
                    f"will not override erasure code profile {name}"
                )
            m.erasure_code_profiles[name] = normalized
            return f"profile {name} set"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_profile_get(self, cmd, reply) -> None:
        name = cmd["name"]
        prof = self.osdmap.erasure_code_profiles.get(name)
        if prof is None:
            reply(-2, f"no such profile {name}")
        else:
            reply(0, "", json.dumps(prof).encode())

    def _cmd_profile_ls(self, cmd, reply) -> None:
        reply(0, "", json.dumps(sorted(self.osdmap.erasure_code_profiles)).encode())

    def _cmd_profile_rm(self, cmd, reply) -> None:
        name = cmd["name"]

        def mutate(m: OSDMap) -> str:
            for pool in m.pools.values():
                if pool.erasure_code_profile == name:
                    raise ValueError(f"profile {name} in use by pool {pool.name}")
            if name not in m.erasure_code_profiles:
                raise KeyError(f"no such profile {name}")
            del m.erasure_code_profiles[name]
            return f"profile {name} removed"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_pool_create(self, cmd, reply) -> None:
        name = cmd["pool"]
        pool_type = cmd.get("pool_type", "replicated")
        pg_num = int(cmd.get(
            "pg_num", self.mon.conf.get("osd_pool_default_pg_num")
        ))

        if pool_type == "erasure":
            profile_name = cmd.get("erasure_code_profile", "default")

            def mutate(m: OSDMap) -> str:
                prof = m.erasure_code_profiles.get(profile_name)
                if prof is None:
                    raise KeyError(f"no such erasure-code profile {profile_name}")
                ec = ErasureCodePluginRegistry.instance().factory(
                    prof.get("plugin", "tpu"),
                    {k: v for k, v in prof.items()
                     if not k.startswith("crush-") and k != "stripe_unit"},
                )
                k = ec.get_data_chunk_count()
                stripe_unit = int(prof.get(
                    "stripe_unit",
                    self.mon.conf.get("osd_pool_erasure_code_stripe_unit"),
                ))
                # stripe_unit must equal the codec chunk size
                # (OSDMonitor.cc:7437-7455)
                chunk = ec.get_chunk_size(k * stripe_unit)
                if chunk != stripe_unit:
                    raise ValueError(
                        f"stripe_unit {stripe_unit} incompatible: codec chunk "
                        f"size would be {chunk}"
                    )
                rule = m.crush.rule_id(f"ec_{profile_name}")
                if rule is None:
                    rule = m.crush.add_simple_rule(
                        f"ec_{profile_name}",
                        failure_domain=prof.get("crush-failure-domain", "host"),
                        mode="indep",
                    )
                flags = FLAG_EC_OVERWRITES if cmd.get("allow_ec_overwrites") else 0
                m.create_pool(
                    name,
                    type=POOL_TYPE_ERASURE,
                    size=ec.get_chunk_count(),
                    min_size=k + 1 if ec.get_coding_chunk_count() > 1 else k,
                    pg_num=pg_num,
                    crush_rule=rule,
                    erasure_code_profile=profile_name,
                    stripe_width=k * stripe_unit,
                    flags=flags,
                    # osd_fast_read: the pool-level default for issuing
                    # k+m sub-reads with the first k winning
                    fast_read=bool(cmd.get(
                        "fast_read", self.mon.conf.get("osd_fast_read")
                    )),
                )
                return f"pool '{name}' created"

        else:

            def mutate(m: OSDMap) -> str:
                rule = m.crush.rule_id("replicated_rule")
                if rule is None:
                    rule = m.crush.add_simple_rule(
                        "replicated_rule",
                        failure_domain=cmd.get("crush_failure_domain", "host"),
                        mode="firstn",
                    )
                m.create_pool(
                    name,
                    type=POOL_TYPE_REPLICATED,
                    size=int(cmd.get("size", 3)),
                    pg_num=pg_num,
                    crush_rule=rule,
                )
                return f"pool '{name}' created"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_pool_set_quota(self, cmd, reply) -> None:
        """`osd pool set-quota <pool> max_bytes|max_objects <val>`
        (OSDMonitor prepare_command; 0 clears).  Enforcement closes the
        loop in tick(): the mgr digest flips FLAG_FULL_QUOTA."""
        pool, field, val = cmd.get("pool"), cmd.get("field"), cmd.get("val")
        if field not in ("max_bytes", "max_objects"):
            reply(-EINVAL, f"unknown quota field {field!r}")
            return

        def mutate(m: OSDMap) -> str:
            p = m.get_pool(pool)
            if p is None:
                raise KeyError(f"pool {pool!r} does not exist")
            setattr(p, f"quota_{field}", int(val))
            if not p.quota_max_bytes and not p.quota_max_objects:
                p.flags &= ~FLAG_FULL_QUOTA  # clearing quotas unfulls
            return f"set-quota {field}={val} on pool {pool!r}"

        self._queue(mutate, reply)

    def tick(self) -> None:
        """Leader timers: auto-out of long-down OSDs
        (mon_osd_down_out_interval, OSDMonitor::tick's down-out sweep)
        and quota enforcement — compare the mgr's PGMap digest against
        pool quotas and flip FLAG_FULL_QUOTA via paxos."""
        if not self.mon.is_leader():
            return
        self._tick_down_out()
        # expire stale laggy evidence even when nobody reads health: the
        # clog clear must fire from the timeline, not a status request
        now = time.monotonic()
        for target in list(self.laggy):
            self._prune_laggy(target, now)
        stats = (self.mon.pg_digest or {}).get("pools", {})
        for p in list(self.osdmap.pools.values()):
            if not p.quota_max_bytes and not p.quota_max_objects:
                continue
            st = stats.get(p.name)
            if st is None:
                continue
            full = (
                (p.quota_max_objects and st["objects"] >= p.quota_max_objects)
                or (p.quota_max_bytes and st["stored"] >= p.quota_max_bytes)
            )
            if bool(p.flags & FLAG_FULL_QUOTA) == bool(full):
                continue
            name, want = p.name, bool(full)

            def mutate(m: OSDMap, name=name, want=want) -> str:
                tp = m.get_pool(name)
                if tp is None:
                    return ""
                if want:
                    tp.flags |= FLAG_FULL_QUOTA
                else:
                    tp.flags &= ~FLAG_FULL_QUOTA
                return f"pool {name!r} {'full (quota)' if want else 'no longer full'}"

            self._queue(mutate, None)

    def _cmd_app_enable(self, cmd, reply) -> None:
        """`osd pool application enable <pool> <app>` (OSDMonitor
        application metadata; rbd/cephfs/rgw tag their pools)."""
        pool, app = cmd.get("pool"), cmd.get("app", "")
        if not app:
            reply(-EINVAL, "usage: osd pool application enable <pool> <app>")
            return

        def mutate(m: OSDMap) -> str:
            p = m.get_pool(pool)
            if p is None:
                raise KeyError(f"pool {pool!r} does not exist")
            if p.application and p.application != app:
                raise ValueError(
                    f"pool {pool!r} already tagged {p.application!r}"
                )
            p.application = app
            return f"enabled application {app!r} on pool {pool!r}"

        self._queue(mutate, reply)

    def _cmd_app_get(self, cmd, reply) -> None:
        p = self.osdmap.get_pool(cmd.get("pool"))
        if p is None:
            reply(-EINVAL, f"pool {cmd.get('pool')!r} does not exist")
            return
        reply(0, "", json.dumps({"application": p.application}).encode())

    def _cmd_blocklist_add(self, cmd, reply) -> None:
        """`osd blocklist add <entity>` — fence a client instance
        (OSDMonitor blocklist; OSDs refuse its ops from the next epoch)."""
        entity = cmd.get("addr") or cmd.get("entity") or ""
        if not entity:
            reply(-EINVAL, "usage: osd blocklist add <entity>")
            return

        def mutate(m: OSDMap) -> str:
            m.blocklist.add(entity)
            return f"blocklisting {entity}"

        self._queue(mutate, reply)

    def _cmd_blocklist_rm(self, cmd, reply) -> None:
        entity = cmd.get("addr") or cmd.get("entity") or ""

        def mutate(m: OSDMap) -> str:
            if entity not in m.blocklist:
                raise KeyError(f"{entity} is not blocklisted")
            m.blocklist.discard(entity)
            return f"un-blocklisting {entity}"

        self._queue(mutate, reply)

    def _cmd_blocklist_ls(self, cmd, reply) -> None:
        reply(0, "", json.dumps(sorted(self.osdmap.blocklist)).encode())

    def _cmd_pool_get(self, cmd, reply) -> None:
        """`osd pool get <pool> <var>|all` (OSDMonitor prepare_command
        get variants)."""
        import dataclasses

        p = self.osdmap.get_pool(cmd.get("pool"))
        if p is None:
            reply(-EINVAL, f"pool {cmd.get('pool')!r} does not exist")
            return
        info = dataclasses.asdict(p)
        var = cmd.get("var", "all")
        if var in ("", "all"):
            reply(0, "", json.dumps(info).encode())
            return
        if var not in info:
            reply(-EINVAL, f"unknown pool variable {var!r}")
            return
        reply(0, "", json.dumps({var: info[var]}).encode())

    def _cmd_pool_ls(self, cmd, reply) -> None:
        reply(0, "", json.dumps([p.name for p in self.osdmap.pools.values()]).encode())

    def _cmd_pool_rm(self, cmd, reply) -> None:
        name = cmd["pool"]

        def mutate(m: OSDMap) -> str:
            pool = m.get_pool(name)
            if pool is None:
                raise KeyError(f"no such pool {name}")
            del m.pools[pool.id]
            del m.pool_name_to_id[name]
            return f"pool '{name}' removed"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    # -- cache tiering (OSDMonitor prepare_command `osd tier ...`) -----------

    def _cmd_tier_add(self, cmd, reply) -> None:
        """`osd tier add <base> <tierpool>` — attach tierpool as a cache
        tier of base (OSDMonitor.cc tier add: both must exist, neither may
        already be in a tier relationship)."""
        base_n, tier_n = cmd["pool"], cmd["tierpool"]

        def mutate(m: OSDMap) -> str:
            base, tier = m.get_pool(base_n), m.get_pool(tier_n)
            if base is None or tier is None:
                raise KeyError(f"no such pool {base_n if base is None else tier_n}")
            if tier.tier_of >= 0:
                raise ValueError(f"pool '{tier_n}' is already a tier")
            if tier.tiers or base.tier_of >= 0:
                raise ValueError("tiers cannot be stacked")
            if tier.id == base.id:
                raise ValueError("pool cannot be a tier of itself")
            if tier.is_erasure():
                # The reference requires replicated cache pools too
                # (OSDMonitor tier add: EC tiers rejected).
                raise ValueError("cache tier pools must be replicated")
            tier.tier_of = base.id
            base.tiers.append(tier.id)
            return f"pool '{tier_n}' is now (or already was) a tier of '{base_n}'"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_tier_remove(self, cmd, reply) -> None:
        base_n, tier_n = cmd["pool"], cmd["tierpool"]

        def mutate(m: OSDMap) -> str:
            base, tier = m.get_pool(base_n), m.get_pool(tier_n)
            if base is None or tier is None:
                raise KeyError(f"no such pool {base_n if base is None else tier_n}")
            if tier.tier_of != base.id:
                raise ValueError(f"pool '{tier_n}' is not a tier of '{base_n}'")
            if base.read_tier == tier.id:
                raise ValueError("remove the overlay first (osd tier remove-overlay)")
            tier.tier_of = -1
            tier.cache_mode = "none"
            base.tiers.remove(tier.id)
            return f"pool '{tier_n}' is now (or already was) not a tier of '{base_n}'"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_tier_cache_mode(self, cmd, reply) -> None:
        tier_n, mode = cmd["pool"], cmd["mode"]

        def mutate(m: OSDMap) -> str:
            tier = m.get_pool(tier_n)
            if tier is None:
                raise KeyError(f"no such pool {tier_n}")
            if tier.tier_of < 0:
                raise ValueError(f"pool '{tier_n}' is not a tier")
            if mode not in ("none", "writeback", "readonly"):
                raise ValueError(f"unknown cache mode '{mode}'")
            base = m.get_pool(tier.tier_of)
            if mode == "none" and base is not None and base.read_tier == tier.id:
                # mode 'none' disables the PG-side tier gate while clients
                # still redirect to this pool: base-resident data would
                # stop promoting.  Same ordering rule as tier remove.
                raise ValueError(
                    "pool is an overlay; remove the overlay first "
                    "(osd tier remove-overlay)"
                )
            tier.cache_mode = mode
            return f"set cache-mode for pool '{tier_n}' to {mode}"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_tier_set_overlay(self, cmd, reply) -> None:
        """`osd tier set-overlay <base> <overlaypool>` — clients targeting
        base redirect ops to the overlay (Objecter _calc_target read_tier)."""
        base_n, overlay_n = cmd["pool"], cmd["overlaypool"]

        def mutate(m: OSDMap) -> str:
            base, overlay = m.get_pool(base_n), m.get_pool(overlay_n)
            if base is None or overlay is None:
                raise KeyError(
                    f"no such pool {base_n if base is None else overlay_n}"
                )
            if overlay.tier_of != base.id:
                raise ValueError(f"pool '{overlay_n}' is not a tier of '{base_n}'")
            if overlay.cache_mode == "none":
                raise ValueError("set a cache-mode first (osd tier cache-mode)")
            base.read_tier = overlay.id
            return f"overlay for '{base_n}' is now (or already was) '{overlay_n}'"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_tier_remove_overlay(self, cmd, reply) -> None:
        base_n = cmd["pool"]

        def mutate(m: OSDMap) -> str:
            base = m.get_pool(base_n)
            if base is None:
                raise KeyError(f"no such pool {base_n}")
            base.read_tier = -1
            return f"there is now (or already was) no overlay for '{base_n}'"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_dump(self, cmd, reply) -> None:
        m = self.osdmap
        reply(
            0,
            "",
            json.dumps(
                {
                    "epoch": m.epoch,
                    "osds": {
                        str(o): {"up": i.up, "in": i.in_, "addr": i.addr}
                        for o, i in m.osds.items()
                    },
                    "pools": {
                        str(p.id): {
                            "name": p.name,
                            "type": p.type,
                            "size": p.size,
                            "pg_num": p.pg_num,
                            "erasure_code_profile": p.erasure_code_profile,
                            "stripe_width": p.stripe_width,
                        }
                        for p in m.pools.values()
                    },
                }
            ).encode(),
        )

    def _tick_down_out(self) -> None:
        """mon_osd_down_out_interval: an OSD that stays down for the
        interval is marked OUT so CRUSH remaps its data and recovery
        starts — without it a dead OSD's PGs stay degraded forever
        unless an operator runs `osd out` by hand.  <= 0 disables the
        sweep.

        ISSUE 15 hardening: the per-OSD grace is flap-dampened (a
        repeatedly-bouncing OSD earns backoff^(markdowns-1) times the
        base interval before its data is remapped — a genuinely dead
        OSD, with one markdown, still goes out at the base interval),
        and at most mon_osd_flap_max_auto_out_per_tick OSDs are outed
        per sweep — a rack-wide blip cannot rewrite the whole map in
        one epoch.  OSDs over budget keep their down-clock and go out
        on later ticks."""
        interval = float(self.mon.conf.get("mon_osd_down_out_interval"))
        budget = int(self.mon.conf.get("mon_osd_flap_max_auto_out_per_tick"))
        outed = 0
        now = time.monotonic()
        for oid, info in list(self.osdmap.osds.items()):
            if info.up or not info.in_:
                self._down_since.pop(oid, None)
                self._hold_logged.discard(oid)
                continue
            t0 = self._down_since.setdefault(oid, now)
            if interval <= 0:
                continue
            grace = self._down_out_grace(oid, now)
            if now - t0 < grace:
                if now - t0 >= interval:
                    # past the base interval but inside the dampened
                    # grace: the hold is the dampening WORKING, counted
                    # so chaos/tests can witness it
                    self.dampened_holds += 1
                    if oid not in self._hold_logged:
                        # one timeline entry per down episode: the
                        # "flap-dampened" step in the storm sequence
                        self._hold_logged.add(oid)
                        self._clog(
                            "info",
                            f"osd.{oid} down {now - t0:.0f}s; auto-out "
                            f"deferred by flap dampening "
                            f"(grace {grace:.0f}s, "
                            f"{self._recent_markdown_count(oid, now)} "
                            f"recent markdowns)",
                        )
                continue
            if budget > 0 and outed >= budget:
                continue  # churn cap: keep the clock, out it next tick
            self._down_since.pop(oid, None)
            self._hold_logged.discard(oid)
            outed += 1
            self.auto_outs_total += 1

            def mutate(m: OSDMap, oid=oid, grace=grace) -> str:
                m.set_osd_weight(oid, 0)
                self._auto_outed.add(oid)
                return f"osd.{oid} marked out after {grace:.0f}s down"

            dout("mon", 1, f"osd.{oid} down {now - t0:.0f}s >= "
                           f"{grace:.0f}s (dampened grace): marking out")
            self._queue(mutate, None)
            self._clog(
                "warn",
                f"osd.{oid} marked out after {grace:.0f}s down (auto-out)",
            )

    def _cmd_out(self, cmd, reply) -> None:
        osd = int(cmd["id"])

        def mutate(m: OSDMap) -> str:
            m.set_osd_weight(osd, 0)
            return f"osd.{osd} out"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_in(self, cmd, reply) -> None:
        osd = int(cmd["id"])

        def mutate(m: OSDMap) -> str:
            from ..crush.crush import WEIGHT_ONE

            m.set_osd_weight(osd, WEIGHT_ONE)
            return f"osd.{osd} in"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_reweight(self, cmd, reply) -> None:
        """`osd reweight <id> <weight>` — the balancer's knob
        (OSDMonitor reweight; weight in [0,1] scales CRUSH acceptance)."""
        osd = int(cmd["id"])
        weight = float(cmd["weight"])
        if not 0.0 <= weight <= 1.0:
            reply(-EINVAL, f"weight {weight} not in [0, 1]")
            return

        def mutate(m: OSDMap) -> str:
            from ..crush.crush import WEIGHT_ONE

            if osd not in m.osds:
                raise KeyError(f"osd.{osd} does not exist")
            m.set_osd_weight(osd, int(weight * WEIGHT_ONE))
            return f"osd.{osd} reweighted to {weight}"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))

    def _cmd_snap_create(self, cmd, reply) -> None:
        """Allocate a self-managed snapshot id from the pool's snap_seq
        (OSDMonitor prepare_pool_op SELFMANAGED_SNAP_CREATE): the id is
        durable via paxos before any client uses it in a SnapContext."""
        import json as _json

        name = cmd["pool"]
        out: dict = {}

        def mutate(m: OSDMap) -> str:
            if name not in m.pool_name_to_id:
                raise KeyError(f"no such pool {name}")
            pool = m.pools[m.pool_name_to_id[name]]
            pool.snap_seq += 1
            out["snap_id"] = pool.snap_seq
            return f"created snap {pool.snap_seq} in {name}"

        self._queue(
            mutate,
            lambda rv, rs: reply(
                rv, rs, _json.dumps(out).encode() if rv == 0 else b""
            ),
        )

    def _cmd_pool_set(self, cmd, reply) -> None:
        """`osd pool set <pool> <var> <val>` (OSDMonitor prepare_command
        pool set).  pg_num changes remap existing objects and this
        framework has no PG-splitting data migration, so they require the
        caller to assert the pool is empty via `yes_i_really_mean_it`
        (the reference's own force-flag convention for dangerous pool
        mutations); the autoscaler defaults to warn-only mode for the
        same reason."""
        name = cmd["pool"]
        var = cmd["var"]
        val = cmd["val"]
        if var == "pg_num" and not cmd.get("yes_i_really_mean_it"):
            reply(
                -EINVAL,
                "pg_num changes move every object's placement and existing "
                "data is NOT migrated (no PG splitting); pass "
                "yes_i_really_mean_it for an empty pool",
            )
            return

        def mutate(m: OSDMap) -> str:
            pool = m.get_pool(name)
            if pool is None:
                raise KeyError(f"pool {name!r} does not exist")
            if var == "pg_num":
                pool.pg_num = int(val)
            elif var == "size":
                pool.size = int(val)
            elif var == "min_size":
                pool.min_size = int(val)
            elif var == "fast_read":
                pool.fast_read = str(val).lower() in ("1", "true", "yes")
            elif var == "target_max_objects":
                pool.target_max_objects = int(val)
            else:
                raise ValueError(f"unknown pool variable {var!r}")
            return f"set pool {name} {var} to {val}"

        self._queue(mutate, lambda rv, rs: reply(rv, rs))
