"""Device-resident chunk cache — keep hot EC chunks in HBM (ISSUE 11).

The third lever of the per-chip-gap tentpole: a repeated degraded read
(and the read leg of a degraded RMW cycle — both flow through
``ECBackend.objects_read_and_reconstruct``) re-reconstructs the same
missing chunks launch after launch, paying the H2D staging of the whole
survivor batch every time.  This cache holds recently encoded/decoded
chunk buffers ON DEVICE, keyed by ``(object, shard, generation, offset)``,
so the next read of the same (object, generation) serves the missing
chunks with a single D2H copy — no H2D, no kernel, no launch at all.

Coherence model:

- ``generation`` is the object's version at put/get time (the producer
  passes it); a write bumps the version, so stale entries simply miss.
- Overwrites additionally ``invalidate_object`` eagerly at encode
  dispatch — the moment the bytes actually change — so dead bytes free
  immediately.  NOT at submit: the write's own RMW read leg runs between
  the two and reads exactly the committed pre-write bytes, so it may
  serve them from the cache (``ECBackend`` captures the pre-write
  generation at submit and threads it through the read).
- A DEGRADED backend transition (``ops/guard.py mark_degraded``) clears
  the cache and gates ``put``: a wedged runtime cannot be trusted to
  serve buffers, and the byte-identical host path needs no cache.
- Keys are opaque to this module — ``ECBackend`` namespaces them with a
  never-reused per-backend token, so one process hosting many clusters
  (the test harnesses) can never cross-serve bytes.

Bounded by ``ec_tpu_device_cache_bytes`` (LRU, runtime-mutable through
the OSD config-observer pattern); hit/miss/evict counters export through
``ops/dispatch.perf_dump()`` (asok ``perf dump`` ``ec_dispatch.cache_*``
→ ``ceph_tpu_ec_dispatch_cache_*`` Prometheus families).  A served hit
commits a ``cache_hit``-flagged flight record whose only span is the
D2H copy — the "skips H2D" acceptance criterion is a visible property
of the timeline, not an inference.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

import numpy as np

from ceph_tpu.common.lockdep import make_lock
from ceph_tpu.common.mempool import ledger as _hbm_ledger


class _Entry:
    __slots__ = ("buf", "nbytes", "generation", "off", "mem")

    def __init__(self, buf, nbytes: int, generation, off: int, mem=None):
        self.buf = buf
        self.nbytes = int(nbytes)
        self.generation = generation
        self.off = int(off)
        # HBM ledger handle (ISSUE 13): one per resident entry,
        # buffer-finalized so a dropped cache instance cannot leak
        # ledger bytes past its buffers' death
        self.mem = mem


class DeviceChunkCache:
    """Bounded per-backend LRU of device-resident chunk buffers."""

    def __init__(self, max_bytes: int | None = None):
        if max_bytes is None:
            from ceph_tpu.common.options import OPTIONS

            max_bytes = int(OPTIONS["ec_tpu_device_cache_bytes"].default)
        self._lock = make_lock("device_cache")
        # (obj, shard, off) -> _Entry; generation checked on get so a
        # stale-generation entry is replaced in place by the next put
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # obj -> {keys} index so the per-write invalidate_object hook is
        # O(entries-for-that-object), not a scan of the whole cache
        self._by_obj: dict[object, set[tuple]] = {}
        self._bytes = 0
        self.max_bytes = int(max_bytes)
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0
        self.invalidations = 0
        self.served_bytes = 0
        self.put_failures = 0
        self.delta_updates = 0

    # -- configuration -------------------------------------------------------

    def configure(self, max_bytes: int | None = None) -> None:
        """Apply live config (`ec_tpu_device_cache_bytes`); shrinking
        evicts LRU-first, 0 disables and drops everything.

        `resident_bytes` is RECOMPUTED from the entry index before the
        eviction loop, not trusted from the decremented counter: the
        cap-shrink observer is exactly where accumulated counter drift
        would evict too little (a stale-high counter over-evicts, which
        merely wastes cache; a stale-LOW counter leaves the cache over
        the new cap forever) — and the HBM ledger reconciliation exists
        to expose precisely that drift class."""
        if max_bytes is None:
            return
        with self._lock:
            self.max_bytes = int(max_bytes)
            self._bytes = sum(e.nbytes for e in self._entries.values())
            self._evict_to_fit_locked(0)

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    # -- producer side -------------------------------------------------------

    def put(self, obj, shard: int, generation, data, off: int = 0) -> bool:
        """Commit one chunk's bytes to the device and cache the buffer.
        ``data`` is host bytes/ndarray (flattened) or an already-committed
        device array.  No-ops while the backend is DEGRADED (a wedged
        runtime must not be handed fresh work) or when the item alone
        exceeds the bound."""
        if not self.enabled or generation is None:
            return False
        from .guard import DeviceTimeout, device_guard

        if device_guard().degraded:
            return False
        if isinstance(data, (bytes, bytearray, memoryview)):
            arr = np.frombuffer(data, dtype=np.uint8)
        else:
            arr = np.asarray(data, dtype=np.uint8).reshape(-1)
        nbytes = arr.nbytes
        if nbytes == 0 or nbytes > self.max_bytes:
            return False
        try:
            import jax

            # deadline-guarded like every other device wait: a wedged
            # runtime can HANG device_put, and the producer sits on the
            # decode-materialize path
            buf = device_guard().call(
                lambda: jax.device_put(arr), what="cache put"
            )
        except DeviceTimeout as e:
            # the commit wedged: degrade (which clears this cache) so
            # every path stops trusting the runtime, and fail the put
            device_guard().mark_degraded(f"cache put: {e}")
            return False
        except Exception:
            # a broken runtime must never fail the producer — but the
            # refusal is counted (`cache.put_failures` on the perf dump),
            # not invisible
            self.put_failures += 1
            return False
        with self._lock:
            key = (obj, int(shard), int(off))
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._by_obj[obj].discard(key)
                if old.mem is not None:
                    old.mem.free()
            self._evict_to_fit_locked(nbytes)
            self._entries[key] = _Entry(
                buf, nbytes, generation, off,
                mem=_hbm_ledger().alloc("device_cache", nbytes, buf=buf),
            )
            self._by_obj.setdefault(obj, set()).add(key)
            self._bytes += nbytes
            self.insertions += 1
        return True

    def _evict_lru_one_locked(self) -> int:
        """Evict the single LRU entry (counter + ledger + index
        bookkeeping in ONE place); returns its bytes."""
        key, entry = self._entries.popitem(last=False)
        self._bytes -= entry.nbytes
        if entry.mem is not None:
            entry.mem.free()
        keys = self._by_obj.get(key[0])
        if keys is not None:
            keys.discard(key)
            if not keys:
                del self._by_obj[key[0]]
        self.evictions += 1
        return entry.nbytes

    def _evict_to_fit_locked(self, incoming: int) -> None:
        while self._entries and self._bytes + incoming > self.max_bytes:
            self._evict_lru_one_locked()

    def trim_for_pressure(self, nbytes: int) -> int:
        """Evict LRU-first until at least `nbytes` were released (or
        the cache is empty); returns the bytes freed.  The HBM pressure
        layer's stage-1 action (common/mempool.py): cached chunks are
        rebuildable pure optimization — the cheapest resident bytes to
        give back."""
        freed = 0
        with self._lock:
            while self._entries and freed < nbytes:
                freed += self._evict_lru_one_locked()
        return freed

    def replace(self, obj, shard: int, generation, buf, off: int = 0) -> bool:
        """Commit an ALREADY-DEVICE-RESIDENT buffer under a new
        generation — the RMW delta path's parity/data commit (ISSUE 18):
        the delta kernel's output never leaves HBM, so there is no host
        array to ``put``; the generation bumps in place and only the
        ledger re-accounts.  Counts on ``delta_updates``."""
        if not self.enabled or generation is None:
            return False
        from .guard import device_guard

        if device_guard().degraded:
            return False
        nbytes = int(buf.nbytes)
        if nbytes == 0 or nbytes > self.max_bytes:
            return False
        with self._lock:
            key = (obj, int(shard), int(off))
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._by_obj[obj].discard(key)
                if old.mem is not None:
                    old.mem.free()
            self._evict_to_fit_locked(nbytes)
            self._entries[key] = _Entry(
                buf, nbytes, generation, off,
                mem=_hbm_ledger().alloc("device_cache", nbytes, buf=buf),
            )
            self._by_obj.setdefault(obj, set()).add(key)
            self._bytes += nbytes
            self.insertions += 1
            self.delta_updates += 1
        return True

    # -- consumer side -------------------------------------------------------

    def get_resident_many(
        self, obj, shards, generation, off: int = 0,
        length: int | None = None,
    ) -> dict | None:
        """All-or-nothing consult returning the DEVICE buffers — no D2H,
        no flight record: the RMW delta read leg (ISSUE 18).  The caller
        composes these into ONE delta launch whose flight record shows
        h2d_s == d2h_s == 0; a partial hit returns None (the materialize
        path re-encodes anyway, so serving half would be pure waste).
        The returned buffers stay valid even if a subsequent put/replace
        supersedes their keys (the arrays are refcounted)."""
        shards = list(shards)
        if not shards or not self.enabled:
            return None
        with self._lock:
            out = {}
            for s in shards:
                entry = self._entries.get((obj, int(s), int(off)))
                if (
                    entry is None
                    or entry.generation != generation
                    or (length is not None and entry.nbytes < length)
                ):
                    self.misses += len(shards)
                    return None
                out[int(s)] = entry.buf
            for s in shards:
                self._entries.move_to_end((obj, int(s), int(off)))
            self.hits += len(shards)
        return out

    def get(self, obj, shard: int, generation, off: int = 0,
            length: int | None = None):
        """The cached device buffer for (obj, shard, generation, off), or
        None.  ``length`` (bytes) must fit inside the stored buffer."""
        with self._lock:
            key = (obj, int(shard), int(off))
            entry = self._entries.get(key)
            if (
                entry is None
                or entry.generation != generation
                or (length is not None and entry.nbytes < length)
            ):
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry.buf

    def fetch_many(
        self, obj, shards, generation, off: int = 0,
        length: int | None = None, kind: str = "decode", stripes: int = 0,
    ) -> dict[int, np.ndarray] | None:
        """Serve a whole missing-chunk set from HBM, or None when ANY
        chunk misses (an all-or-nothing consult: a partial hit still
        needs the decode launch, so serving half would be pure waste).

        On a full hit the D2H copies are timed and committed as ONE
        ``cache_hit``-flagged flight record with h2d_s = kernel_s = 0 —
        the timeline proof that this path skipped the H2D leg entirely.
        """
        shards = list(shards)
        if not shards or not self.enabled:
            return None
        with self._lock:
            entries = []
            for s in shards:
                entry = self._entries.get((obj, int(s), int(off)))
                if (
                    entry is None
                    or entry.generation != generation
                    or (length is not None and entry.nbytes < length)
                ):
                    self.misses += len(shards)
                    return None
                entries.append(entry)
            for s in shards:
                self._entries.move_to_end((obj, int(s), int(off)))
        from .guard import device_guard

        def _copy_out():
            res: dict[int, np.ndarray] = {}
            n = 0
            for s, entry in zip(shards, entries):
                host = np.asarray(entry.buf)
                if length is not None and host.nbytes > length:
                    host = host[:length]
                res[int(s)] = host
                n += host.nbytes
            return res, n

        t0 = time.monotonic()
        try:
            # deadline-guarded like every other device wait: on a wedged
            # runtime np.asarray blocks forever, and this consult sits on
            # the degraded-read path the guard exists to protect
            out, nbytes = device_guard().call(_copy_out, what="cache fetch")
        except Exception as e:
            # the D2H hung or failed: degrade (which clears this cache)
            # and report a MISS so the caller's decode launch takes the
            # guarded host-fallback path instead of hanging here
            device_guard().mark_degraded(f"cache fetch: {e}")
            with self._lock:
                self.misses += len(shards)
            return None
        d2h_s = time.monotonic() - t0
        with self._lock:
            self.hits += len(shards)
            self.served_bytes += nbytes
        self._record_hit(kind, stripes or len(shards), nbytes, d2h_s)
        return out

    @staticmethod
    def _record_hit(kind: str, stripes: int, nbytes: int, d2h_s: float) -> None:
        """Flight record for a cache-served read: no queue wait, no H2D,
        no kernel — only the D2H copy of the resident chunks."""
        from .flight_recorder import flight_recorder, new_record

        rec = new_record(kind, group="#cache", stripes=stripes,
                         batch=stripes, nbytes=nbytes)
        now = time.monotonic()
        rec["dispatch_ts"] = now - d2h_s
        rec["submit_ts"] = rec["dispatch_ts"]
        rec["complete_ts"] = rec["dispatch_ts"]
        rec["d2h_s"] = d2h_s
        rec["flags"]["cache_hit"] = True
        flight_recorder().commit(rec)

    # -- invalidation --------------------------------------------------------

    def invalidate_object(self, obj) -> int:
        """Drop every entry of one object (any shard/offset): the
        overwrite hook.  Returns how many entries died."""
        with self._lock:
            doomed = self._by_obj.pop(obj, None)
            if not doomed:
                return 0
            for key in doomed:
                entry = self._entries.pop(key)
                self._bytes -= entry.nbytes
                if entry.mem is not None:
                    entry.mem.free()
            self.invalidations += len(doomed)
            return len(doomed)

    def clear(self) -> None:
        """Drop everything (the DEGRADED-transition hook): buffers on a
        wedged runtime are unreachable, and the host path needs none."""
        with self._lock:
            self.invalidations += len(self._entries)
            for entry in self._entries.values():
                if entry.mem is not None:
                    entry.mem.free()
            self._entries.clear()
            self._by_obj.clear()
            self._bytes = 0

    # -- introspection -------------------------------------------------------

    def perf_dump(self) -> dict[str, int]:
        """JSON-safe counters for the `ec_dispatch.cache_*` slice.
        `resident_bytes`/`entries` are gauges (they fall on eviction and
        invalidation); the rest are monotonic counters."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "insertions": self.insertions,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "put_failures": self.put_failures,
                "delta_updates": self.delta_updates,
                "served_bytes": self.served_bytes,
                "resident_bytes": self._bytes,
                "entries": len(self._entries),
            }


_CACHE: DeviceChunkCache | None = None


def device_chunk_cache() -> DeviceChunkCache:
    """The process-wide (per-backend: one device runtime per process)
    cache, built lazily from option defaults like the device guard and
    the default aggregators; daemons with a live Config re-bound it
    through their runtime observers."""
    global _CACHE
    if _CACHE is None:
        _CACHE = DeviceChunkCache()
    return _CACHE
