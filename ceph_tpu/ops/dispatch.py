"""Device-launch accounting for the coding hot path.

Three counters, incremented exactly once per host->device kernel dispatch
by the lowest-level python wrapper of each coding path (PackedPlan, the
Pallas CodingPlan, the jnp bitsliced fallback, xor_reduce, the sharded
shard_map dispatch): `LAUNCHES` totals every coding dispatch,
`DECODE_LAUNCHES` additionally totals the dispatches issued on behalf of
a decode (recovery / degraded read), and `SHARDED_LAUNCHES` additionally
totals the dispatches that spanned more than one device of the mesh
(parallel/dispatch.py data-parallel fan-out).  Tests assert batching
invariants against them — "encoding N stripes cost 1 dispatch",
"recovering N same-pattern objects cost O(1) decode dispatches", "a
bulk batch crossed the shard threshold and spanned the mesh" — so a
regression back to per-stripe launches (or silently single-device
launches) fails tier-1 instead of only showing up as a bench number
(ISSUE 3 / ISSUE 5 / ISSUE 6 launch-counter contracts).

Caveat: counting happens at python dispatch time.  A coding call traced
inside an OUTER jax.jit (bench.py's serial chain) runs the wrapper once
at trace time, so executions of the compiled program are not re-counted.
That is the correct reading for the batching invariant — the outer
program still contains one fused encode — but it means the counter is a
dispatch-shape witness, not an execution profiler.
"""

from __future__ import annotations

import threading

from ceph_tpu.common.lockdep import make_lock


class LaunchCounter:
    """Monotonic totals: device dispatches, stripes and bytes they carried."""

    __slots__ = ("_lock", "launches", "stripes", "bytes")

    def __init__(self) -> None:
        self._lock = make_lock("launch_counter")
        self.launches = 0
        self.stripes = 0
        self.bytes = 0

    def record(self, stripes: int, nbytes: int) -> None:
        with self._lock:
            self.launches += 1
            self.stripes += int(stripes)
            self.bytes += int(nbytes)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return {
                "launches": self.launches,
                "stripes": self.stripes,
                "bytes": self.bytes,
            }

    def reset(self) -> None:
        with self._lock:
            self.launches = 0
            self.stripes = 0
            self.bytes = 0


LAUNCHES = LaunchCounter()

# Decode-only dispatches (recovery / degraded reads).  Every decode
# dispatch is counted here AND in LAUNCHES: LAUNCHES stays the
# process-wide total every existing invariant is written against, while
# this counter isolates the read/recovery half so "N objects recovered
# in one window = O(1) decode launches" is assertable on its own.
DECODE_LAUNCHES = LaunchCounter()

# Multi-device dispatches (parallel/dispatch.py shard_map fan-out over
# the stripe axis).  Counted here AND in LAUNCHES (and DECODE_LAUNCHES
# when it is a decode): by construction SHARDED_LAUNCHES.launches <=
# LAUNCHES.launches, and a 1-device process records zero here — the
# consistency contract tests/test_perf_smoke.py pins.
SHARDED_LAUNCHES = LaunchCounter()

# Verify-only dispatches (ISSUE 9: the deep-scrub compare-only kernel,
# ops/packed_gf.PackedVerifyPlan).  Counted here AND in LAUNCHES, like
# the decode counter: LAUNCHES stays the process-wide total, while this
# isolates the integrity-check traffic so "a whole scrub chunk verified
# in one launch" is assertable on its own (the acceptance criterion's
# VERIFY_LAUNCHES > 0 witness).
VERIFY_LAUNCHES = LaunchCounter()


class DeviceOccupancy:
    """Devices-per-launch distribution: how wide each coding dispatch
    ran.  Exact per-count buckets (device counts are tiny integers, a
    log2 histogram would blur 6 vs 8 chips) plus a device-launch total so
    mean occupancy is derivable from two scalars."""

    __slots__ = ("_lock", "counts", "device_launches")

    def __init__(self) -> None:
        self._lock = make_lock("device_occupancy")
        self.counts: dict[int, int] = {}
        self.device_launches = 0  # sum(devices) over every dispatch

    def record(self, devices: int) -> None:
        with self._lock:
            self.counts[devices] = self.counts.get(devices, 0) + 1
            self.device_launches += devices

    def snapshot(self) -> dict[int, int]:
        with self._lock:
            return dict(self.counts)

    def reset(self) -> None:
        with self._lock:
            self.counts.clear()
            self.device_launches = 0


DEVICES_PER_LAUNCH = DeviceOccupancy()

class PipelineGauges:
    """Process-wide pipeline/donation accounting for the depth-N async
    launch ring (ISSUE 11, codec/matrix_codec.LaunchAggregator):

    - ``depth``: the configured ``ec_tpu_pipeline_depth`` (gauge),
    - ``inflight`` / ``inflight_peak``: launches dispatched but not yet
      settled, now and at peak,
    - ``drains``: ring-full settles (the submitter paid the oldest
      launch's wait so the new one could overlap it),
    - ``donation_reuses``: output buffers recycled from the donation
      pool into a later launch,
    - ``donation_recycled_live``: the INVARIANT counter — a pooled
      buffer handed out while its producing launch was still in flight.
      Must stay 0; the chaos pipelined-wedge phase asserts it.
    """

    __slots__ = ("_lock", "depth", "inflight", "inflight_peak", "drains",
                 "donation_reuses", "donation_recycled_live")

    def __init__(self) -> None:
        self._lock = make_lock("pipeline_gauges")
        self.depth = 0
        self.inflight = 0
        self.inflight_peak = 0
        self.drains = 0
        self.donation_reuses = 0
        self.donation_recycled_live = 0

    def set_depth(self, depth: int) -> None:
        with self._lock:
            self.depth = int(depth)

    def launch(self) -> None:
        with self._lock:
            self.inflight += 1
            self.inflight_peak = max(self.inflight_peak, self.inflight)

    def settle(self) -> None:
        with self._lock:
            self.inflight = max(0, self.inflight - 1)

    def record_drain(self) -> None:
        with self._lock:
            self.drains += 1

    def record_donation(self, reused: bool, live: bool = False) -> None:
        with self._lock:
            if reused:
                self.donation_reuses += 1
            if live:
                self.donation_recycled_live += 1

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return {
                "depth": self.depth,
                "inflight": self.inflight,
                "inflight_peak": self.inflight_peak,
                "drains": self.drains,
                "donation_reuses": self.donation_reuses,
                "donation_recycled_live": self.donation_recycled_live,
            }


PIPELINE = PipelineGauges()


class PaddingWaste:
    """Pad-stripe accounting for aggregated launches (ISSUE 18): every
    padded launch records its padded batch and how many of those stripes
    were zero padding, globally and per group label, so `perf dump` (and
    the bench) can show WHERE padding bytes go instead of only that the
    `pad_stripes` counter moved.  The per-label map is capped — group
    labels are bounded in practice (one per (matrix, chunk-size) key),
    but a pathological key churn must not grow the perf dump unboundedly."""

    LABEL_CAP = 32

    __slots__ = ("_lock", "padded_stripes", "pad_stripes", "_labels")

    def __init__(self) -> None:
        self._lock = make_lock("padding_waste")
        self.padded_stripes = 0  # stripes dispatched, padding included
        self.pad_stripes = 0  # of those, zero-pad stripes
        self._labels: dict[str, list[int]] = {}  # label -> [padded, pad]

    def record(self, label: str, padded: int, pad: int) -> None:
        with self._lock:
            self.padded_stripes += int(padded)
            self.pad_stripes += int(pad)
            slot = self._labels.get(label)
            if slot is None:
                if len(self._labels) >= self.LABEL_CAP:
                    return  # global totals still track the overflow
                slot = self._labels[label] = [0, 0]
            slot[0] += int(padded)
            slot[1] += int(pad)

    def ratio(self) -> float:
        with self._lock:
            if not self.padded_stripes:
                return 0.0
            return self.pad_stripes / self.padded_stripes

    def per_label(self) -> dict[str, float]:
        with self._lock:
            return {
                label: (pad / padded if padded else 0.0)
                for label, (padded, pad) in self._labels.items()
            }

    def reset(self) -> None:
        with self._lock:
            self.padded_stripes = 0
            self.pad_stripes = 0
            self._labels.clear()


PAD_WASTE = PaddingWaste()


def record_padding(label: str, padded: int, pad: int) -> None:
    """Record one padded aggregated launch: `padded` stripes dispatched
    (padding included) of which `pad` were zero padding, attributed to
    the group `label` (codec/matrix_codec._group_label)."""
    PAD_WASTE.record(label, padded, pad)


class FusedGauges:
    """Super-launch fusion totals (ISSUE 18): launches that carried more
    than one aggregation window's worth of tickets because the in-flight
    ring was full when their window tripped, and the windows they fused.
    Mirrors of the per-aggregator `fused_launches`/`fused_windows` perf
    counters, totalled process-wide for the dispatch perf dump."""

    __slots__ = ("_lock", "fused_launches", "fused_windows")

    def __init__(self) -> None:
        self._lock = make_lock("fused_gauges")
        self.fused_launches = 0
        self.fused_windows = 0

    def record(self, windows: int) -> None:
        with self._lock:
            self.fused_launches += 1
            self.fused_windows += int(windows)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return {
                "fused_launches": self.fused_launches,
                "fused_windows": self.fused_windows,
            }

    def reset(self) -> None:
        with self._lock:
            self.fused_launches = 0
            self.fused_windows = 0


FUSED = FusedGauges()


def record_fused(windows: int) -> None:
    """Record one fused multi-window launch spanning `windows` windows."""
    FUSED.record(windows)


# Launches that completed on the HOST ORACLE instead of the device
# (ops/guard.py DeviceGuard fallback: launch deadline exceeded, device
# error, or degraded-mode bypass).  NOT counted in LAUNCHES — these never
# reached the device — so LAUNCHES keeps meaning "device dispatches" for
# every existing batching invariant, and FALLBACK_LAUNCHES isolates the
# degraded-mode traffic the TPU_BACKEND_DEGRADED health check describes.
FALLBACK_LAUNCHES = LaunchCounter()


def record_fallback(stripes: int, nbytes: int) -> None:
    """Record one host-oracle fallback carrying `stripes` stripes /
    `nbytes` input bytes (the aggregator's degraded-path accounting)."""
    FALLBACK_LAUNCHES.record(stripes, nbytes)


def record_launch(
    stripes: int, nbytes: int, decode: bool = False, devices: int = 1,
    verify: bool = False,
) -> None:
    """Record one device dispatch carrying `stripes` stripes / `nbytes`
    input bytes on the global counter(s).  `decode=True` marks a dispatch
    issued on behalf of a decode (the coder's kind, threaded down from
    PLAN_CACHE.decode_coder) so it also lands on DECODE_LAUNCHES;
    `verify=True` marks a compare-only scrub dispatch
    (PLAN_CACHE.verify_coder) landing on VERIFY_LAUNCHES the same way.
    `devices` is how many mesh devices the dispatch spanned (the sharded
    dispatcher passes its stripe-shard count); > 1 additionally lands on
    SHARDED_LAUNCHES and every value feeds the occupancy distribution.

    Flight recorder hook (ISSUE 8): a dispatch running under an
    aggregator launch annotates devices/kind onto the ACTIVE flight
    record; a dispatch with no active record (eager bulk paths, bench
    loops) appends a lightweight span-less record so `dump_flight` and
    the trace export still show it on the timeline."""
    LAUNCHES.record(stripes, nbytes)
    if decode:
        DECODE_LAUNCHES.record(stripes, nbytes)
    if verify:
        VERIFY_LAUNCHES.record(stripes, nbytes)
    if devices > 1:
        SHARDED_LAUNCHES.record(stripes, nbytes)
    DEVICES_PER_LAUNCH.record(devices)
    from .flight_recorder import flight_recorder

    fr = flight_recorder()
    rec = fr.active()
    kind = "verify" if verify else ("decode" if decode else "encode")
    if rec is not None:
        # skip records that already settled: an abandoned watchdog
        # worker whose device unwedges minutes later still holds this
        # record through its contextvars copy, and a post-commit
        # rewrite would corrupt the ring under readers
        if not rec["settle_ts"]:
            rec["devices"] = max(rec["devices"], int(devices))
            rec["flags"]["sharded"] = rec["flags"]["sharded"] or devices > 1
            if decode or verify:
                rec["kind"] = kind
    else:
        fr.record_raw(kind, stripes, nbytes, devices)


def perf_dump() -> dict[str, object]:
    """JSON-safe export of every dispatch counter — the `ec_dispatch`
    section of the OSD's asok `perf dump` and (flattened) of the
    MMgrReport payload the mgr Prometheus scrape re-exports.  The
    devices-per-launch distribution rides as `devices_per_launch.<n>`
    scalars so the scrape renders one labeled-by-dot series per width."""
    out: dict[str, object] = {}
    for prefix, counter in (
        ("", LAUNCHES),
        ("decode_", DECODE_LAUNCHES),
        ("verify_", VERIFY_LAUNCHES),
        ("sharded_", SHARDED_LAUNCHES),
        ("fallback_", FALLBACK_LAUNCHES),
    ):
        for name, val in counter.snapshot().items():
            out[f"{prefix}{name}"] = val
    out["device_launches"] = DEVICES_PER_LAUNCH.device_launches
    for devices, launches in sorted(DEVICES_PER_LAUNCH.snapshot().items()):
        out[f"devices_per_launch.{devices}"] = launches
    # degraded-backend state (ops/guard.py): `backend_degraded` is the
    # gauge the prometheus scrape exports next to the fallback counters
    from .guard import device_guard

    snap = device_guard().snapshot()
    out["backend_degraded"] = snap["degraded"]
    out["backend_degraded_total"] = snap["degraded_total"]
    out["backend_probes"] = snap["probes"]
    out["backend_probe_failures"] = snap["probe_failures"]
    # device-utilization accounting derived from the flight recorder
    # (ISSUE 8): busy-seconds weighted by launch width, occupancy % of
    # the observation window, and the flight-ring health scalars.  The
    # OSD's MMgrReport re-exports the first two under their canonical
    # prometheus names (ceph_tpu_ec_device_busy_seconds /
    # ceph_tpu_ec_device_occupancy).
    from .flight_recorder import flight_recorder

    util = flight_recorder().utilization()
    out["device_busy_seconds"] = round(util["device_busy_seconds"], 6)
    out["device_occupancy"] = round(util["occupancy"], 6)
    out["flight_records"] = int(util["span_records"])
    out["flight_mean_queue_wait_ms"] = round(
        util["mean_queue_wait_s"] * 1e3, 3
    )
    # launch-scheduler QoS counters (ISSUE 9): per-class enqueue/dequeue
    # totals, accumulated queue wait, and the current queue-depth gauge,
    # as `sched.<class>.<counter>` scalars — the prometheus scrape
    # renders one labeled-by-dot series per class/counter pair
    from .launch_scheduler import launch_scheduler

    for name, val in launch_scheduler().perf_dump().items():
        out[f"sched.{name}"] = val
    # pipelined-dispatch ring + donation-pool invariants (ISSUE 11):
    # configured depth, current/peak in-flight launches, ring-full
    # drains, and the recycled-live invariant counter (must stay 0)
    for name, val in PIPELINE.snapshot().items():
        out[f"pipeline.{name}"] = val
    # super-launch fusion totals (ISSUE 18): launches carrying more than
    # one window's worth of tickets because the ring was full, and the
    # windows they fused — launches < submits/window proves amortization
    for name, val in FUSED.snapshot().items():
        out[name] = val
    # padding-waste accounting (ISSUE 18): the process-wide pad-stripe
    # fraction of everything dispatched padded, plus a per-group-label
    # slice (`pad_waste.<label>`) so asok/Perfetto show WHERE padding
    # bytes go — the bench proves the bucketed targets push the global
    # ratio below the pow2 baseline
    out["padding_waste_ratio"] = round(PAD_WASTE.ratio(), 6)
    for label, ratio in sorted(PAD_WASTE.per_label().items()):
        out[f"pad_waste.{label}"] = round(ratio, 6)
    # device-resident chunk cache (ISSUE 11): hit/miss/evict counters
    # plus the resident-bytes/entries gauges, as `cache.<counter>`
    # scalars -> ceph_tpu_ec_dispatch_cache_* prometheus families
    from .device_cache import device_chunk_cache

    for name, val in device_chunk_cache().perf_dump().items():
        out[f"cache.{name}"] = val
    return out
