"""Device-launch accounting for the coding hot path.

Two counters, incremented exactly once per host->device kernel dispatch by
the lowest-level python wrapper of each coding path (PackedPlan, the
Pallas CodingPlan, the jnp bitsliced fallback, xor_reduce): `LAUNCHES`
totals every coding dispatch, `DECODE_LAUNCHES` additionally totals the
dispatches issued on behalf of a decode (recovery / degraded read).
Tests assert batching invariants against them — "encoding N stripes cost
1 dispatch", "recovering N same-pattern objects cost O(1) decode
dispatches" — so a regression back to per-stripe launches fails tier-1
instead of only showing up as a bench number (ISSUE 3 / ISSUE 5
launch-counter contracts).

Caveat: counting happens at python dispatch time.  A coding call traced
inside an OUTER jax.jit (bench.py's serial chain) runs the wrapper once
at trace time, so executions of the compiled program are not re-counted.
That is the correct reading for the batching invariant — the outer
program still contains one fused encode — but it means the counter is a
dispatch-shape witness, not an execution profiler.
"""

from __future__ import annotations

import threading


class LaunchCounter:
    """Monotonic totals: device dispatches, stripes and bytes they carried."""

    __slots__ = ("_lock", "launches", "stripes", "bytes")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.launches = 0
        self.stripes = 0
        self.bytes = 0

    def record(self, stripes: int, nbytes: int) -> None:
        with self._lock:
            self.launches += 1
            self.stripes += int(stripes)
            self.bytes += int(nbytes)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return {
                "launches": self.launches,
                "stripes": self.stripes,
                "bytes": self.bytes,
            }

    def reset(self) -> None:
        with self._lock:
            self.launches = 0
            self.stripes = 0
            self.bytes = 0


LAUNCHES = LaunchCounter()

# Decode-only dispatches (recovery / degraded reads).  Every decode
# dispatch is counted here AND in LAUNCHES: LAUNCHES stays the
# process-wide total every existing invariant is written against, while
# this counter isolates the read/recovery half so "N objects recovered
# in one window = O(1) decode launches" is assertable on its own.
DECODE_LAUNCHES = LaunchCounter()


def record_launch(stripes: int, nbytes: int, decode: bool = False) -> None:
    """Record one device dispatch carrying `stripes` stripes / `nbytes`
    input bytes on the global counter(s).  `decode=True` marks a dispatch
    issued on behalf of a decode (the coder's kind, threaded down from
    PLAN_CACHE.decode_coder) so it also lands on DECODE_LAUNCHES."""
    LAUNCHES.record(stripes, nbytes)
    if decode:
        DECODE_LAUNCHES.record(stripes, nbytes)
