"""Packed-bitplane GF(2^8) coding — the device hot path without the 8x blow-up.

The original jnp path (ceph_tpu.ops.xor_mm.xor_matmul) inflates every data
byte into 8 int8 bit-planes before an (8m, 8k) int32 matmul: an 8x operand
expansion plus a 4x-wide accumulator, exactly the operand blow-up where
bitmatrix codecs lose their bandwidth ("Accelerating XOR-based Erasure
Coding using Program Optimization Techniques", arXiv:2108.02692).  This
module keeps the planes PACKED 8-per-byte and reorganizes the contraction
around packed words ("Fast Xor-based Erasure Coding based on Polynomial
Ring Transforms", arXiv:1701.07731):

    byte j of a chunk already holds its own 8 bit-planes, packed.  The
    GF(2)-linear action of a coefficient c decomposes over the bits of c:

        c * x = XOR over set bits b of c of (x * 2^b)

    and multiplication by 2 (`xtime`) is itself a packed GF(2) map:

        x * 2 = (x << 1) ^ (0x1d if x & 0x80)      (poly 0x11d, ISA-L's)

    so the whole encode is: build the k x 8 tower of packed power planes
    (7 xtime steps per chunk, pure byte-wise shifts/XORs), then XOR the
    planes selected by each output coefficient's bits.  Operand stays
    (k, L) uint8 — 8x smaller than the bit-plane expansion — accumulators
    stay uint8, and the schedule's XOR count is sum(popcount(c_ij)), a
    fraction of the 8m x 8k bit-row schedule.

The gather-reshape -> plane tower -> XOR schedule -> output stack chain is
ONE jitted computation per (matrix, geometry); `PackedPlan.__call__`
accepts an `out=` device buffer and routes through a `donate_argnums`
variant so steady-state aggregated launches (codec/matrix_codec.py's
EncodeAggregator) reuse the parity allocation instead of growing the heap.

Byte-identical to `xor_matmul` and to the host oracle
(gf.bitslice.xor_matmul_host) for every matrix — the schedule is an exact
refactoring of the same GF(2) linear map, verified across geometries by
tests/test_packed_gf.py.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.common.lockdep import make_lock
from ceph_tpu.gf.tables import GF_MUL_TABLE

from .dispatch import record_launch

# xtime reduction byte: 2 * 0x80 in GF(2^8) == generator poly & 0xFF.
# Derived from the table so the kernel can never drift from the host GF.
_XTIME_RED = int(GF_MUL_TABLE[2, 0x80])

# Below this many input bytes the one-kernel-per-(shape) bitsliced matmul
# (matrix as a runtime operand) wins: the packed kernel bakes its XOR
# schedule in at trace time, so every distinct matrix costs a compile —
# fine for the handful of encode matrices and hot decode patterns, wasteful
# for tiny one-off decodes (SHEC's searched inverses on 4 KiB chunks).
PACKED_MIN_BYTES = 64 * 1024


def plane_schedule(gf_matrix: np.ndarray) -> tuple[tuple[tuple[int, int], ...], ...]:
    """(m, k) GF matrix -> per-output-row tuple of (chunk j, power b) terms.

    Output byte i is the XOR of packed planes data[j] * 2^b for every set
    bit b of coefficient gf_matrix[i, j]."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    return tuple(
        tuple(
            (j, b)
            for j in range(k)
            for b in range(8)
            if (int(gfm[i, j]) >> b) & 1
        )
        for i in range(m)
    )


# --- schedule reduction (ISSUE 11) -----------------------------------------
#
# A *plane program* is a straight-line schedule over uint8 plane registers:
# registers 0..k-1 are the input chunk planes data[..., j, :]; each op
# appends one new register, either ("x", a, b) = regs[a] ^ regs[b] or
# ("t", a) = xtime(regs[a]); `outputs` names one register per output row
# (-1 = all-zero row).  The whole tuple is hashable, so it rides the jit's
# static args exactly like the old (j, b) row schedule did, and the SAME
# program executes on device (jnp) and host (numpy) — the fallback oracle
# is derived from the schedule, not re-derived from the matrix.
#
# Three generators, cheapest picked per matrix at plan-build time:
#
# - `naive_program`: the original tower construction — xtime power towers
#   per chunk, then one XOR chain per output row over the selected tower
#   planes.  Cost = tower xtimes + sum(popcount(c_ij)) - rows.
# - `cse_program`: the naive leaves run through greedy pairwise
#   common-subexpression elimination across output rows ("Accelerating
#   XOR-based Erasure Coding using Program Optimization Techniques",
#   arXiv:2108.02692 §4): every tower-plane pair shared by f >= 2 rows is
#   factored into one intermediate, saving f-1 XORs.  By construction
#   cse_cost <= naive_cost for every matrix.
# - `ring_program`: the polynomial-ring evaluation ("Fast XOR-based
#   Erasure Coding based on Polynomial Ring Transforms", arXiv:1701.07731):
#   a coefficient is a polynomial in the ring F2[x]/(p(x)) acting through
#   multiplication-by-x, and xtime is GF(2)-linear — xtime(a ^ b) =
#   xtime(a) ^ xtime(b) — so each output row evaluates Horner-style over
#   its bit levels: row = x*(...x*(x*L_B ^ L_{B-1})...) ^ L_0 with L_b the
#   XOR of the chunks whose coefficient has bit b set.  No towers at all:
#   at most 7 xtimes per OUTPUT row instead of up to 7 per INPUT chunk,
#   which wins exactly when m < k (RS(8,3): 3 rows vs 8 chunk towers).
#
# Cost currency: one op = one vector instruction's worth of work (an XOR,
# or an xtime = shift + carry-fold XOR).  The tier-1 regression bound
# (tests/test_schedule_reduce.py) pins best <= naive per matrix family and
# strictly below for RS(8,3).

_PROG_TAG = "prog"


def naive_program(gf_matrix: np.ndarray) -> tuple:
    """The tower schedule as a plane program (the pre-reduction shape)."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    ops, leaf = _tower_ops(plane_schedule(gfm), k)
    outputs = []
    for row in plane_schedule(gfm):
        outputs.append(_xor_chain(ops, k, [leaf[t] for t in row]))
    return (_PROG_TAG, k, m, tuple(ops), tuple(outputs))


def cse_program(gf_matrix: np.ndarray) -> tuple:
    """Greedy pairwise CSE over the tower leaves (arXiv:2108.02692):
    repeatedly factor the plane pair shared by the most output rows into
    one intermediate register.  Deterministic (ties break on the lowest
    register pair) so the jit cache sees one program per matrix."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    rows_terms = plane_schedule(gfm)
    ops, leaf = _tower_ops(rows_terms, k)
    rows = [set(leaf[t] for t in row) for row in rows_terms]
    while True:
        counts: dict[tuple[int, int], int] = {}
        for row in rows:
            srow = sorted(row)
            for i, a in enumerate(srow):
                for b in srow[i + 1 :]:
                    counts[(a, b)] = counts.get((a, b), 0) + 1
        best = None
        for pair, f in counts.items():
            if f < 2:
                continue
            rank = (f, -pair[0], -pair[1])
            if best is None or rank > best[0]:
                best = (rank, pair)
        if best is None:
            break
        a, b = best[1]
        ops.append(("x", a, b))
        node = k + len(ops) - 1
        for row in rows:
            if a in row and b in row:
                row.discard(a)
                row.discard(b)
                row.add(node)
    outputs = [_xor_chain(ops, k, sorted(row)) for row in rows]
    return (_PROG_TAG, k, m, tuple(ops), tuple(outputs))


def ring_program(gf_matrix: np.ndarray) -> tuple:
    """Horner evaluation over the polynomial ring (arXiv:1701.07731):
    per output row, XOR the bit-level sums and chain multiply-by-x —
    tower-free, at most 7 xtimes per output row."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    ops: list[tuple] = []
    outputs = []
    for i in range(m):
        levels = [
            [j for j in range(k) if (int(gfm[i, j]) >> b) & 1]
            for b in range(8)
        ]
        nonzero = [b for b in range(8) if levels[b]]
        if not nonzero:
            outputs.append(-1)
            continue
        top = nonzero[-1]
        acc = _xor_chain(ops, k, levels[top])
        for b in range(top - 1, -1, -1):
            ops.append(("t", acc))
            acc = k + len(ops) - 1
            if levels[b]:
                lvl = _xor_chain(ops, k, levels[b])
                ops.append(("x", acc, lvl))
                acc = k + len(ops) - 1
        outputs.append(acc)
    return (_PROG_TAG, k, m, tuple(ops), tuple(outputs))


def _tower_ops(rows, k: int):
    """xtime power towers for every (chunk, power) leaf the rows use.
    Returns (ops list, {(j, b): register})."""
    ops: list[tuple] = []
    leaf: dict[tuple[int, int], int] = {}
    max_pow = [0] * k
    for row in rows:
        for j, b in row:
            max_pow[j] = max(max_pow[j], b)
    for j in range(k):
        leaf[(j, 0)] = j
        prev = j
        for b in range(1, max_pow[j] + 1):
            ops.append(("t", prev))
            prev = k + len(ops) - 1
            leaf[(j, b)] = prev
    return ops, leaf


def _xor_chain(ops: list, k: int, regs: list[int]) -> int:
    """Left-to-right XOR chain over registers; returns the result reg
    (-1 for an empty row — an all-zero output)."""
    if not regs:
        return -1
    acc = regs[0]
    for r in regs[1:]:
        ops.append(("x", acc, r))
        acc = k + len(ops) - 1
    return acc


def is_program(sched) -> bool:
    return bool(sched) and sched[0] == _PROG_TAG


def program_cost(prog) -> int:
    """Vector-op count of a plane program (XORs + xtimes)."""
    assert is_program(prog), prog
    return len(prog[3])


# best_program memo: decode matrices churn (one per erasure pattern), and
# the host-fallback oracle re-derives the program per launch without it.
_PROGRAM_MEMO_CAPACITY = 512
_PROGRAM_MEMO: "dict[tuple, tuple]" = {}
_PROGRAM_LOCK = make_lock("packed_program_cache")


def best_program(gf_matrix: np.ndarray) -> tuple:
    """The cheapest schedule for this matrix: min-cost of the naive
    tower, CSE-reduced, and ring-transform constructions (memoized).
    Every candidate is an exact refactoring of the same GF(2) linear map,
    so the choice is pure cost — bytes are identical by construction."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    key = (gfm.shape, gfm.tobytes())
    with _PROGRAM_LOCK:
        cached = _PROGRAM_MEMO.get(key)
    if cached is not None:
        return cached
    candidates = [cse_program(gfm), ring_program(gfm), naive_program(gfm)]
    prog = min(candidates, key=program_cost)
    with _PROGRAM_LOCK:
        if len(_PROGRAM_MEMO) >= _PROGRAM_MEMO_CAPACITY:
            _PROGRAM_MEMO.clear()  # tiny entries; wholesale reset is fine
        _PROGRAM_MEMO.setdefault(key, prog)
        return _PROGRAM_MEMO[key]


def _xtime_host(x: np.ndarray) -> np.ndarray:
    """Host xtime, bit-identical to the device `_xtime` (uint8 shift
    wraps mod 256 in numpy exactly like jnp)."""
    return ((x << 1) ^ ((x >> 7) * np.uint8(_XTIME_RED))).astype(np.uint8)


def run_program_host(prog: tuple, data: np.ndarray) -> np.ndarray:
    """Execute a plane program in pure numpy: (..., k, L) -> (..., m, L).
    This IS the host oracle of the packed kernel — same schedule, same
    xtime, so the DEGRADED-mode fallback can never drift from the device
    bytes.  Never touches the jax runtime."""
    tag, k, m, ops, outputs = prog
    assert tag == _PROG_TAG
    data = np.asarray(data, dtype=np.uint8)
    *lead, kk, L = data.shape
    assert kk == k, (kk, k)
    regs: list[np.ndarray] = [data[..., j, :] for j in range(k)]
    for op in ops:
        if op[0] == "x":
            regs.append(regs[op[1]] ^ regs[op[2]])
        else:
            regs.append(_xtime_host(regs[op[1]]))
    outs = [
        np.zeros((*lead, L), np.uint8) if o < 0 else regs[o]
        for o in outputs
    ]
    return np.stack(outs, axis=-2)


def packed_code_host(gf_matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Host-oracle encode through the SAME reduced schedule the device
    kernel compiles (best_program): (..., k, L) uint8 -> (..., m, L).
    Memory-light next to the bit-matrix oracle — (k + ops) uint8 planes
    instead of the 8x int32 bit-plane expansion."""
    return run_program_host(best_program(gf_matrix), data)


def _xtime(x: jax.Array) -> jax.Array:
    """Packed multiply-by-2 in GF(2^8): byte-wise, carry folded via the
    reduction poly.  uint8 shift-left wraps mod 256, which is exactly the
    discard of the top bit the reduction replaces."""
    return (x << 1) ^ ((x >> 7) * jnp.uint8(_XTIME_RED))


def _packed_code_impl(data: jax.Array, sched, k: int, m: int) -> jax.Array:
    *lead, kk, L = data.shape
    assert kk == k, (kk, k)
    if is_program(sched):
        # reduced straight-line schedule (ISSUE 11): execute the plane
        # program — the same op list run_program_host executes in numpy
        _tag, pk, pm, ops, outputs = sched
        assert (pk, pm) == (k, m), (sched[1:3], k, m)
        regs: list[jax.Array] = [data[..., j, :] for j in range(k)]
        for op in ops:
            if op[0] == "x":
                regs.append(regs[op[1]] ^ regs[op[2]])
            else:
                regs.append(_xtime(regs[op[1]]))
        outs = [
            jnp.zeros((*lead, L), jnp.uint8) if o < 0 else regs[o]
            for o in outputs
        ]
        return jnp.stack(outs, axis=-2)
    # legacy (chunk, power)-row schedule: power towers + per-row chains
    max_pow = [0] * k
    for row in sched:
        for j, b in row:
            max_pow[j] = max(max_pow[j], b)
    towers: list[list[jax.Array]] = []
    for j in range(k):
        t = [data[..., j, :]]
        for _ in range(max_pow[j]):
            t.append(_xtime(t[-1]))
        towers.append(t)
    outs = []
    for i in range(m):
        row = sched[i]
        if not row:
            outs.append(jnp.zeros((*lead, L), jnp.uint8))
            continue
        acc = towers[row[0][0]][row[0][1]]
        for j, b in row[1:]:
            acc = acc ^ towers[j][b]
        outs.append(acc)
    return jnp.stack(outs, axis=-2)


@functools.partial(jax.jit, static_argnames=("sched", "k", "m"))
def _packed_code(data: jax.Array, *, sched, k: int, m: int) -> jax.Array:
    return _packed_code_impl(data, sched, k, m)


@functools.partial(
    jax.jit, static_argnames=("sched", "k", "m"), donate_argnums=(0,)
)
def _packed_code_into(out: jax.Array, data: jax.Array, *, sched, k: int, m: int) -> jax.Array:
    """Donating variant: `out` is a dead parity buffer of the result's
    exact (..., m, L) shape; XLA aliases the result into it, so launches
    at a recurring aggregated geometry stop allocating.  The donated array
    is INVALID after the call — callers own that discipline
    (docs/PERFORMANCE.md, donation caveats)."""
    return _packed_code_impl(data, sched, k, m)


def _packed_verify_impl(codeword: jax.Array, sched, k: int, m: int) -> jax.Array:
    """(..., k+m, L) uint8 codeword -> (...,) uint8 per-stripe mismatch
    bitmap: bit j set iff recomputed parity row j differs from the
    stored row j anywhere in the chunk.  The recompute is the SAME
    packed-plane schedule the encode kernel runs — an exact refactoring
    of the GF(2) linear map — so a zero bitmap is a proof the stored
    parity matches the encode kernel (and the host oracle) bit for bit."""
    data = codeword[..., :k, :]
    stored = codeword[..., k:, :]
    recomputed = _packed_code_impl(data, sched, k, m)
    # per-(stripe, parity-row) mismatch -> packed per-stripe bitmap.
    # m <= 8 for every registered geometry (the uint8 bitmap bound is
    # asserted host-side in PackedVerifyPlan.__init__).
    row_bad = jnp.any(recomputed ^ stored, axis=-1)  # (..., m) bool
    weights = (jnp.uint8(1) << jnp.arange(m, dtype=jnp.uint8))
    return jnp.sum(row_bad.astype(jnp.uint8) * weights, axis=-1).astype(
        jnp.uint8
    )


@functools.partial(jax.jit, static_argnames=("sched", "k", "m"))
def _packed_verify(codeword: jax.Array, *, sched, k: int, m: int) -> jax.Array:
    return _packed_verify_impl(codeword, sched, k, m)


@functools.partial(jax.jit, static_argnames=("sched", "k", "m"))
def _packed_delta(
    old_data: jax.Array,
    new_data: jax.Array,
    old_parity: jax.Array,
    *,
    sched,
    k: int,
    m: int,
) -> jax.Array:
    """RMW parity delta (ISSUE 18), fully on device: the GF(2^8) code is
    linear over GF(2), so

        parity_new = parity_old ^ Encode(data_old ^ data_new)

    with Encode the SAME plane program a full encode would run — the
    delta path can never drift from the materialize path byte-wise.
    (..., k, L) old/new data + (..., m, L) old parity -> (..., m, L) new
    parity, one fused launch, no host round-trip."""
    return old_parity ^ _packed_code_impl(old_data ^ new_data, sched, k, m)


@functools.partial(
    jax.jit, static_argnames=("sched", "k", "m", "chunk")
)
def _packed_delta_flat(
    old_data: tuple,
    new_data: tuple,
    old_parity: tuple,
    *,
    sched,
    k: int,
    m: int,
    chunk: int,
) -> jax.Array:
    """`_packed_delta` over the cache's native layout: k + k + m FLAT
    per-shard device buffers (each a shard's contiguous (stripes*chunk,)
    bytes, exactly what DeviceChunkCache holds) fused into one launch —
    the reshape/stack/xor/encode/xor chain compiles as a single program,
    so a cache-hit RMW pays ONE dispatch and zero host transfers."""
    od = jnp.stack([b.reshape(-1, chunk) for b in old_data], axis=1)
    nd = jnp.stack([b.reshape(-1, chunk) for b in new_data], axis=1)
    op_ = jnp.stack([b.reshape(-1, chunk) for b in old_parity], axis=1)
    return op_ ^ _packed_code_impl(od ^ nd, sched, k, m)


def packed_delta_host(
    gf_matrix: np.ndarray,
    old_data: np.ndarray,
    new_data: np.ndarray,
    old_parity: np.ndarray,
) -> np.ndarray:
    """Host oracle of `_packed_delta`: same chosen program via
    run_program_host, same xor composition — the byte-identity anchor
    the delta-path tests pin the device bytes against."""
    delta = run_program_host(
        best_program(gf_matrix),
        np.asarray(old_data, np.uint8) ^ np.asarray(new_data, np.uint8),
    )
    return np.asarray(old_parity, np.uint8) ^ delta


class PackedVerifyPlan:
    """Compare-only packed-plane plan (ISSUE 9): one fused jit per
    parity matrix that recomputes parity for a (batch, k+m, L) codeword
    window and returns the per-stripe mismatch bitmap instead of chunks
    — the deep-scrub aggregation kernel.  Dispatches count on
    VERIFY_LAUNCHES (and LAUNCHES) so "a whole scrub chunk verified in
    one launch" is a testable dispatch-shape invariant."""

    __slots__ = ("k", "m", "sched")

    def __init__(self, gf_matrix: np.ndarray):
        gfm = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gfm.shape
        assert self.m <= 8, f"mismatch bitmap is uint8; m={self.m} > 8"
        # the recompute is the SAME reduced schedule the encode kernel
        # compiles, so verify stays an exact replay of the encode bytes
        self.sched = best_program(gfm)

    def __call__(self, codeword: jax.Array) -> jax.Array:
        """(..., k+m, L) uint8 -> (...,) uint8 mismatch bitmap."""
        lead = codeword.shape[:-2]
        record_launch(
            int(np.prod(lead)) if lead else 1,
            int(np.prod(codeword.shape)),
            verify=True,
        )
        return _packed_verify(codeword, sched=self.sched, k=self.k, m=self.m)


def packed_verify_host(
    gf_matrix: np.ndarray, codeword: np.ndarray
) -> np.ndarray:
    """Byte-identical HOST oracle of PackedVerifyPlan (pure numpy, never
    touches the jax runtime): the DEGRADED-mode fallback of the verify
    aggregator, and the reference the kernel tests pin the bitmap
    against.  Recomputes parity through the same reduced plane program
    the host encode oracle runs, so both paths agree on every byte."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    assert m <= 8, f"mismatch bitmap is uint8; m={m} > 8"
    cw = np.asarray(codeword, dtype=np.uint8)
    data, stored = cw[..., :k, :], cw[..., k:, :]
    # recompute through the SAME reduced schedule the device kernel
    # compiles (ISSUE 11): the host oracle is derived from the program,
    # not re-derived from the matrix, so the paths cannot drift
    recomputed = packed_code_host(gfm, data)
    row_bad = np.any(recomputed ^ stored, axis=-1)  # (..., m) bool
    weights = (np.uint8(1) << np.arange(m, dtype=np.uint8))
    return np.sum(
        row_bad.astype(np.uint8) * weights, axis=-1, dtype=np.uint8
    )


class PackedPlan:
    """Host-built packed-plane plan: one fused jit per (matrix, geometry).

    The packed analog of pallas_gf.CodingPlan — works on every backend
    (pure jnp), no chunk-length alignment constraint, and the dispatch
    unit the launch counter observes."""

    __slots__ = ("k", "m", "sched", "decode")

    def __init__(self, gf_matrix: np.ndarray, decode: bool = False):
        gfm = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gfm.shape
        # the cheapest of the naive/CSE/ring schedules for THIS matrix
        # (ISSUE 11 schedule reduction); cached in PLAN_CACHE with the
        # plan, and byte-identical to every other construction
        self.sched = best_program(gfm)
        # decode-kind plans additionally count on DECODE_LAUNCHES so
        # recovery batching invariants are assertable on their own
        self.decode = decode

    def _stripes(self, shape) -> int:
        lead = shape[:-2]
        return int(np.prod(lead)) if lead else 1

    def __call__(self, data: jax.Array, out: jax.Array | None = None) -> jax.Array:
        """(..., k, L) uint8 -> (..., m, L) uint8 parity/coded output.

        `out`: optional donated device buffer of the result shape (see
        _packed_code_into); ignored when the shape/dtype does not match."""
        record_launch(
            self._stripes(data.shape), int(np.prod(data.shape)), decode=self.decode
        )
        kw = dict(sched=self.sched, k=self.k, m=self.m)
        want_shape = (*data.shape[:-2], self.m, data.shape[-1])
        if (
            out is not None
            and tuple(getattr(out, "shape", ())) == want_shape
            and getattr(out, "dtype", None) == jnp.uint8
        ):
            return _packed_code_into(out, data, **kw)
        return _packed_code(data, **kw)
