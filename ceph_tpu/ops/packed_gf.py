"""Packed-bitplane GF(2^8) coding — the device hot path without the 8x blow-up.

The original jnp path (ceph_tpu.ops.xor_mm.xor_matmul) inflates every data
byte into 8 int8 bit-planes before an (8m, 8k) int32 matmul: an 8x operand
expansion plus a 4x-wide accumulator, exactly the operand blow-up where
bitmatrix codecs lose their bandwidth ("Accelerating XOR-based Erasure
Coding using Program Optimization Techniques", arXiv:2108.02692).  This
module keeps the planes PACKED 8-per-byte and reorganizes the contraction
around packed words ("Fast Xor-based Erasure Coding based on Polynomial
Ring Transforms", arXiv:1701.07731):

    byte j of a chunk already holds its own 8 bit-planes, packed.  The
    GF(2)-linear action of a coefficient c decomposes over the bits of c:

        c * x = XOR over set bits b of c of (x * 2^b)

    and multiplication by 2 (`xtime`) is itself a packed GF(2) map:

        x * 2 = (x << 1) ^ (0x1d if x & 0x80)      (poly 0x11d, ISA-L's)

    so the whole encode is: build the k x 8 tower of packed power planes
    (7 xtime steps per chunk, pure byte-wise shifts/XORs), then XOR the
    planes selected by each output coefficient's bits.  Operand stays
    (k, L) uint8 — 8x smaller than the bit-plane expansion — accumulators
    stay uint8, and the schedule's XOR count is sum(popcount(c_ij)), a
    fraction of the 8m x 8k bit-row schedule.

The gather-reshape -> plane tower -> XOR schedule -> output stack chain is
ONE jitted computation per (matrix, geometry); `PackedPlan.__call__`
accepts an `out=` device buffer and routes through a `donate_argnums`
variant so steady-state aggregated launches (codec/matrix_codec.py's
EncodeAggregator) reuse the parity allocation instead of growing the heap.

Byte-identical to `xor_matmul` and to the host oracle
(gf.bitslice.xor_matmul_host) for every matrix — the schedule is an exact
refactoring of the same GF(2) linear map, verified across geometries by
tests/test_packed_gf.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ceph_tpu.gf.tables import GF_MUL_TABLE

from .dispatch import record_launch

# xtime reduction byte: 2 * 0x80 in GF(2^8) == generator poly & 0xFF.
# Derived from the table so the kernel can never drift from the host GF.
_XTIME_RED = int(GF_MUL_TABLE[2, 0x80])

# Below this many input bytes the one-kernel-per-(shape) bitsliced matmul
# (matrix as a runtime operand) wins: the packed kernel bakes its XOR
# schedule in at trace time, so every distinct matrix costs a compile —
# fine for the handful of encode matrices and hot decode patterns, wasteful
# for tiny one-off decodes (SHEC's searched inverses on 4 KiB chunks).
PACKED_MIN_BYTES = 64 * 1024


def plane_schedule(gf_matrix: np.ndarray) -> tuple[tuple[tuple[int, int], ...], ...]:
    """(m, k) GF matrix -> per-output-row tuple of (chunk j, power b) terms.

    Output byte i is the XOR of packed planes data[j] * 2^b for every set
    bit b of coefficient gf_matrix[i, j]."""
    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    return tuple(
        tuple(
            (j, b)
            for j in range(k)
            for b in range(8)
            if (int(gfm[i, j]) >> b) & 1
        )
        for i in range(m)
    )


def _xtime(x: jax.Array) -> jax.Array:
    """Packed multiply-by-2 in GF(2^8): byte-wise, carry folded via the
    reduction poly.  uint8 shift-left wraps mod 256, which is exactly the
    discard of the top bit the reduction replaces."""
    return (x << 1) ^ ((x >> 7) * jnp.uint8(_XTIME_RED))


def _packed_code_impl(data: jax.Array, sched, k: int, m: int) -> jax.Array:
    *lead, kk, L = data.shape
    assert kk == k, (kk, k)
    # Power towers only up to the highest bit any coefficient uses.
    max_pow = [0] * k
    for row in sched:
        for j, b in row:
            max_pow[j] = max(max_pow[j], b)
    towers: list[list[jax.Array]] = []
    for j in range(k):
        t = [data[..., j, :]]
        for _ in range(max_pow[j]):
            t.append(_xtime(t[-1]))
        towers.append(t)
    outs = []
    for i in range(m):
        row = sched[i]
        if not row:
            outs.append(jnp.zeros((*lead, L), jnp.uint8))
            continue
        acc = towers[row[0][0]][row[0][1]]
        for j, b in row[1:]:
            acc = acc ^ towers[j][b]
        outs.append(acc)
    return jnp.stack(outs, axis=-2)


@functools.partial(jax.jit, static_argnames=("sched", "k", "m"))
def _packed_code(data: jax.Array, *, sched, k: int, m: int) -> jax.Array:
    return _packed_code_impl(data, sched, k, m)


@functools.partial(
    jax.jit, static_argnames=("sched", "k", "m"), donate_argnums=(0,)
)
def _packed_code_into(out: jax.Array, data: jax.Array, *, sched, k: int, m: int) -> jax.Array:
    """Donating variant: `out` is a dead parity buffer of the result's
    exact (..., m, L) shape; XLA aliases the result into it, so launches
    at a recurring aggregated geometry stop allocating.  The donated array
    is INVALID after the call — callers own that discipline
    (docs/PERFORMANCE.md, donation caveats)."""
    return _packed_code_impl(data, sched, k, m)


def _packed_verify_impl(codeword: jax.Array, sched, k: int, m: int) -> jax.Array:
    """(..., k+m, L) uint8 codeword -> (...,) uint8 per-stripe mismatch
    bitmap: bit j set iff recomputed parity row j differs from the
    stored row j anywhere in the chunk.  The recompute is the SAME
    packed-plane schedule the encode kernel runs — an exact refactoring
    of the GF(2) linear map — so a zero bitmap is a proof the stored
    parity matches the encode kernel (and the host oracle) bit for bit."""
    data = codeword[..., :k, :]
    stored = codeword[..., k:, :]
    recomputed = _packed_code_impl(data, sched, k, m)
    # per-(stripe, parity-row) mismatch -> packed per-stripe bitmap.
    # m <= 8 for every registered geometry (the uint8 bitmap bound is
    # asserted host-side in PackedVerifyPlan.__init__).
    row_bad = jnp.any(recomputed ^ stored, axis=-1)  # (..., m) bool
    weights = (jnp.uint8(1) << jnp.arange(m, dtype=jnp.uint8))
    return jnp.sum(row_bad.astype(jnp.uint8) * weights, axis=-1).astype(
        jnp.uint8
    )


@functools.partial(jax.jit, static_argnames=("sched", "k", "m"))
def _packed_verify(codeword: jax.Array, *, sched, k: int, m: int) -> jax.Array:
    return _packed_verify_impl(codeword, sched, k, m)


class PackedVerifyPlan:
    """Compare-only packed-plane plan (ISSUE 9): one fused jit per
    parity matrix that recomputes parity for a (batch, k+m, L) codeword
    window and returns the per-stripe mismatch bitmap instead of chunks
    — the deep-scrub aggregation kernel.  Dispatches count on
    VERIFY_LAUNCHES (and LAUNCHES) so "a whole scrub chunk verified in
    one launch" is a testable dispatch-shape invariant."""

    __slots__ = ("k", "m", "sched")

    def __init__(self, gf_matrix: np.ndarray):
        gfm = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gfm.shape
        assert self.m <= 8, f"mismatch bitmap is uint8; m={self.m} > 8"
        self.sched = plane_schedule(gfm)

    def __call__(self, codeword: jax.Array) -> jax.Array:
        """(..., k+m, L) uint8 -> (...,) uint8 mismatch bitmap."""
        lead = codeword.shape[:-2]
        record_launch(
            int(np.prod(lead)) if lead else 1,
            int(np.prod(codeword.shape)),
            verify=True,
        )
        return _packed_verify(codeword, sched=self.sched, k=self.k, m=self.m)


def packed_verify_host(
    gf_matrix: np.ndarray, codeword: np.ndarray
) -> np.ndarray:
    """Byte-identical HOST oracle of PackedVerifyPlan (pure numpy, never
    touches the jax runtime): the DEGRADED-mode fallback of the verify
    aggregator, and the reference the kernel tests pin the bitmap
    against.  Recomputes parity through the same expanded bit-matrix the
    host encode oracle uses, so both paths agree on every byte."""
    from ceph_tpu.gf import expand_matrix
    from ceph_tpu.gf.bitslice import xor_matmul_host_batch

    gfm = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gfm.shape
    assert m <= 8, f"mismatch bitmap is uint8; m={m} > 8"
    cw = np.asarray(codeword, dtype=np.uint8)
    data, stored = cw[..., :k, :], cw[..., k:, :]
    recomputed = xor_matmul_host_batch(expand_matrix(gfm), data)
    row_bad = np.any(recomputed ^ stored, axis=-1)  # (..., m) bool
    weights = (np.uint8(1) << np.arange(m, dtype=np.uint8))
    return np.sum(
        row_bad.astype(np.uint8) * weights, axis=-1, dtype=np.uint8
    )


class PackedPlan:
    """Host-built packed-plane plan: one fused jit per (matrix, geometry).

    The packed analog of pallas_gf.CodingPlan — works on every backend
    (pure jnp), no chunk-length alignment constraint, and the dispatch
    unit the launch counter observes."""

    __slots__ = ("k", "m", "sched", "decode")

    def __init__(self, gf_matrix: np.ndarray, decode: bool = False):
        gfm = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gfm.shape
        self.sched = plane_schedule(gfm)
        # decode-kind plans additionally count on DECODE_LAUNCHES so
        # recovery batching invariants are assertable on their own
        self.decode = decode

    def _stripes(self, shape) -> int:
        lead = shape[:-2]
        return int(np.prod(lead)) if lead else 1

    def __call__(self, data: jax.Array, out: jax.Array | None = None) -> jax.Array:
        """(..., k, L) uint8 -> (..., m, L) uint8 parity/coded output.

        `out`: optional donated device buffer of the result shape (see
        _packed_code_into); ignored when the shape/dtype does not match."""
        record_launch(
            self._stripes(data.shape), int(np.prod(data.shape)), decode=self.decode
        )
        kw = dict(sched=self.sched, k=self.k, m=self.m)
        want_shape = (*data.shape[:-2], self.m, data.shape[-1])
        if (
            out is not None
            and tuple(getattr(out, "shape", ())) == want_shape
            and getattr(out, "dtype", None) == jnp.uint8
        ):
            return _packed_code_into(out, data, **kw)
        return _packed_code(data, **kw)
