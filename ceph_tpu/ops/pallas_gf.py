"""Pallas TPU kernel: fused bitsliced GF(2^8) coding.

The perf-critical path behind the 40 GB/s/chip north star (BASELINE.md).  The
jnp reference (ceph_tpu.ops.xor_mm) materializes the 8x bit-plane expansion
and the int32 parity accumulators in HBM, capping throughput at ~1/10 of HBM
bandwidth.  This kernel keeps the whole pipeline in VMEM per tile:

    HBM -> VMEM:  (k, T) uint8 chunk tile            (the only data read)
    VPU:          8 bit-planes per chunk              (shifts/masks, unrolled)
    MXU:          (8m, 8k) @ (8k, T) bf16 matmul, f32 accumulation
    VPU:          mod-2 + fold bits -> (m, T)
    VMEM -> HBM:  (m, T) uint8 parity tile            (the only data write)

so HBM traffic is the information-theoretic minimum: k bytes in, m bytes out
per stripe byte.

Layout choices are driven by Mosaic's tiling and the MXU's native modes:
- planes are computed as int32 (native (8, 128) tiles) and stacked *b-major*:
  piece b is ((data >> b) & 1) with k rows, so the 8 concat pieces are
  sublane-tile multiples for k % 8 == 0 — no relayouts; the single cast of
  the full (8k, T) block to the compute dtype is one aligned relayout.
- the coding matrix is DENSE: exactly 8m rows (byte-major, row i*8 + r holds
  bit r of output byte i) by 8k columns (b-major to match the planes).  8m is
  always a sublane-tile multiple, so the mod-2 fold is a tile-aligned
  (m, 8, T) reshape + weighted sublane reduction — no padded output rows.
  (Earlier revisions padded every output bit-block to 8 rows, computing
  8*8=64 matmul rows for RS(8,3)'s 24: 2.7x wasted MXU work.)
- the matmul runs in bf16 with f32 accumulation — the MXU's native full-rate
  mode.  Operands are 0/1 and sums are bounded by 8k, so bf16/f32 is exact
  for any k <= 2^20.  (f32 operands cost 3-6 MXU passes each; int8 is not
  faster than bf16 for this shape on v5e and needs (32, 128) relayouts.)

One compiled kernel per (rows, k, dtype, shape) serves every coding matrix —
encode, any-erasure decode, LRC locality groups — because the bit-matrix is
an operand, not a constant (the device analog of the reference's LRU
decode-table cache, isa/ErasureCodeIsaTableCache.h:48).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.gf.bitslice import expand_matrix

# Tile of the chunk-length (lane) axis each program processes.  VMEM per
# program is dominated by the int32 planes block: T*(k + 4*8k + 2*8k + 4*8m
# + m) bytes; T=4096 with k=8 is ~1.7 MB, comfortably inside VMEM with
# double-buffered pipelining.
DEFAULT_TILE = 4096


def arrange_dense_matrix(gf_matrix: np.ndarray) -> np.ndarray:
    """(m, k) GF matrix -> dense (8m, 8k) 0/1 matrix in kernel layout.

    Rows are byte-major (row i*8 + r = bit r of output byte i, the natural
    `expand_matrix` order); columns are b-major (col b*k + j = plane b of
    chunk j) to match the kernel's concat-based plane stacking.
    """
    gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gf_matrix.shape
    plain = expand_matrix(gf_matrix)  # rows 8i+r, cols 8j+b
    perm = np.array([j * 8 + b for b in range(8) for j in range(k)])
    return plain[:, perm].astype(np.float32)


def _coding_kernel(bm_ref, data_ref, out_ref, *, k: int, m: int):
    """One (stripe, lane-tile) program: parity tile from a chunk tile."""
    d32 = data_ref[0].astype(jnp.int32)  # (k, T)
    # Bit-plane expansion, b-major stacking: (8k, T) int32, aligned pieces.
    planes = jnp.concatenate([(d32 >> b) & 1 for b in range(8)], axis=0)
    cd = bm_ref.dtype
    acc = jax.lax.dot_general(
        bm_ref[:],
        planes.astype(cd),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32 if cd == jnp.int8 else jnp.float32,
    )  # (8m, T)
    bits = acc.astype(jnp.int32) & 1
    # Fold: output byte i is sum_r bits[i*8 + r] << r — a tile-aligned
    # (m, 8, T) regroup + weighted reduction over the sublane axis.
    t = bits.shape[-1]
    grouped = bits.reshape(m, 8, t)
    weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32)).reshape(1, 8, 1)
    out_ref[0] = (grouped * weights).sum(axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("m", "tile", "interpret"))
def _gf_code_stripes(
    dense_bm: jax.Array,
    data: jax.Array,
    *,
    m: int,
    tile: int,
    interpret: bool = False,
) -> jax.Array:
    s, k, L = data.shape
    assert dense_bm.shape == (8 * m, 8 * k), (dense_bm.shape, m, k)
    assert L % tile == 0, (L, tile)
    grid = (s, L // tile)
    return pl.pallas_call(
        functools.partial(_coding_kernel, k=k, m=m),
        grid=grid,
        interpret=interpret,
        in_specs=[
            pl.BlockSpec(
                (8 * m, 8 * k), lambda i, j: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, m, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((s, m, L), jnp.uint8),
    )(dense_bm, data)


def pick_tile(L: int, cap: int = DEFAULT_TILE) -> int:
    """Largest power-of-two tile <= cap dividing L (L is 128-aligned)."""
    t = cap
    while t > 128 and L % t:
        t //= 2
    return t


class CodingPlan:
    """Host-built plan: GF matrix arranged for the kernel + dispatch info.

    The device-side analog of ISA-L's `ec_init_tables` product
    (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:83-91): built once
    per (matrix, geometry), then applied to any number of stripe batches.
    """

    def __init__(
        self,
        gf_matrix: np.ndarray,
        *,
        interpret: bool = False,
        compute_dtype=jnp.bfloat16,
        tile: int = DEFAULT_TILE,
    ):
        gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gf_matrix.shape
        self.interpret = interpret
        self.tile_cap = tile
        self.bm = jnp.asarray(arrange_dense_matrix(gf_matrix), dtype=compute_dtype)

    def __call__(self, data: jax.Array) -> jax.Array:
        """(..., k, L) uint8 -> (..., m, L) uint8 coded output."""
        *lead, k, L = data.shape
        assert k == self.k, (k, self.k)
        stripes = int(np.prod(lead)) if lead else 1
        flat = data.reshape(stripes, k, L)
        out = _gf_code_stripes(
            self.bm,
            flat,
            m=self.m,
            tile=pick_tile(L, self.tile_cap),
            interpret=self.interpret,
        )
        return out.reshape(*lead, self.m, L)


def gf_code(bit_matrix_or_plan, data: jax.Array) -> jax.Array:
    """Shape-flexible coding entry.

    Accepts a CodingPlan (preferred, TPU path; also runs anywhere with
    interpret=True) or a raw (8m, 8k) bit-matrix (jnp fallback — used
    off-TPU where Pallas TPU kernels can't run).
    """
    if isinstance(bit_matrix_or_plan, CodingPlan):
        plan = bit_matrix_or_plan
        if plan.interpret or jax.devices()[0].platform == "tpu":
            return plan(data)
        raise TypeError("CodingPlan requires a TPU backend; pass a bit-matrix")
    from .xor_mm import xor_matmul

    return xor_matmul(bit_matrix_or_plan, data)
