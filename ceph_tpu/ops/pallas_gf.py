"""Pallas TPU kernel: fused bitsliced GF(2^8) coding.

The perf-critical path behind the 40 GB/s/chip north star (BASELINE.md).  The
jnp reference (ceph_tpu.ops.xor_mm) materializes the 8x bit-plane expansion
and the int32 parity accumulators in HBM, capping throughput at ~1/10 of HBM
bandwidth.  This kernel keeps the whole pipeline in VMEM per tile:

    HBM -> VMEM:  (k, T) uint8 chunk tile           (the only data read)
    VPU:          8 bit-planes per chunk, f32       (shifts/masks, unrolled)
    MXU:          (8*MP, 8k) @ (8k, T) f32 matmul
    VPU:          mod-2 + fold bits -> (m, T)
    VMEM -> HBM:  (m, T) uint8 parity tile          (the only data write)

so HBM traffic is the information-theoretic minimum: k bytes in, m bytes out
per stripe byte.

Layout choices are driven by Mosaic's tiling:
- planes are f32 (native (8, 128) tiles) and stacked *b-major* — piece b is
  ((data >> b) & 1) with k rows, so for k = 8 every concat piece is exactly
  one sublane tile: no relayouts.
- output rows are padded to MP = 8 per bit-block: the coding matrix is
  arranged on host as B'[r*MP + i, b*k + j] = bit r of (C[i,j] * 2^b), so the
  fold reads tile-aligned (MP, T) slices per output bit r.
- f32 accumulation is exact: operands are 0/1, sums bounded by 8k << 2^24.

One compiled kernel per (rows, k, shape) serves every coding matrix — encode,
any-erasure decode, LRC locality groups — because the bit-matrix is an
operand, not a constant (the device analog of the reference's LRU
decode-table cache, isa/ErasureCodeIsaTableCache.h:48).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.gf.bitslice import coeff_bitmatrix

# Rows per bit-block in the arranged matrix (f32 sublane tile height).
MP = 8

# Tile of the chunk-length (lane) axis each program processes.  VMEM per
# program ~= T*(k + 4k + 32k + 32*MP + m) bytes; T=4096 with k=8 is ~1.3 MB.
DEFAULT_TILE = 4096


def arrange_bit_matrix(gf_matrix: np.ndarray) -> np.ndarray:
    """(m, k) GF matrix -> (8*MP, 8k) f32 0/1 matrix in MXU-friendly layout.

    B'[r*MP + i, b*k + j] = bit r of (gf_matrix[i, j] * 2^b); rows i >= m are
    zero padding.  Requires m <= MP (callers split larger codes into row
    groups of MP).
    """
    gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gf_matrix.shape
    assert m <= MP, f"m={m} > {MP}; split the matrix into row groups"
    out = np.zeros((8 * MP, 8 * k), dtype=np.float32)
    for i in range(m):
        for j in range(k):
            c = int(gf_matrix[i, j])
            if c:
                mc = coeff_bitmatrix(c)  # mc[r, b] = bit r of c*2^b
                for r in range(8):
                    for b in range(8):
                        out[r * MP + i, b * k + j] = mc[r, b]
    return out


def _coding_kernel(bm_ref, data_ref, out_ref, *, k: int, m: int):
    """One (stripe, lane-tile) program: parity tile from a chunk tile."""
    d32 = data_ref[0].astype(jnp.int32)  # (k, T)
    # Bit-plane expansion, b-major stacking: (8k, T) f32, tile-aligned pieces.
    planes = jnp.concatenate(
        [((d32 >> b) & 1).astype(jnp.float32) for b in range(8)], axis=0
    )
    acc = jax.lax.dot_general(
        bm_ref[:],
        planes,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(jnp.int32)  # (8*MP, T)
    # Fold: out byte bit r lives in tile-aligned row block [r*MP, r*MP+MP).
    folded = acc[0:MP] & 1
    for r in range(1, 8):
        folded |= (acc[r * MP : (r + 1) * MP] & 1) << r
    out_ref[0] = folded[:m].astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("m", "tile", "interpret"))
def _gf_code_stripes(
    arranged_bm: jax.Array,
    data: jax.Array,
    *,
    m: int,
    tile: int,
    interpret: bool = False,
) -> jax.Array:
    s, k, L = data.shape
    assert arranged_bm.shape == (8 * MP, 8 * k), (arranged_bm.shape, k)
    assert L % tile == 0, (L, tile)
    grid = (s, L // tile)
    return pl.pallas_call(
        functools.partial(_coding_kernel, k=k, m=m),
        grid=grid,
        interpret=interpret,
        in_specs=[
            pl.BlockSpec(
                (8 * MP, 8 * k), lambda i, j: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, k, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, m, tile), lambda i, j: (i, 0, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((s, m, L), jnp.uint8),
    )(arranged_bm, data)


def pick_tile(L: int, cap: int = DEFAULT_TILE) -> int:
    """Largest power-of-two tile <= cap dividing L (L is 128-aligned)."""
    t = cap
    while t > 128 and L % t:
        t //= 2
    return t


class CodingPlan:
    """Host-built plan: GF matrix arranged for the kernel + dispatch info.

    The device-side analog of ISA-L's `ec_init_tables` product: built once
    per (matrix, geometry), then applied to any number of stripe batches.
    Matrices with m > MP rows are split into row groups applied back-to-back.
    """

    def __init__(self, gf_matrix: np.ndarray, *, interpret: bool = False):
        gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gf_matrix.shape
        self.interpret = interpret
        self.groups = [
            jnp.asarray(arrange_bit_matrix(gf_matrix[i : i + MP]))
            for i in range(0, self.m, MP)
        ]

    def __call__(self, data: jax.Array) -> jax.Array:
        """(..., k, L) uint8 -> (..., m, L) uint8 coded output."""
        *lead, k, L = data.shape
        assert k == self.k, (k, self.k)
        stripes = int(np.prod(lead)) if lead else 1
        flat = data.reshape(stripes, k, L)
        tile = pick_tile(L)
        outs = []
        for g, bm in enumerate(self.groups):
            rows = min(MP, self.m - g * MP)
            outs.append(
                _gf_code_stripes(
                    bm, flat, m=rows, tile=tile, interpret=self.interpret
                )
            )
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
        return out.reshape(*lead, self.m, L)


def gf_code(bit_matrix_or_plan, data: jax.Array) -> jax.Array:
    """Shape-flexible coding entry.

    Accepts a CodingPlan (preferred, TPU path) or a raw (8m, 8k) bit-matrix
    (jnp fallback — also used off-TPU where Pallas TPU kernels can't run).
    """
    if isinstance(bit_matrix_or_plan, CodingPlan) and jax.devices()[0].platform == "tpu":
        return bit_matrix_or_plan(data)
    from .xor_mm import xor_matmul

    if isinstance(bit_matrix_or_plan, CodingPlan):
        raise TypeError("CodingPlan requires a TPU backend; pass a bit-matrix")
    return xor_matmul(bit_matrix_or_plan, data)
