"""Pallas TPU kernel: fused SWAR bitsliced GF(2^8) coding.

The perf-critical path behind the 40 GB/s/chip north star (BASELINE.md).
The jnp reference (ceph_tpu.ops.xor_mm) materializes the 8x bit-plane
expansion and int32 parity accumulators in HBM, capping throughput at ~1/10
of HBM bandwidth.  This kernel keeps the whole pipeline in VMEM per tile
and — unlike earlier revisions that fed an (8m, 8k) bit-matrix matmul to
the MXU — does the GF(2) contraction as a compile-time XOR schedule on
SWAR-packed words, because on-chip measurement showed the MXU formulation
was bottlenecked on the VPU-side uint8 -> int32 bit-plane expansion
(the unpacking relayout + 16 vector ops/byte), not on the matmul:

    HBM -> VMEM:  (k, R, C) uint8 chunk tile          (the only data read)
    VMEM:         pltpu.bitcast -> (R/4, C) int32     free register
                  reinterpret: a uint8 tile already packs 4 sublanes per
                  32-bit register row, so "4 bytes per word" costs nothing
    VPU:          plane(j,b) = (word >> b) & 0x01010101   (2 ops / 4 bytes)
    VPU:          out bit-plane = XOR of scheduled planes; GF(2) linearity
                  keeps the 4 packed byte fields independent (no carries:
                  every field holds 0/1)
    VPU:          out word = OR of (plane_r << r)      (byte re-assembly)
    VMEM -> HBM:  (m, R, C) uint8 parity tile          (the only data write)

so HBM traffic is the information-theoretic minimum (k bytes in, m bytes
out per stripe byte) and the inner loop is pure full-width int32 vector
XORs — no MXU, no bf16 casts, no sub-byte relayouts.  The byte->word
grouping the bitcast induces (bytes strided by the lane count) is
immaterial: the transform is byte-elementwise, and the output is bitcast
back through the exact inverse mapping.

The schedule (which input planes XOR into each output bit-plane) is the
bit-expanded coding matrix (gf.bitslice.expand_matrix), baked into the
kernel at trace time.  One compiled kernel per (matrix, geometry) — the
device analog of ISA-L's `ec_init_tables` product
(/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:83-91); decode
matrices get the same treatment through the signature-keyed coder LRU in
codec/matrix_codec.py, mirroring the reference's decode-table cache
(isa/ErasureCodeIsaTableCache.h:48).

Measured on a v5e chip (serial-chain methodology, 256 MiB launches):
52.9 GB/s input-rate vs 56.2 GB/s for a pure HBM copy kernel — i.e. the
kernel runs at ~94% of the achievable memory-bandwidth ceiling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ceph_tpu.gf.bitslice import expand_matrix

# One bit per packed byte field: plane words hold bit b of 4 bytes at bit
# positions {0, 8, 16, 24}.
_FIELD_MASK = 0x01010101

# Per-chunk tile (rows x cols bytes) each program processes.  rows % 4 == 0
# so the sublane bitcast packs exactly; VMEM per program is k data tiles +
# up to 8k int32 plane tiles + m output tiles: ~2.5 MB at (128, 256), k=8.
_GEOMETRY_COLS = (256, 128, 64, 32)
_MAX_ROWS = 128


def pick_geometry(L: int) -> tuple[int, int] | None:
    """(rows, cols) byte tile for chunk length L, or None if unsupported.

    cols is the lane axis (prefer full 128/256-lane tiles), rows the sublane
    axis (must be a multiple of 4 for the uint8->int32 register bitcast).
    Any L that is a multiple of 128 has a geometry (worst case (4, 32)).
    """
    for cols in _GEOMETRY_COLS:
        if L % cols:
            continue
        rows_total = L // cols
        # scan only multiples of 4 (start rounded down, else e.g.
        # rows_total=66 never lands on one and skips this cols entirely)
        r = min(_MAX_ROWS, rows_total - rows_total % 4)
        while r >= 4:
            if rows_total % r == 0:
                return r, cols
            r -= 4
    return None


def schedule_from_matrix(gf_matrix: np.ndarray) -> tuple[tuple[tuple[int, int], ...], ...]:
    """(m, k) GF matrix -> per-output-bit-row tuple of (chunk, bit) terms.

    Row o = 8*i + r of the bit-expanded matrix lists which input planes
    (chunk j, bit b) XOR into bit r of output byte i.
    """
    plain = expand_matrix(np.asarray(gf_matrix, dtype=np.uint8))  # (8m, 8k)
    m8, k8 = plain.shape
    return tuple(
        tuple((c // 8, c % 8) for c in range(k8) if plain[o, c])
        for o in range(m8)
    )


def _swar_kernel(data_ref, out_ref, *, sched, m: int):
    """One (stripe, tile) program: data_ref (1, k, 1, R, C) uint8 ->
    out_ref (1, m, 1, R, C) uint8."""
    _, k, _, r_, c_ = data_ref.shape
    needed = {t for row in sched for t in row}
    planes: dict[tuple[int, int], jax.Array] = {}
    zeros = jnp.zeros((1, r_ // 4, c_), jnp.int32)
    for j in range(k):
        bits = [b for b in range(8) if (j, b) in needed]
        if not bits:
            continue
        d32 = pltpu.bitcast(data_ref[0, j], jnp.int32)  # (1, R/4, C)
        for b in bits:
            shifted = jax.lax.shift_right_logical(d32, b) if b else d32
            planes[(j, b)] = shifted & _FIELD_MASK
    for i in range(m):
        word = None
        for r in range(8):
            row = sched[i * 8 + r]
            if not row:
                continue
            acc = planes[row[0]]
            for t in row[1:]:
                acc = acc ^ planes[t]
            contrib = acc << r if r else acc
            word = contrib if word is None else word | contrib
        if word is None:  # all-zero matrix row
            word = zeros
        out_ref[0, i] = pltpu.bitcast(word, jnp.uint8)


@functools.partial(
    jax.jit, static_argnames=("sched", "m", "rows", "cols", "interpret")
)
def _gf_code_swar(
    data: jax.Array,
    *,
    sched,
    m: int,
    rows: int,
    cols: int,
    interpret: bool = False,
) -> jax.Array:
    s, k, L = data.shape
    tile = rows * cols
    nt = L // tile
    d = data.reshape(s, k, nt, rows, cols)
    out = pl.pallas_call(
        functools.partial(_swar_kernel, sched=sched, m=m),
        grid=(s, nt),
        interpret=interpret,
        in_specs=[
            pl.BlockSpec(
                (1, k, 1, rows, cols),
                lambda i, j: (i, 0, j, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, m, 1, rows, cols),
            lambda i, j: (i, 0, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((s, m, nt, rows, cols), jnp.uint8),
    )(d)
    return out.reshape(s, m, L)


class CodingPlan:
    """Host-built plan: XOR schedule for the kernel + dispatch info.

    The device-side analog of ISA-L's `ec_init_tables` product
    (/root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:83-91): built
    once per (matrix, geometry), then applied to any number of stripe
    batches.  Chunk lengths without a tile geometry (not a multiple of 128)
    fall back to the jnp bitsliced matmul.
    """

    def __init__(
        self, gf_matrix: np.ndarray, *, interpret: bool = False, decode: bool = False
    ):
        gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
        self.m, self.k = gf_matrix.shape
        self.interpret = interpret
        self.sched = schedule_from_matrix(gf_matrix)
        self.bm = jnp.asarray(expand_matrix(gf_matrix), dtype=jnp.uint8)
        self._gf = gf_matrix
        self._packed = None  # lazy packed-plane fallback for unaligned L
        self.decode = decode  # decode-kind plans also count DECODE_LAUNCHES

    def __call__(self, data: jax.Array) -> jax.Array:
        """(..., k, L) uint8 -> (..., m, L) uint8 coded output."""
        from .dispatch import record_launch

        *lead, k, L = data.shape
        assert k == self.k, (k, self.k)
        geom = pick_geometry(L)
        stripes = int(np.prod(lead)) if lead else 1
        if geom is None:
            from .packed_gf import PACKED_MIN_BYTES, PackedPlan
            from .xor_mm import xor_matmul

            if int(np.prod(data.shape)) >= PACKED_MIN_BYTES:
                if self._packed is None:
                    self._packed = PackedPlan(self._gf, decode=self.decode)
                return self._packed(data)
            record_launch(stripes, int(np.prod(data.shape)), decode=self.decode)
            return xor_matmul(self.bm, data)
        rows, cols = geom
        record_launch(stripes, int(np.prod(data.shape)), decode=self.decode)
        flat = data.reshape(stripes, k, L)
        out = _gf_code_swar(
            flat,
            sched=self.sched,
            m=self.m,
            rows=rows,
            cols=cols,
            interpret=self.interpret,
        )
        return out.reshape(*lead, self.m, L)


def gf_code(bit_matrix_or_plan, data: jax.Array) -> jax.Array:
    """Shape-flexible coding entry.

    Accepts a CodingPlan (preferred, TPU path; also runs anywhere with
    interpret=True) or a raw (8m, 8k) bit-matrix (jnp fallback — used
    off-TPU where Pallas TPU kernels can't run).
    """
    if isinstance(bit_matrix_or_plan, CodingPlan):
        plan = bit_matrix_or_plan
        if plan.interpret or jax.devices()[0].platform == "tpu":
            return plan(data)
        raise TypeError("CodingPlan requires a TPU backend; pass a bit-matrix")
    from .xor_mm import xor_matmul

    return xor_matmul(bit_matrix_or_plan, data)
