"""Device kernels: packed-bitplane GF(2^8) coding (packed_gf), bitsliced
XOR-matmul reference paths (xor_mm), the Pallas TPU kernel (pallas_gf),
and the device-launch accounting tests batch-invariants against
(dispatch)."""

from .dispatch import DECODE_LAUNCHES, LAUNCHES, record_launch
from .packed_gf import PackedPlan, plane_schedule
from .xor_mm import as_device_bit_matrix, encode_full, xor_matmul, xor_reduce

__all__ = [
    "DECODE_LAUNCHES",
    "LAUNCHES",
    "PackedPlan",
    "as_device_bit_matrix",
    "encode_full",
    "plane_schedule",
    "record_launch",
    "xor_matmul",
    "xor_reduce",
]
