"""Device kernels: bitsliced GF(2^8) XOR-matmul (jnp + Pallas paths)."""

from .xor_mm import as_device_bit_matrix, encode_full, xor_matmul, xor_reduce

__all__ = ["as_device_bit_matrix", "encode_full", "xor_matmul", "xor_reduce"]
