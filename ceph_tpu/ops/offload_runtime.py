"""Service-agnostic device-offload runtime (ISSUE 20).

The LaunchAggregator / DonationPool / pad-bucket / guard / launch-
scheduler / mempool stack grew up inside codec/matrix_codec.py serving
exactly one client: EC coding launches.  Nothing in it is EC-specific —
a "launch" is any batched per-byte transform with a device plan and a
byte-identical host oracle — so this module hoists the machinery out of
the codec and fronts it with a small service registry:

- **LaunchAggregator** (and its moving parts: AggTicket, DonationPool,
  _PadBuckets, _AggGroup) is the generic aggregation engine.  A service
  subclasses it and supplies the device plan builder (`_dispatch`), the
  byte-identical host oracle (`_dispatch_host`), the output geometry
  (`_out_shape`) and the donation predicate (`_donate_ok`); the engine
  owns windowing, padding, pipelining, donation-pool recycling, QoS
  lane submission (SCHED_CLASS), guard fallback and mempool accounting.
- **register_service / service_aggregator** is the registry: a service
  registers its aggregator factory, QoS lane and host-oracle
  description once; callers reach the shared process-wide instance by
  name.  The EC encode/decode/verify aggregators (still defined in
  codec/matrix_codec.py, now as plain subclasses of this module's
  engine) are the first three entries — zero behavior change, their
  perf names, knobs and import paths are untouched.  The device
  crc32c service (ops/checksum_offload.py) and the batched device
  compressor (compressor/device.py) are the first post-EC riders.
- **offload_perf_dump** flattens every registered service's aggregator
  counters into the `offload.*` slice of the OSD perf report — the
  `ceph_tpu_offload_*` Prometheus families.

Nothing here imports the codec package at module scope (the codec
imports THIS module); the one EC-flavored seam left is that a failed
launch surfaces as `EcError(EIO, ...)` at the reap, imported lazily —
every existing reap path catches exactly that type.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

from ceph_tpu.common.lockdep import make_lock as _lockdep_make_lock
from ceph_tpu.common.lockdep import make_rlock as _lockdep_make_rlock
from ceph_tpu.common.mempool import ledger as _hbm_ledger


def _next_pow2(n: int) -> int:
    return 1 << (max(1, n) - 1).bit_length()


class AggTicket:
    """One submitted stripe-batch coding launch awaiting aggregation.

    Resolves to this submission's (stripes, rows, L) output — parity for
    an encode submission, reconstructed chunks for a decode submission.
    Duck-types the surface PendingEncode/PendingDecode expect of a live
    device array: `is_ready()` for non-blocking polls and `__array__` for
    materialization (np.asarray on a ticket forces its group's launch and
    blocks until it finishes)."""

    __slots__ = ("_agg", "_group", "_start", "_stripes", "_value")

    def __init__(self, agg: "LaunchAggregator", group: "_AggGroup", start: int, stripes: int):
        self._agg = agg
        self._group = group
        self._start = start
        self._stripes = stripes
        self._value: np.ndarray | None = None

    @property
    def launched(self) -> bool:
        if self._value is not None:
            return True
        g = self._group
        return g.host is not None or g.parity is not None or g.error is not None

    def is_ready(self) -> bool:
        if self._value is not None:
            return True
        g = self._group
        if g.host is not None or g.error is not None:
            return True  # a failed launch is "ready": the reap reports it
        if g.parity is None:
            return False  # still windowed; a flush will launch it
        ready = getattr(g.parity, "is_ready", None)
        return True if ready is None else bool(ready())

    def result(self) -> np.ndarray:
        if self._value is None:
            self._agg._materialize(self)
        return self._value

    def __array__(self, dtype=None, copy=None):
        out = self.result()
        return out if dtype is None else out.astype(dtype)


class DonationPool:
    """Per-shape pool of dead device output buffers with per-buffer LIVE
    refcounts (ISSUE 11).  At pipeline depth > 1 several launches'
    outputs are in flight at once; a buffer becomes donatable only after
    ITS producing launch settles — `hold` marks an output live at
    dispatch, `release` at settle, and `take`/`put` refuse live buffers,
    counting any violation on the process-wide invariant gauge
    (`ec_dispatch.pipeline.donation_recycled_live`, asserted 0 by the
    chaos pipelined-wedge phase).  Callers serialize access under the
    aggregator-wide lock; the pool itself is not thread-safe."""

    # ceiling on settled buffers retained per shape: pipeline-depth
    # launches can settle close together, and one slot (the old
    # dict-per-shape pool) would drop all but the last.  The aggregator
    # syncs the effective `cap` to its ring depth — retaining more dead
    # buffers than launches that can be in flight would just pin HBM
    # (each pooled RS(8,3) output of a large launch is tens of MiB).
    SLOT_CAP = 4

    __slots__ = ("_free", "_live", "cap", "_mem")

    def __init__(self, cap: int | None = None) -> None:
        self._free: dict[tuple, list] = {}
        self._live: dict[int, int] = {}  # id(buf) -> refcount
        self.cap = self.SLOT_CAP if cap is None else max(1, int(cap))
        # HBM ledger handles per pooled FREE buffer (ISSUE 13): pooled
        # dead buffers are resident device memory nothing else accounts
        # for.  Handles are buffer-finalized too, so a pool dropped with
        # buffers still slotted cannot leak ledger bytes.
        self._mem: dict[int, object] = {}

    def hold(self, buf) -> None:
        self._live[id(buf)] = self._live.get(id(buf), 0) + 1

    def release(self, buf) -> None:
        key = id(buf)
        refs = self._live.get(key, 0) - 1
        if refs <= 0:
            self._live.pop(key, None)
        else:
            self._live[key] = refs

    def _mem_release(self, buf) -> int:
        """Close a pooled buffer's ledger handle; returns its bytes."""
        h = self._mem.pop(id(buf), None)
        if h is None:
            return 0
        nbytes = h.nbytes
        h.free()
        return nbytes

    def take(self, shape):
        from ceph_tpu.ops.dispatch import PIPELINE

        slot = self._free.get(tuple(shape))
        if not slot:
            return None
        buf = slot.pop()
        self._mem_release(buf)  # leaving the free list either way
        if id(buf) in self._live:
            PIPELINE.record_donation(reused=False, live=True)
            return None  # never hand out a live buffer
        PIPELINE.record_donation(reused=True)
        return buf

    def put(self, shape, buf) -> None:
        from ceph_tpu.ops.dispatch import PIPELINE

        if id(buf) in self._live:
            # pooling an unsettled launch's output would let a later
            # launch donate (and XLA invalidate) bytes a reaper still
            # needs — refuse and count the invariant violation
            PIPELINE.record_donation(reused=False, live=True)
            return
        led = _hbm_ledger()
        if led.donation_capped:
            # HBM pressure stage 2: retention capped — dead buffers go
            # back to the allocator instead of pinning device memory
            return
        slot = self._free.setdefault(tuple(shape), [])
        slot.append(buf)
        self._mem[id(buf)] = led.alloc(
            "ec_donation", int(getattr(buf, "nbytes", 0) or 0), buf=buf
        )
        while len(slot) > self.cap:
            # oldest out — also trims promptly after a runtime cap
            # shrink (a pipeline-depth config drop)
            self._mem_release(slot.pop(0))

    def drop_free(self) -> int:
        """Drop every FREE pooled buffer (HBM pressure stage 2);
        returns the bytes released.  Live refcounts are untouched —
        in-flight launches still settle normally."""
        freed = 0
        for slot in self._free.values():
            for buf in slot:
                freed += self._mem_release(buf)
        self._free.clear()
        return freed

    def drop_batch(self, batch: int) -> int:
        """Drop the FREE pooled buffers whose leading (batch) dimension
        is `batch` — shapes a retired pad bucket can no longer produce
        (ISSUE 18): when the bucket learner evicts a target, every
        pooled output at that geometry is dead weight, and bucket churn
        must not pin HBM in the mempool ledger.  Returns bytes freed;
        live refcounts are untouched."""
        freed = 0
        for shape in [s for s in self._free if s and s[0] == batch]:
            for buf in self._free.pop(shape):
                freed += self._mem_release(buf)
        return freed

    # mapping-ish view (tests and introspection): the shapes with at
    # least one FREE buffer pooled
    def __iter__(self):
        return iter([s for s, slot in self._free.items() if slot])

    def __len__(self) -> int:
        return sum(1 for slot in self._free.values() if slot)


class _PadBuckets:
    """Learned launch-size buckets for one (matrix, chunk-size) group
    key (ISSUE 18): replaces the static pow2/64-multiple `_pad_target`
    with a small set of batch sizes the key's workload actually
    produces.  A batch size seen `PROMOTE_AFTER` times becomes a bucket
    (padding a recurring 23-stripe launch to 32 wastes 28% of every
    launch forever; padding it to 23 wastes nothing and still recurs
    for the jit cache and the donation pool); the slot set is bounded
    and LRU-evicted so the jit-cache geometry count stays capped, and
    the caller drops the evicted target's pooled output buffers
    (DonationPool.drop_batch).  A padding-waste EWMA per key feeds the
    `padding_waste_ratio` export.  Callers serialize access under the
    aggregator-wide lock."""

    PROMOTE_AFTER = 3
    EWMA_ALPHA = 0.2
    # candidate-count map bound: recurring sizes promote out of it long
    # before this; a never-repeating workload must not grow it unboundedly
    CANDIDATE_CAP = 64

    __slots__ = ("buckets", "_counts", "_lru", "_seq", "waste_ewma")

    def __init__(self) -> None:
        self.buckets: list[int] = []  # sorted learned batch targets
        self._counts: "OrderedDict[int, int]" = OrderedDict()
        self._lru: dict[int, int] = {}  # bucket -> last-use seq
        self._seq = 0
        self.waste_ewma = 0.0

    def target(self, stripes: int, static: int, cap: int) -> tuple[int, int | None]:
        """(pad target for `stripes`, evicted bucket or None).

        The smallest learned bucket >= `stripes` wins when it beats the
        static bucket; otherwise the static target stands.  Learning:
        `stripes` itself is promoted to a bucket once seen
        PROMOTE_AFTER times (exact fit = zero waste for the recurring
        size); past `cap` buckets the least-recently-used target is
        evicted and returned so the caller can drop its pooled buffers."""
        self._seq += 1
        evicted: int | None = None
        target = static
        for b in self.buckets:  # sorted: first fit is smallest
            if b >= stripes:
                if b < static:
                    target = b
                break
        if target in self._lru:
            self._lru[target] = self._seq
        if target != stripes and stripes not in self.buckets:
            # static padding is wasting stripes on this size: count it
            # toward promotion
            seen = self._counts.get(stripes, 0) + 1
            if seen >= self.PROMOTE_AFTER:
                self._counts.pop(stripes, None)
                self.buckets.append(stripes)
                self.buckets.sort()
                self._lru[stripes] = self._seq
                target = stripes
                if len(self.buckets) > max(1, cap):
                    evicted = min(self.buckets, key=lambda b: self._lru[b])
                    self.buckets.remove(evicted)
                    self._lru.pop(evicted, None)
                    if evicted == target:  # evicted ourselves: static stands
                        target = static
            else:
                self._counts[stripes] = seen
                self._counts.move_to_end(stripes)
                while len(self._counts) > self.CANDIDATE_CAP:
                    self._counts.popitem(last=False)
        waste = (target - stripes) / target if target else 0.0
        self.waste_ewma += self.EWMA_ALPHA * (waste - self.waste_ewma)
        return target, evicted


class _AggGroup:
    """Pending submissions sharing one (matrix, chunk-length) geometry —
    the unit that concatenates into a single padded device launch."""

    __slots__ = (
        "key", "ec", "ctx", "arrays", "tickets", "stripes", "nbytes",
        "parity", "host", "pad", "error", "donatable", "lock",
        "input", "credit", "flight", "submit_ts", "stalled", "held",
        "mem", "fused_windows",
    )

    def __init__(self, key, ec, ctx=None):
        self.key = key
        self.ec = ec
        self.ctx = ctx  # per-kind dispatch context (decode: erasure tuple)
        self.arrays: list[np.ndarray] = []
        self.tickets: list[AggTicket] = []
        self.stripes = 0
        self.nbytes = 0
        self.parity = None  # live device array once launched
        self.host: np.ndarray | None = None  # materialized parity
        self.pad = 0
        self.error: BaseException | None = None  # a failed launch, sticky
        self.donatable = False  # launch path can reuse a donated buffer
        # the in-flight launch's device output, refcounted in the
        # donation pool from dispatch until settle (pipeline depth > 1)
        self.held = None
        # HBM ledger handle for that in-flight output (ISSUE 13):
        # alloc'd at dispatch, freed at settle on every outcome —
        # host-fallback and sticky-error settles included
        self.mem = None
        # concatenated padded launch input, retained from launch until
        # settle so a device that wedges AFTER dispatch can still be
        # recomputed on the host oracle
        self.input: np.ndarray | None = None
        self.credit = 0  # inflight-byte throttle credit held by this group
        # flight-recorder state (ISSUE 8): the launch's record, the
        # window-open timestamp queue-wait anchors on, and whether any
        # submitter hit the backpressure bound getting in
        self.flight: dict | None = None
        self.submit_ts = time.monotonic()
        self.stalled = False
        # super-launch fusion (ISSUE 18): > 0 once this group's window
        # trip was deferred because the in-flight ring was full — the
        # group keeps accumulating whole windows behind the backlog and
        # launches them fused (one dispatch, per-ticket settle slices)
        self.fused_windows = 0
        # serializes THIS group's launch/materialization (the encode
        # dispatch + blocking device wait) without stalling the
        # aggregator-wide lock; RLock because a reap-forced launch runs
        # inside the reap's own hold
        self.lock = threading.RLock()


class LaunchAggregator:
    """Cross-op launch aggregation: coalesce concurrent small stripe-batch
    coding calls (different ops, PGs, objects) into one padded device
    launch.  Shared machinery of the encode and decode aggregators; the
    subclasses supply the group key and the device dispatch.

    The storage-side analog of a training stack's bucketed all-reduce:
    per-op launches under ~1 MiB are dominated by dispatch overhead, so
    submissions queue in per-geometry groups and launch together when the
    window fills, the byte budget trips, or a barrier drains the window
    (ECBackend.flush_encodes / flush_decodes — or any ticket reap).
    window <= 1 launches every submission immediately (aggregation off,
    metrics still recorded).

    In aggregating mode, stripe counts are padded to a bounded bucket set
    (power of two up to 64, then multiples of 64 — capped waste, unlike
    pure pow2) so the jit cache sees few geometries and the donation pool
    can recycle output buffers across launches (see docs/PERFORMANCE.md
    for the donation caveats).  Tickets slice their own stripes back out,
    in submission order.

    Occupancy and launch-size distributions are PerfHistograms on
    `self.perf`, exportable through the PR-1 prometheus layer
    (PerfCountersCollection.add(agg.perf))."""

    PERF_NAME = "ec_aggregator"
    WHAT = "encode"  # used in error reports
    # QoS lane every launch of this aggregator dispatches under (ISSUE 9
    # launch scheduler): client encodes preempt queued background work;
    # the decode/verify subclasses override with their own lane.
    SCHED_CLASS = "client"
    # HBM ledger pool this aggregator's in-flight launch outputs charge
    # (ISSUE 13); the verify subclass charges its own pool so the leak
    # gate can drain-check the EC data path and scrub independently.
    MEM_POOL = "ec_pipeline_inflight"

    def __init__(self, window: int = 0, max_bytes: int = 64 << 20,
                 pad_pow2: bool = True, inflight_max_bytes: int | None = None,
                 pipeline_depth: int | None = None,
                 fuse_max_windows: int | None = None,
                 pad_buckets: int | None = None):
        from ceph_tpu.common.perf_counters import PerfCountersBuilder
        from ceph_tpu.common.throttle import Throttle

        self.window = int(window)
        self.max_bytes = int(max_bytes)
        self.pad_pow2 = pad_pow2
        # depth-N asynchronous launch pipeline (ISSUE 11): how many
        # launched-but-unsettled groups may be in flight before a new
        # launch first settles the oldest — the settle happens AFTER the
        # new dispatch, so window N+1's H2D overlaps window N's kernel.
        # <= 0 disables the ring (in-flight bounded only by the byte
        # throttle, the pre-ISSUE-11 behavior).
        if pipeline_depth is None:
            from ceph_tpu.common.options import OPTIONS

            pipeline_depth = int(OPTIONS["ec_tpu_pipeline_depth"].default)
        self.pipeline_depth = int(pipeline_depth)
        # super-launch fusion bound (ISSUE 18): with the in-flight ring
        # full, a group whose window trips may keep accumulating up to
        # this many windows and launch them as ONE fused dispatch —
        # amortizing dispatch overhead exactly when the backlog proves
        # demand.  <= 1 disables fusion (every window trip launches).
        if fuse_max_windows is None:
            from ceph_tpu.common.options import OPTIONS

            fuse_max_windows = int(OPTIONS["ec_tpu_fuse_max_windows"].default)
        self.fuse_max_windows = int(fuse_max_windows)
        # learned pad-bucket slots per group key (ISSUE 18): recurring
        # batch sizes promote to exact-fit launch targets, bounded and
        # LRU-evicted so the jit cache stays capped.  <= 0 keeps the
        # static pow2/64-multiple targets only.
        if pad_buckets is None:
            from ceph_tpu.common.options import OPTIONS

            pad_buckets = int(OPTIONS["ec_tpu_pad_buckets"].default)
        self.pad_buckets = int(pad_buckets)
        self._pad_state: dict[tuple, _PadBuckets] = {}
        from ceph_tpu.ops.dispatch import PIPELINE

        PIPELINE.set_depth(self.pipeline_depth)
        # RLock: a reap (`_materialize`) forces its group's launch from
        # inside the lock (make_rlock: per-instance reentrant, ordering
        # still validated on the outermost acquire)
        self._lock = _lockdep_make_rlock(self.PERF_NAME)
        self._groups: "OrderedDict[tuple, _AggGroup]" = OrderedDict()
        # per-shape retention follows the ring depth: more dead buffers
        # than launches that can be in flight would only pin HBM
        self._donate_pool = DonationPool(
            cap=min(DonationPool.SLOT_CAP, max(1, self.pipeline_depth))
        )
        # end-to-end backpressure (ec_tpu_inflight_max_bytes): byte credit
        # over everything admitted but not yet settled — windowed groups
        # AND launched-but-unreaped ones.  Over the bound, _admit makes
        # the SUBMITTER settle older launches first.
        if inflight_max_bytes is None:
            from ceph_tpu.common.options import OPTIONS

            inflight_max_bytes = int(OPTIONS["ec_tpu_inflight_max_bytes"].default)
        self.inflight = Throttle(
            f"{self.PERF_NAME}.inflight", int(inflight_max_bytes)
        )
        self._live: list[_AggGroup] = []  # launched, not yet settled (FIFO)
        b = PerfCountersBuilder(self.PERF_NAME)
        for c in ("submits", "launches", "flush_window", "flush_bytes",
                  "flush_explicit", "flush_immediate", "flush_reap",
                  "flush_backpressure", "pad_stripes", "host_fallbacks",
                  "throttle_stalls", "fused_launches", "fused_windows"):
            b.add_u64_counter(c)
        b.add_histogram("stripes_per_launch",
                        "stripe-batch occupancy of each device launch",
                        lowest=1, buckets=14)
        b.add_histogram("tickets_per_launch",
                        "submissions coalesced into each device launch",
                        lowest=1, buckets=8)
        b.add_histogram("launch_bytes",
                        "input bytes per device launch",
                        lowest=4096, buckets=18)
        self.perf = b.create_perf_counters()
        # live-aggregator registry (ISSUE 13): HBM pressure's stage-2
        # trim and the leak-gate drain reach every instance through it
        _AGGREGATORS.add(self)

    def configure(self, window: int | None = None, max_bytes: int | None = None,
                  inflight_max_bytes: int | None = None,
                  pipeline_depth: int | None = None,
                  fuse_max_windows: int | None = None,
                  pad_buckets: int | None = None) -> None:
        """Apply live config (the OSD wires its Config + runtime observers
        here, so the aggregate_* settings reach the shared instance)."""
        if window is not None:
            self.window = int(window)
        if max_bytes is not None:
            self.max_bytes = int(max_bytes)
        if inflight_max_bytes is not None:
            self.inflight.limit = int(inflight_max_bytes)
        if fuse_max_windows is not None:
            self.fuse_max_windows = int(fuse_max_windows)
        if pad_buckets is not None:
            self.pad_buckets = int(pad_buckets)
            with self._lock:
                # shrinking the bucket bound must trim now-dead shapes:
                # retired targets' pooled outputs would pin HBM forever
                for state in self._pad_state.values():
                    while len(state.buckets) > max(1, self.pad_buckets):
                        gone = min(
                            state.buckets, key=lambda b: state._lru[b]
                        )
                        state.buckets.remove(gone)
                        state._lru.pop(gone, None)
                        self._donate_pool.drop_batch(gone)
                if self.pad_buckets <= 0:
                    for state in self._pad_state.values():
                        for b in state.buckets:
                            self._donate_pool.drop_batch(b)
                    self._pad_state.clear()
        if pipeline_depth is not None:
            self.pipeline_depth = int(pipeline_depth)
            with self._lock:
                self._donate_pool.cap = min(
                    DonationPool.SLOT_CAP, max(1, self.pipeline_depth)
                )
            from ceph_tpu.ops.dispatch import PIPELINE

            PIPELINE.set_depth(self.pipeline_depth)

    # -- subclass hooks ------------------------------------------------------

    def _dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        raise NotImplementedError

    def _dispatch_host(self, g: _AggGroup, data: np.ndarray) -> np.ndarray:
        """Byte-identical host-oracle recompute of `_dispatch` (pure
        numpy): the DEGRADED-mode path a wedged device cannot hang."""
        raise NotImplementedError

    def _out_shape(self, g: _AggGroup, data_shape) -> tuple:
        raise NotImplementedError

    def _donate_ok(self, g: _AggGroup, data_shape) -> bool:
        raise NotImplementedError

    # -- submission ----------------------------------------------------------

    def _submit(self, key, ec, ctx, shaped: np.ndarray) -> AggTicket:
        """Queue one (stripes, k, L) uint8 batch under `key`; returns its
        ticket.  May launch (this or earlier submissions) when a threshold
        trips.  Admission is throttled: past ec_tpu_inflight_max_bytes of
        unsettled work, this call settles older launches first."""
        stripes = shaped.shape[0]
        # HBM pressure hook (ISSUE 13): time-throttled, no locks held —
        # under a target, sustained submission pressure trims the cache
        # / caps donation retention / clamps depth without waiting for
        # the next status beacon
        _hbm_ledger().maybe_check_pressure()
        stalled = self._admit(shaped.nbytes)
        reason = None
        with self._lock:
            self.perf.inc("submits")
            g = self._groups.get(key)
            if g is None:
                g = self._groups[key] = _AggGroup(key, ec, ctx)
            if stalled:
                g.stalled = True  # flight record flags the stall
            ticket = AggTicket(self, g, g.stripes, stripes)
            g.arrays.append(shaped)
            g.tickets.append(ticket)
            g.stripes += stripes
            g.nbytes += shaped.nbytes
            g.credit += shaped.nbytes
            if self.window <= 1:
                reason = "flush_immediate"
            elif g.nbytes >= self.max_bytes:
                reason = "flush_bytes"
            elif len(g.tickets) >= self.window:
                reason = "flush_window"
                # super-launch fusion (ISSUE 18): the window tripped but
                # the in-flight ring is full — launching now would only
                # queue a dispatch behind the backlog.  Defer the trip
                # (the group stays windowed, accumulating whole windows)
                # until the ring drains, the fuse bound or byte budget
                # trips, or a barrier/reap flushes: the deferred windows
                # then ride ONE fused dispatch, amortizing its overhead
                # exactly when demand is proven.  Per-ticket settle
                # slices, QoS arbitration, and the host-oracle fallback
                # are untouched — a fused group is just a bigger group.
                if (
                    self.fuse_max_windows > 1
                    and self.pipeline_depth > 0
                    and len(self._live) >= self.pipeline_depth
                    and len(g.tickets) < self.window * self.fuse_max_windows
                    and g.nbytes < self.max_bytes
                ):
                    g.fused_windows = len(g.tickets) // self.window
                    reason = None
            if reason is not None:
                self._groups.pop(key, None)  # detach under the lock...
        if reason is not None:
            try:
                self._launch(g, reason)  # ...dispatch/compile outside it
            except Exception:
                # sticky on the group: every co-rider's reap reports it
                # (raising here would blame an arbitrary submitter and
                # tear down its unrelated write)
                pass
            # pipeline ring (ISSUE 11): AFTER the new launch dispatched,
            # settle down to the depth bound — the new window's H2D ran
            # before the oldest's blocking wait, which is the overlap
            self._drain_pipeline()
        return ticket

    def _drain_pipeline(self) -> None:
        """Bound the in-flight launch set at `ec_tpu_pipeline_depth` by
        settling the oldest launches.  Runs with NO locks held (a settle
        takes the victim group's lock; holding another group's lock here
        would deadlock two submitters draining each other)."""
        depth = self.pipeline_depth
        if depth <= 0:
            return
        if _hbm_ledger().depth_clamped:
            # HBM pressure stage 3: one launch's output in flight at a
            # time — overlap traded for bounded residency until relief
            depth = 1
        from ceph_tpu.ops.dispatch import PIPELINE

        while True:
            with self._lock:
                if len(self._live) <= depth:
                    return
                g = self._live[0]
            PIPELINE.record_drain()
            self._settle(g)
            with self._lock:
                if g in self._live:  # defensive: settle always removes
                    return

    def _admit(self, nbytes: int) -> bool:
        """Backpressure admission (the byte Throttle): take credit for a
        submission; over the bound, the SUBMITTER settles the oldest
        outstanding launches — paying the drain latency itself — until
        credit frees.  Pushing back on the producer is the point: a
        degraded/slow backend must stall its writers, not queue device
        work unboundedly.  A single submission larger than the whole
        bound is admitted once nothing older remains (the reference
        Throttle's oversized-request semantics: the dispatch path must
        not wedge).  Returns True when the submitter stalled (the flight
        record of the launch it rides flags `throttle_stall`)."""
        if self.inflight.get_or_fail(nbytes):
            return False
        self.perf.inc("throttle_stalls")
        while not self.inflight.get_or_fail(nbytes):
            if not self._settle_oldest():
                self.inflight.take(nbytes)  # oversized: admit anyway
                break
        return True

    def _settle_oldest(self) -> bool:
        """Settle one outstanding group, oldest first — launched groups
        before windowed ones (their credit frees on a blocking wait;
        windowed groups must be launched first).  False when nothing is
        outstanding."""
        with self._lock:
            if self._live:
                g = self._live[0]
            elif self._groups:
                g = next(iter(self._groups.values()))
            else:
                return False
        if g.parity is None and g.host is None and g.error is None:
            with self._lock:
                if self._groups.get(g.key) is g:
                    del self._groups[g.key]
            try:
                self._launch(g, "flush_backpressure")
            except Exception:
                pass  # sticky on the group; settle releases its credit
        self._settle(g)
        return True

    def pending(self) -> int:
        """Submissions queued but not yet launched."""
        with self._lock:
            return sum(len(g.tickets) for g in self._groups.values())

    def drain(self) -> None:
        """Settle EVERYTHING: flush the windowed groups, then settle
        every launched group oldest-first.  The HBM leak gate's
        teardown hook — after a drain the in-flight ledger pool must
        read zero (sticky errors settle too; they just stay sticky for
        their tickets' reaps)."""
        self.flush()
        while True:
            with self._lock:
                g = self._live[0] if self._live else None
            if g is None:
                return
            self._settle(g)

    def flush(self) -> None:
        """Launch every windowed group, FIFO (the commit barrier)."""
        with self._lock:
            detached = list(self._groups.values())
            self._groups.clear()
        for g in detached:
            try:
                self._launch(g, "flush_explicit")
            except Exception:
                continue  # sticky on the group; other groups still launch
        if detached:
            # a fused group deferred past a full ring (ISSUE 18) launches
            # here — re-bound the in-flight set at the depth budget
            self._drain_pipeline()

    # -- launch + reap -------------------------------------------------------

    def _pad_target(self, stripes: int) -> int:
        """Launch-size bucket: pow2 up to 64 stripes, then multiples of 64.
        Bounds both the jit-cache geometry count AND the padding waste
        (pure pow2 would pad up to 2x on exactly the biggest launches the
        byte budget exists to bound)."""
        if stripes <= 64:
            return _next_pow2(stripes)
        return -(-stripes // 64) * 64

    def _pad_target_for(self, key, stripes: int) -> int:
        """Bucketed pad specialization (ISSUE 18): the static bucket,
        improved by the per-key learner when this key's workload keeps
        producing a batch size the static rounding wastes stripes on.
        Updates the key's waste EWMA and the process-wide pad_waste
        slice inputs; evicted bucket targets drop their pooled output
        buffers so bucket churn cannot pin HBM."""
        static = self._pad_target(stripes)
        if self.pad_buckets <= 0:
            return static
        with self._lock:
            state = self._pad_state.get(key)
            if state is None:
                state = self._pad_state[key] = _PadBuckets()
            target, evicted = state.target(stripes, static, self.pad_buckets)
            if evicted is not None:
                self._donate_pool.drop_batch(evicted)
        return target

    def padding_waste(self) -> dict[str, float]:
        """Per-key padding-waste EWMA snapshot (introspection/tests),
        keyed by the group label `_group_label` would give the key."""
        import zlib

        with self._lock:
            out = {}
            for key, state in self._pad_state.items():
                chunk = key[-1] if key and isinstance(key[-1], int) else 0
                digest = zlib.crc32(repr(key).encode())
                label = f"{self.PERF_NAME}/{digest:08x}/L{chunk}"
                out[label] = state.waste_ewma
            return out

    def _launch(self, g: _AggGroup, reason: str) -> None:
        """Concatenate a (detached) group's submissions into one padded
        device launch.  Runs OUTSIDE the aggregator-wide lock: the encode
        dispatch — including a first-time jit compile, seconds on a
        remote-compile TPU path — must not stall other geometries'
        submits.  The group lock serializes against same-group reaps."""
        with g.lock:
            if g.parity is not None or g.host is not None or g.error is not None:
                return
            data = g.arrays[0] if len(g.arrays) == 1 else np.concatenate(g.arrays)
            # pad only in aggregating mode: with the window off, every
            # write would pay a concatenate copy + dead-stripe encode the
            # direct path never did
            pad = 0
            if self.pad_pow2 and self.window > 1:
                pad = self._pad_target_for(g.key, g.stripes) - g.stripes
            if pad:
                data = np.concatenate(
                    [data, np.zeros((pad, *data.shape[1:]), dtype=np.uint8)]
                )
            out_shape = self._out_shape(g, data.shape)
            # the donation pool only pays off when the coder's dispatch
            # will actually consume the donated buffer (the packed jnp
            # path); on e.g. the Pallas path pooling would just hold dead
            # device memory an extra launch
            g.donatable = self._donate_ok(g, data.shape)
            donate = None
            if g.donatable:
                with self._lock:
                    donate = self._donate_pool.take(out_shape)
            # retained until settle: a device that wedges AFTER this
            # dispatch is recomputed from these exact bytes on the host
            g.input = data
            # flight record (ISSUE 8): the launch's timeline entry.
            # queue_wait anchors on the group's window-open timestamp;
            # the guarded dispatch runs inside the record's scope so
            # ops/dispatch.py annotates devices and ops/guard.py flags
            # deadline hits on THIS record.
            from ceph_tpu.ops.flight_recorder import flight_recorder, new_record

            fr = flight_recorder()
            rec = g.flight = new_record(
                self.WHAT,
                group=self._group_label(g),
                tickets=len(g.tickets),
                stripes=g.stripes,
                batch=data.shape[0],
                nbytes=data.nbytes,
                submit_ts=g.submit_ts,
                reason=reason,
                sched_class=self.SCHED_CLASS,
            )
            rec["pad_stripes"] = pad
            # fused verdict (ISSUE 18): the deferral armed AND the group
            # actually accumulated more than one window before launching
            # (a reap right after the deferral is a plain launch)
            fused_windows = 0
            if g.fused_windows and self.window > 1:
                fused_windows = len(g.tickets) // self.window
            if fused_windows > 1:
                rec["flags"]["fused"] = True
                rec["fused_windows"] = fused_windows
            if g.stalled:
                rec["flags"]["throttle_stall"] = True
            # QoS arbitration (ISSUE 9): the ready launch enters the
            # shared device queue tagged with this aggregator's lane and
            # leaves it in dmClock tag order — a queued client encode
            # dequeues ahead of a queued background verify.  The
            # scheduler runs the dispatch under THIS context (captured
            # at submit), so the active flight record and tracer scope
            # survive even when another submitter's drain executes it.
            # Timing anchors live INSIDE the scheduled callable: time
            # spent queued behind other classes' launches (or spent
            # cooperatively executing them) is queue wait, not h2d —
            # banking it as busy would double-count wall clock across
            # concurrent records and overstate occupancy under exactly
            # the contention the scheduler creates.
            from ceph_tpu.ops.launch_scheduler import (
                CLASS_BY_LANE,
                launch_scheduler,
            )

            t_enqueue = time.monotonic()
            timing: dict[str, float] = {}

            def _dispatch_scheduled():
                timing["t_dispatch"] = time.monotonic()
                out = self._guarded_dispatch(g, data, donate)
                timing["t_done"] = time.monotonic()
                return out

            from ceph_tpu.ops.guard import device_guard

            try:
                with fr.active_scope(rec):
                    if device_guard().degraded:
                        # DEGRADED bypass: this launch re-runs on the
                        # host oracle (or at most a rate-limited compile
                        # probe), so there is no device to arbitrate —
                        # routing it through the device turn would
                        # serialize every lane's numpy recompute behind
                        # one lock, head-of-line-blocking client encodes
                        # exactly when the backend is already hurting
                        parity = _dispatch_scheduled()
                    else:
                        parity = launch_scheduler().submit(
                            CLASS_BY_LANE[self.SCHED_CLASS],
                            _dispatch_scheduled,
                            cost=data.nbytes,
                        )
            except BaseException as e:
                # sticky: every co-rider's reap reports the launch failure
                # instead of crashing on a half-torn group.  The group
                # still enters the live list so its backpressure credit
                # releases at settle.
                # same dead-time rule as the success path, stricter: a
                # launch that RAISED (deadline wait, device error with a
                # failed host recompute, bad geometry) produced nothing
                # — none of its elapsed time banks as busy
                rec["dispatch_ts"] = timing.get("t_dispatch", t_enqueue)
                g.error = e
                g.pad = pad
                with self._lock:
                    self._live.append(g)
                    rec["inflight_depth"] = len(self._live)
                from ceph_tpu.ops.dispatch import PIPELINE

                PIPELINE.launch()
                raise
            # dispatch_ts anchors where the launch LEFT the queue and
            # actually began dispatching (queue-wait — window AND
            # scheduler — ends here); h2d_s is the synchronous slice of
            # the dispatch — H2D staging + launch enqueue (JAX dispatch
            # is async, kernel time shows up at settle).  A fallback
            # launch gets h2d_s = 0: its host compute is already banked
            # in kernel_s, and the remainder of the elapsed time is the
            # watchdog DEADLINE wait on a wedged device — dead time that
            # must not inflate device_busy_seconds/occupancy.
            t_dispatch = timing.get("t_dispatch", t_enqueue)
            rec["dispatch_ts"] = t_dispatch
            if rec["flags"]["fallback"]:
                rec["h2d_s"] = 0.0
            else:
                rec["h2d_s"] = max(
                    0.0,
                    timing.get("t_done", t_dispatch)
                    - t_dispatch
                    - rec["kernel_s"],
                )
            g.arrays = []
            g.pad = pad
            g.parity = parity
            # HBM ledger (ISSUE 13): the in-flight device output is
            # resident from this dispatch until settle.  The handle is
            # buffer-finalized too, so even an abandoned group cannot
            # leak ledger bytes past the output's death.
            if not isinstance(parity, np.ndarray):
                out_nbytes = int(getattr(parity, "nbytes", 0) or 0)
                if out_nbytes:
                    g.mem = _hbm_ledger().alloc(
                        self.MEM_POOL, out_nbytes, buf=parity
                    )
            rec["hbm_bytes"] = _hbm_ledger().total_device_bytes()
            # donation-pool refcount (ISSUE 11): the device output is
            # LIVE until this launch settles — at pipeline depth > 1 a
            # same-shape co-launch settling first must not recycle it
            if g.donatable and not isinstance(parity, np.ndarray):
                with self._lock:
                    self._donate_pool.hold(parity)
                    g.held = parity
            # inside g.lock, like the error path above: appending after
            # release races a reaper that settles (and _live-removes) the
            # group first, which would pin a settled group in _live
            with self._lock:
                self._live.append(g)
                rec["inflight_depth"] = len(self._live)
            from ceph_tpu.ops.dispatch import PIPELINE

            PIPELINE.launch()
        self.perf.inc("launches")
        self.perf.inc(reason)
        self.perf.inc("pad_stripes", pad)
        self.perf.hinc("stripes_per_launch", g.stripes)
        self.perf.hinc("tickets_per_launch", len(g.tickets))
        self.perf.hinc("launch_bytes", data.nbytes)
        if fused_windows > 1:
            self.perf.inc("fused_launches")
            self.perf.inc("fused_windows", fused_windows)
            from ceph_tpu.ops.dispatch import record_fused

            record_fused(fused_windows)
        if pad or (self.pad_pow2 and self.window > 1):
            # padding-waste slice (ISSUE 18): every padded-mode launch
            # reports its batch and pad so perf_dump's pad_waste.<label>
            # and padding_waste_ratio show where padding bytes go
            from ceph_tpu.ops.dispatch import record_padding

            record_padding(self._group_label(g), g.stripes + pad, pad)

    def _group_label(self, g: _AggGroup) -> str:
        """Stable human-readable lane name for a group's flight records
        and trace-export lanes: aggregator kind + a short key digest +
        the chunk length (the key's raw bytes are not JSON-safe).
        crc32 over the key's repr, NOT hash(): the built-in is salted
        per process, which would break cross-run lane correlation."""
        import zlib

        chunk = g.key[-1] if g.key and isinstance(g.key[-1], int) else 0
        digest = zlib.crc32(repr(g.key).encode())
        return f"{self.PERF_NAME}/{digest:08x}/L{chunk}"

    # -- device guard / host fallback ---------------------------------------

    def _guarded_dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        """Dispatch one launch under the device guard: the `codec.launch`
        faultpoint and the per-launch deadline apply here; a device error
        or timeout re-runs the group on the byte-identical host oracle
        and marks the backend DEGRADED.  While degraded, the device is
        bypassed entirely until a probe heals it."""
        from ceph_tpu.common.fault_injector import faultpoint
        from ceph_tpu.ops.guard import device_guard

        guard = device_guard()
        if not guard.maybe_probe():
            # DEGRADED, probe not due (or failed): straight to the host
            return self._host_fallback(g, data, None)
        try:
            faultpoint("codec.launch")
            return guard.call(
                lambda: self._dispatch(g, data, donate),
                what=f"{self.WHAT} dispatch",
            )
        except BaseException as e:
            return self._host_fallback(g, data, e)

    def _host_fallback(self, g: _AggGroup, data: np.ndarray, cause):
        """Re-run a launch on the host oracle.  `cause` is the device
        failure that sent us here (None = degraded-mode bypass); the
        backend is marked DEGRADED only when the host recompute SUCCEEDS
        after a device failure — a recompute that fails identically
        (singular matrix, bad geometry) is a data error, not a backend
        verdict, and raises sticky like any launch failure."""
        t0 = time.monotonic()
        host = self._dispatch_host(g, data)
        if g.flight is not None:
            # flight-record verdict: this launch completed on the host.
            # The host compute banks as kernel_s (it IS the kernel, just
            # not on the device); degraded_bypass marks launches that
            # never tried the device at all.
            g.flight["flags"]["fallback"] = True
            if cause is None:
                g.flight["flags"]["degraded_bypass"] = True
            g.flight["kernel_s"] += time.monotonic() - t0
        if cause is not None:
            from ceph_tpu.ops.guard import device_guard

            device_guard().mark_degraded(
                f"{self.WHAT} launch failed: {cause!r}"
            )
        from ceph_tpu.ops.dispatch import record_fallback

        record_fallback(data.shape[0], data.nbytes)
        self.perf.inc("host_fallbacks")
        return host

    # -- settle / reap -------------------------------------------------------

    def _settle(self, g: _AggGroup) -> None:
        """Resolve a group to host bytes (or a sticky error), releasing
        its backpressure credit exactly once.  Lock order: group lock ->
        aggregator lock (nothing acquires the other way); the blocking
        device wait runs outside the aggregator-wide lock so other
        geometries never stall behind a kernel.  The wait itself is
        deadline-guarded: a device that wedges AFTER dispatch triggers
        the same host recompute as a failed dispatch."""
        from ceph_tpu.ops.guard import device_guard

        with g.lock:
            if g.host is None and g.error is None and g.parity is None:
                # still windowed: detach and launch it ourselves (a reap
                # must never deadlock behind its own window).  Identity
                # check: a newer group may have reused our key after we
                # were detached by a concurrent flush — popping IT would
                # orphan its window.
                with self._lock:
                    if self._groups.get(g.key) is g:
                        del self._groups[g.key]
                try:
                    self._launch(g, "flush_reap")
                except Exception:
                    pass  # reported as EcError via g.error at the reap
            if g.host is None and g.error is None:
                parity = g.parity
                device_side = not isinstance(parity, np.ndarray)
                single = len(g.tickets) == 1 and not g.pad
                host = parity
                if device_side:
                    # completion-ordered readiness probe (ISSUE 11): at
                    # pipeline depth > 1 a launch often finished under a
                    # LATER launch's dispatch — was_ready marks perfect
                    # overlap on the record, and a DEGRADED backend with
                    # an UNREADY buffer goes straight to the host oracle
                    # so one wedged launch costs one deadline, not one
                    # per in-flight group
                    ready_fn = getattr(parity, "is_ready", None)
                    try:
                        was_ready = bool(ready_fn()) if ready_fn else False
                    except Exception:
                        was_ready = False
                    if device_guard().degraded and not was_ready:
                        try:
                            host = self._host_fallback(g, g.input, None)
                        except BaseException as e2:
                            g.error = e2
                        device_side = False  # suspect buffer: never pool it
                if device_side:
                    # when the buffer is headed for the donation pool the
                    # copy MUST be forced (np.array): a zero-copy
                    # CPU-backend view into a later-donated buffer would
                    # corrupt silently.  Single-ticket unpadded groups
                    # (the window<=1 default path) hand the result
                    # straight through — no forced copy, no pooling.
                    force_copy = g.donatable and not single
                    rec = g.flight
                    # the worker writes spans into a side dict, folded
                    # into the record only on SUCCESS: a materialize
                    # that times out leaves an abandoned worker holding
                    # this closure, and if the device later unwedges it
                    # would otherwise rewrite an already-committed
                    # record with a minutes-long bogus kernel span
                    spans: dict[str, float] = {}

                    def _materialize():
                        # flight sub-spans: kernel_s is how long THIS
                        # reap blocked waiting for the device (0 = the
                        # kernel finished under other work — perfect
                        # overlap); d2h_s is the device->host copy.
                        # complete_ts anchors the record's spans in
                        # completion order: under async dispatch the
                        # wall clock around the (non-blocking) dispatch
                        # no longer brackets the kernel.
                        t0 = time.monotonic()
                        wait = getattr(parity, "block_until_ready", None)
                        if wait is not None:
                            wait()
                        t1 = time.monotonic()
                        out = (
                            np.array(parity)
                            if force_copy
                            else np.asarray(parity)
                        )
                        t2 = time.monotonic()
                        spans["kernel_s"] = t1 - t0
                        spans["complete_ts"] = t1
                        spans["d2h_s"] = t2 - t1
                        return out

                    from ceph_tpu.ops.flight_recorder import flight_recorder

                    try:
                        with flight_recorder().active_scope(rec):
                            host = device_guard().call(
                                _materialize,
                                what=f"{self.WHAT} materialize",
                            )
                        if rec is not None:
                            rec["kernel_s"] += spans.get("kernel_s", 0.0)
                            rec["d2h_s"] += spans.get("d2h_s", 0.0)
                            rec["complete_ts"] = spans.get(
                                "complete_ts", 0.0
                            )
                            if was_ready:
                                rec["flags"]["overlap"] = True
                    except BaseException as e:
                        try:
                            host = self._host_fallback(g, g.input, e)
                        except BaseException as e2:
                            g.error = e2
                        device_side = False  # suspect buffer: never pool it
                # the launch's output stops being LIVE at settle whatever
                # happened to it — leaving a stale refcount would poison
                # a later buffer that reuses the id
                if g.held is not None:
                    with self._lock:
                        self._donate_pool.release(g.held)
                    g.held = None
                if g.error is None:
                    if single:
                        g.host = host
                    else:
                        g.host = host[: g.stripes] if g.pad else host
                        if g.donatable and device_side:
                            # release the in-flight ledger hold BEFORE
                            # the donation pool re-accounts the same
                            # buffer under ec_donation — the two charges
                            # overlapping would double-count the bytes
                            # and permanently inflate the peak gauges
                            if g.mem is not None:
                                g.mem.free()
                                g.mem = None
                            with self._lock:
                                self._donate_pool.put(
                                    tuple(parity.shape), parity
                                )
                    g.parity = None
            # settled (host bytes or sticky error): release the
            # backpressure credit, the retained launch input, and the
            # HBM ledger hold — the release is unconditional, so the
            # host-fallback and sticky-error paths (the historical leak
            # shape) cannot keep the in-flight pool charged
            if g.mem is not None:
                g.mem.free()
                g.mem = None
            if g.credit:
                self.inflight.put(g.credit)
                g.credit = 0
            g.input = None
            # commit the flight record exactly once (g.flight nulls out;
            # later reaps of the same group skip this)
            if g.flight is not None:
                rec, g.flight = g.flight, None
                rec["flags"]["error"] = g.error is not None
                rec["settle_ts"] = time.monotonic()
                from ceph_tpu.ops.flight_recorder import flight_recorder

                flight_recorder().commit(rec)
        with self._lock:
            removed = g in self._live
            if removed:
                self._live.remove(g)
        if removed:
            from ceph_tpu.ops.dispatch import PIPELINE

            PIPELINE.settle()

    def _materialize(self, ticket: AggTicket) -> None:
        g = ticket._group
        self._settle(g)
        if g.error is not None:
            # lazy: the codec imports this module, not the reverse; every
            # reap path (EC and non-EC riders alike) catches EcError
            from ceph_tpu.codec.base import EIO
            from ceph_tpu.codec.interface import EcError

            raise EcError(
                EIO, f"aggregated {self.WHAT} launch failed: {g.error!r}"
            )
        ticket._value = g.host[ticket._start : ticket._start + ticket._stripes]
# every live aggregator, weakly held (ISSUE 13): the HBM pressure
# layer's stage-2 trim and the tier-1 leak gate's teardown drain reach
# all instances — the process-wide defaults AND test-local ones
_AGGREGATORS: "weakref.WeakSet[LaunchAggregator]" = weakref.WeakSet()


def drop_donation_retention() -> int:
    """Drop every live aggregator's FREE pooled buffers (HBM pressure
    stage 2); returns the bytes released."""
    freed = 0
    for agg in list(_AGGREGATORS):
        with agg._lock:
            freed += agg._donate_pool.drop_free()
    return freed


def drain_all_aggregators() -> None:
    """Flush + settle every live aggregator (the tier-1 leak gate and
    the chaos harness's end-of-run drain)."""
    for agg in list(_AGGREGATORS):
        agg.drain()


class OffloadService:
    """One registered device-offload service: a name, the aggregator
    factory that builds (or returns) its process-wide instance, the QoS
    lane its launches ride (ops/launch_scheduler lanes: client /
    recovery / background) and a one-line description of its
    byte-identical host oracle.  The aggregator subclass IS the plan
    builder + oracle pair; the registry names them so generic code
    (perf export, drains, tools) can reach every service uniformly."""

    __slots__ = ("name", "factory", "lane", "oracle", "doc", "_instance")

    def __init__(self, name, factory, lane, oracle, doc):
        self.name = name
        self.factory = factory
        self.lane = lane
        self.oracle = oracle
        self.doc = doc
        self._instance: LaunchAggregator | None = None

    def aggregator(self) -> "LaunchAggregator":
        if self._instance is None:
            self._instance = self.factory()
        return self._instance


_SERVICES: "OrderedDict[str, OffloadService]" = OrderedDict()
_SERVICES_LOCK = _lockdep_make_lock("offload_services")


def register_service(
    name: str,
    factory,
    *,
    lane: str = "client",
    oracle: str = "",
    doc: str = "",
) -> OffloadService:
    """Register (or re-register, idempotently) an offload service.
    `factory` returns the service's process-wide LaunchAggregator;
    factories managing their own singleton (the EC default_*_aggregator
    trio) are called at most once per registry entry anyway."""
    with _SERVICES_LOCK:
        svc = _SERVICES.get(name)
        if svc is None:
            svc = _SERVICES[name] = OffloadService(
                name, factory, lane, oracle, doc
            )
        return svc


def service(name: str) -> OffloadService:
    """The registered service record, importing the module that
    registers it on first miss (the registry is populated by the
    service modules' import side effects)."""
    with _SERVICES_LOCK:
        svc = _SERVICES.get(name)
    if svc is None:
        _import_builtin_services()
        with _SERVICES_LOCK:
            svc = _SERVICES.get(name)
    if svc is None:
        raise KeyError(f"no offload service {name!r}")
    return svc


def service_aggregator(name: str) -> "LaunchAggregator":
    """The named service's shared process-wide aggregator."""
    return service(name).aggregator()


def offload_services() -> tuple[str, ...]:
    """Names of every registered service, registration-ordered."""
    _import_builtin_services()
    with _SERVICES_LOCK:
        return tuple(_SERVICES)


def _import_builtin_services() -> None:
    """Import the modules whose import side effects register the
    built-in services (EC trio, device crc32c, device compressor)."""
    import ceph_tpu.codec.matrix_codec  # noqa: F401  (encode/decode/verify)
    import ceph_tpu.compressor.device  # noqa: F401  (compress)
    import ceph_tpu.ops.checksum_offload  # noqa: F401  (csum)


def offload_perf_dump() -> dict[str, object]:
    """Flat JSON-safe per-service counter export — the `offload.*`
    slice of the OSD perf report, re-exported by the mgr Prometheus
    scrape as the ceph_tpu_offload_* families.  Services whose
    aggregator was never built contribute zeros (a family that appears
    only after first traffic would flap the metrics lint)."""
    _import_builtin_services()
    out: dict[str, object] = {}
    with _SERVICES_LOCK:
        entries = list(_SERVICES.items())
    for name, svc in entries:
        agg = svc.aggregator()
        for counter, val in agg.perf.dump().items():
            out[f"{name}.{counter}"] = val
        out[f"{name}.pending"] = agg.pending()
    out["services"] = len(entries)
    return out
