"""Unified device launch scheduler with QoS classes (ISSUE 9 tentpole).

The encode (PR 2) and decode (PR 3) aggregators each owned a private
path to the device: whoever flushed first dispatched first, so a bulk
background workload (deep-scrub verify, backfill decode storms) could
park a multi-megabyte launch in front of a latency-sensitive client
encode with no arbitration at all.  This module is the missing layer
between the aggregators and ``ops/dispatch``: every ready launch is
enqueued as a schedulable item tagged with a :class:`SchedClass`
(client / recovery / background), and launches leave the queue in
dmClock tag order — the same reservation/weight/limit machinery the OSD
op queue uses (``osd/scheduler.py``), with the launch's input bytes as
its mClock cost.  Client encodes therefore preempt queued scrub work
under load, while scrub soaks up idle device time (the scheduler is
work-conserving: the queue never idles while work is queued).

Threading model — no dedicated dispatcher thread.  ``submit`` enqueues
the launch and then *drives* the queue: whichever submitter holds the
device turn dequeues the best-tagged item (possibly another class's)
and executes it; everyone else blocks on their own item's completion.
This is the storage analog of cooperative io_uring submission — the
arbitration cost in the uncontended single-launch case is one lock
round-trip, and under contention the dequeue order IS the QoS policy.
Launch callables run under the submitter's captured ``contextvars``
context so the flight-recorder active-record scope (and tracing spans)
survive being executed by another submitter's drain loop.

Observability: per-class enqueue/dequeue/queue-depth/wait counters
export through ``ops/dispatch.perf_dump()`` (asok ``perf dump`` →
``ec_dispatch.sched_*``) and again as the ``ceph_tpu_ec_sched_*``
Prometheus families via the OSD's MMgrReport; the class tag also rides
every flight record (``sched_class``) so ``tools/trace_export.py`` can
render one lane per class and make a priority inversion visible.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Callable

from ceph_tpu.common.lockdep import make_lock

from ceph_tpu.osd.scheduler import (
    ClientProfile,
    MClockScheduler,
    SchedClass,
    WorkItem,
)

# The three launch lanes the ISSUE names.  SCRUB and BEST_EFFORT both
# render as "background": a deep-scrub verify launch and a best-effort
# housekeeping launch compete in the same QoS bucket.
LANES = ("client", "recovery", "background")

# lane name -> the scheduler class an aggregator submits under (the
# aggregators name their lane as a string so codec/ never has to import
# the OSD scheduler enum at module-import time)
CLASS_BY_LANE = {
    "client": SchedClass.CLIENT,
    "recovery": SchedClass.RECOVERY,
    "background": SchedClass.SCRUB,
}


def lane_name(klass: SchedClass) -> str:
    """Collapse the OSD scheduling classes onto the three launch lanes
    (flight-record ``sched_class`` values, counter keys, trace rows)."""
    if klass is SchedClass.CLIENT:
        return "client"
    if klass is SchedClass.RECOVERY:
        return "recovery"
    return "background"


class _PendingLaunch:
    """One enqueued launch: the callable, its captured context, and the
    completion rendezvous its submitter blocks on."""

    __slots__ = ("fn", "klass", "cost", "ctx", "done", "result", "error",
                 "enqueue_ts")

    def __init__(self, fn: Callable[[], object], klass: SchedClass, cost: int):
        self.fn = fn
        self.klass = klass
        self.cost = int(cost)
        # the drain loop may run `fn` from ANOTHER submitter's thread;
        # the flight-record contextvar scope (and tracer span scope) set
        # by the launching aggregator must still be visible inside
        self.ctx = contextvars.copy_context()
        self.done = threading.Event()
        self.result: object = None
        self.error: BaseException | None = None
        self.enqueue_ts = time.monotonic()


class LaunchScheduler:
    """QoS arbiter for the shared device queue.

    ``profiles`` maps the three scheduler classes to dmClock
    (reservation, weight, limit) triples; rates are nominal-4KiB items
    per second exactly as in :class:`MClockScheduler`, so a launch of
    N bytes consumes N/4096 nominal items.  ``clock`` is injectable for
    deterministic ordering tests.
    """

    def __init__(
        self,
        profiles: dict[SchedClass, ClientProfile] | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if profiles is None:
            profiles = default_profiles()
        self._mclock = MClockScheduler(profiles=profiles, clock=clock)
        self._lock = make_lock("launch_scheduler")
        self._cv = threading.Condition(self._lock)
        self._busy = False  # a launch is executing (the device turn)
        # bytes_total: input bytes dispatched per lane (ISSUE 11) — with
        # the pipelined aggregators the device turn covers only the
        # (async) dispatch, so per-lane BYTES, not launch counts, are
        # what the QoS knobs actually arbitrate; the gauge pair
        # (dequeued, bytes_total) makes a lane's launch-size mix visible
        self._counters: dict[str, dict[str, float]] = {
            lane: {"enqueued": 0, "dequeued": 0, "wait_ms_total": 0.0,
                   "bytes_total": 0}
            for lane in LANES
        }

    # -- configuration -----------------------------------------------------

    def configure(self, **profiles: ClientProfile) -> None:
        """Apply live QoS profiles by lane name (``client`` /
        ``recovery`` / ``background``): the OSD's ``ec_tpu_sched_*``
        config observers land here."""
        mapping = {
            "client": (SchedClass.CLIENT,),
            "recovery": (SchedClass.RECOVERY,),
            # both background classes share the knob set
            "background": (SchedClass.SCRUB, SchedClass.BEST_EFFORT),
        }
        with self._lock:
            for lane, profile in profiles.items():
                if profile is None:
                    continue
                for klass in mapping[lane]:
                    self._mclock.update_profile(klass, profile)

    # -- submission --------------------------------------------------------

    def submit(self, klass: SchedClass, fn: Callable[[], object],
               cost: int = 4096) -> object:
        """Enqueue one ready launch and drive the queue until it has
        run.  Returns ``fn``'s result (raises its exception).  The
        caller may end up executing OTHER queued launches first — the
        dequeue order is the QoS policy, not submission order."""
        pend = self.submit_async(klass, fn, cost)
        while not pend.done.is_set():
            ran = self._run_one()
            if ran is None and not pend.done.is_set():
                # our item is executing on another submitter's turn (or
                # the turn-holder will dequeue it next): wait for
                # progress instead of spinning
                with self._cv:
                    while self._busy and not pend.done.is_set():
                        self._cv.wait(timeout=0.5)
        if pend.error is not None:
            raise pend.error
        return pend.result

    def submit_async(self, klass: SchedClass, fn: Callable[[], object],
                     cost: int = 4096) -> _PendingLaunch:
        """Enqueue without driving (the test surface, and the first half
        of :meth:`submit`)."""
        pend = _PendingLaunch(fn, klass, cost)
        with self._lock:
            self._mclock.enqueue(
                WorkItem(run=pend, klass=klass, cost=pend.cost)
            )
            self._counters[lane_name(klass)]["enqueued"] += 1
        return pend

    def _run_one(self) -> _PendingLaunch | None:
        """Take the device turn and execute the best-tagged queued
        launch.  None when the turn is held elsewhere or the queue is
        empty."""
        with self._lock:
            if self._busy:
                return None
            item = self._mclock.dequeue()
            if item is None:
                return None
            self._busy = True
            pend: _PendingLaunch = item.run  # the payload, not a callable
            lane = self._counters[lane_name(pend.klass)]
            lane["dequeued"] += 1
            lane["bytes_total"] += pend.cost
            lane["wait_ms_total"] += (
                time.monotonic() - pend.enqueue_ts
            ) * 1e3
        try:
            pend.result = pend.ctx.run(pend.fn)
        except BaseException as e:
            pend.error = e
        finally:
            with self._cv:
                self._busy = False
                pend.done.set()
                self._cv.notify_all()
        return pend

    def drain(self) -> int:
        """Execute queued launches until the queue is empty (tests;
        barrier paths already drain implicitly because every submitter
        drives the queue).  Returns how many launches ran."""
        ran = 0
        while self._run_one() is not None:
            ran += 1
        return ran

    # -- introspection -----------------------------------------------------

    def queue_depths(self) -> dict[str, int]:
        """Per-lane queued-launch counts (the queue-depth gauges)."""
        depths = dict.fromkeys(LANES, 0)
        with self._lock:
            for klass, q in self._mclock._queues.items():
                depths[lane_name(klass)] += len(q)
        return depths

    def perf_dump(self) -> dict[str, float]:
        """Flat per-lane counters for ``ops/dispatch.perf_dump()`` (the
        ``sched.<lane>.<counter>`` keys) and the OSD's MMgrReport
        (``ec_sched.*`` → ``ceph_tpu_ec_sched_*`` families)."""
        depths = self.queue_depths()
        out: dict[str, float] = {}
        with self._lock:
            for lane in LANES:
                c = self._counters[lane]
                out[f"{lane}.enqueued"] = int(c["enqueued"])
                out[f"{lane}.dequeued"] = int(c["dequeued"])
                out[f"{lane}.bytes_total"] = int(c["bytes_total"])
                out[f"{lane}.wait_ms_total"] = round(c["wait_ms_total"], 3)
                out[f"{lane}.queue_depth"] = depths[lane]
        return out

    def reset_counters(self) -> None:
        with self._lock:
            for lane in LANES:
                self._counters[lane] = {
                    "enqueued": 0, "dequeued": 0, "wait_ms_total": 0.0,
                    "bytes_total": 0,
                }


def default_profiles() -> dict[SchedClass, ClientProfile]:
    """The option-table QoS defaults (``ec_tpu_sched_*``): client holds
    a reservation + double weight so its launches mature first; the
    background classes get half weight and no reservation, soaking idle
    time only.  Daemons with a live Config re-apply through
    ``LaunchScheduler.configure``."""
    from ceph_tpu.common.options import OPTIONS

    def prof(lane: str) -> ClientProfile:
        return ClientProfile(
            reservation=float(OPTIONS[f"ec_tpu_sched_{lane}_res"].default),
            weight=float(OPTIONS[f"ec_tpu_sched_{lane}_wgt"].default),
            limit=float(OPTIONS[f"ec_tpu_sched_{lane}_lim"].default),
        )

    background = prof("background")
    return {
        SchedClass.CLIENT: prof("client"),
        SchedClass.RECOVERY: prof("recovery"),
        SchedClass.SCRUB: background,
        SchedClass.BEST_EFFORT: background,
    }


_SCHEDULER: LaunchScheduler | None = None


def launch_scheduler() -> LaunchScheduler:
    """The process-wide scheduler every aggregator dispatches through
    (lazy, like the device guard and the default aggregators)."""
    global _SCHEDULER
    if _SCHEDULER is None:
        _SCHEDULER = LaunchScheduler()
    return _SCHEDULER
