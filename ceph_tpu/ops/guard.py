"""Device-backend watchdog — deadline-bounded launches + degraded state.

The failure mode this contains showed up in the bench trajectory twice: a
TPU backend that wedges (hung compile, dead runtime) does not error, it
BLOCKS — and every EC write and recovery in the process then stalls
forever behind the aggregators.  bench.py grew a stage watchdog for its
own runs; this is the data-path version:

- `call()` runs a device dispatch (or its blocking materialization)
  under the `ec_tpu_launch_timeout_ms` deadline on a watchdog thread and
  raises DeviceTimeout instead of hanging the caller.
- A timeout (or a device error with a healthy host recompute) marks the
  backend DEGRADED: subsequent launches bypass the device entirely and
  run on the byte-identical host oracle (gf/bitslice.py) until a probe
  heals the state.  The degraded flag feeds the `TPU_BACKEND_DEGRADED`
  health check through the OSD status -> mgr digest -> mon pipeline.
- While degraded, `maybe_probe()` re-tries the device at most every
  `ec_tpu_probe_interval_ms` with a tiny compile probe under the same
  deadline — completing it self-heals dispatch back to the TPU path.

The guard is process-wide (like the plan cache and the aggregators): one
wedged runtime affects every PG in the process, so one state machine
owns the verdict.
"""

from __future__ import annotations

import threading
import time

from ceph_tpu.common.lockdep import make_lock


class DeviceTimeout(RuntimeError):
    """A guarded device call exceeded its per-launch deadline."""


def _default_probe() -> None:
    """Tiny compile probe: one shared-kernel dispatch + materialization.
    Cheap (the (8,8)x(1,128) xor_matmul shape is compiled once per
    process) but it exercises exactly the path real launches take:
    dispatch, device execute, D2H."""
    import numpy as np

    import jax.numpy as jnp

    from ceph_tpu.ops.xor_mm import xor_matmul

    bm = jnp.asarray(np.eye(8, dtype=np.uint8))
    x = jnp.asarray(np.arange(128, dtype=np.uint8).reshape(1, 128))
    np.asarray(xor_matmul(bm, x))


class DeviceGuard:
    """Per-process launch deadline + DEGRADED/healthy state machine."""

    def __init__(self, timeout_ms: int | None = None,
                 probe_interval_ms: int | None = None):
        if timeout_ms is None or probe_interval_ms is None:
            from ceph_tpu.common.options import OPTIONS

            if timeout_ms is None:
                timeout_ms = int(OPTIONS["ec_tpu_launch_timeout_ms"].default)
            if probe_interval_ms is None:
                probe_interval_ms = int(
                    OPTIONS["ec_tpu_probe_interval_ms"].default
                )
        self._lock = make_lock("device_guard")
        self.timeout_ms = int(timeout_ms)
        self.probe_interval_ms = int(probe_interval_ms)
        self.degraded = False
        self.degraded_since = 0.0
        self.reason = ""
        self.degraded_total = 0  # transitions into DEGRADED
        self.probes = 0
        self.probe_failures = 0
        self._last_probe = 0.0
        self._probe_cold = True  # first probe of a degrade episode

    def configure(self, timeout_ms: int | None = None,
                  probe_interval_ms: int | None = None) -> None:
        """Apply live config (the OSD wires its runtime observers here)."""
        if timeout_ms is not None:
            self.timeout_ms = int(timeout_ms)
        if probe_interval_ms is not None:
            self.probe_interval_ms = int(probe_interval_ms)

    # -- deadline-bounded execution ------------------------------------------

    def call(self, fn, what: str = "launch", timeout_ms: int | None = None):
        """Run `fn` under the per-launch deadline (or an explicit
        `timeout_ms` override).  Deadline <= 0 runs inline (watchdog
        off).  On timeout the worker thread is abandoned (daemon; its
        eventual result is discarded) and DeviceTimeout raises — the
        caller falls back to the host oracle, which never touches the
        wedged runtime."""
        t_ms = self.timeout_ms if timeout_ms is None else timeout_ms
        if t_ms <= 0:
            return fn()
        box: list = []
        err: list[BaseException] = []
        # carry contextvars (the tracing span scope) onto the worker so a
        # guarded dispatch records its codec spans in the caller's trace
        import contextvars

        ctx = contextvars.copy_context()

        def run() -> None:
            try:
                box.append(ctx.run(fn))
            except BaseException as e:  # re-raised on the calling thread
                err.append(e)

        th = threading.Thread(target=run, daemon=True, name="ec-launch-watchdog")
        th.start()
        th.join(t_ms / 1000.0)
        if th.is_alive():
            # annotate the launch's flight record (ISSUE 8): the deadline
            # verdict belongs to THIS launch's timeline, not just the
            # process-wide degraded gauge
            from ceph_tpu.ops.flight_recorder import flight_recorder

            flight_recorder().flag_active("timeout")
            raise DeviceTimeout(f"device {what} exceeded {t_ms} ms deadline")
        if err:
            raise err[0]
        return box[0]

    # -- state machine --------------------------------------------------------

    def mark_degraded(self, reason: str) -> None:
        with self._lock:
            entered = not self.degraded
            if entered:
                self.degraded = True
                self.degraded_since = time.monotonic()
                self.degraded_total += 1
                # next launch may probe immediately: a transient error
                # (one bad compile) should not cost a full interval.
                # -inf, not 0.0 — monotonic() starts at boot, so on a
                # freshly booted host 0.0 is less than one interval ago
                # and would gate the heal probe
                self._last_probe = float("-inf")
                self._probe_cold = True
            self.reason = reason
        if entered:
            # the device-resident chunk cache (ops/device_cache.py) holds
            # buffers a wedged runtime can no longer serve — drop them on
            # the transition so the host-fallback path never consults a
            # cache it cannot materialize (puts are gated while degraded)
            from .device_cache import device_chunk_cache

            device_chunk_cache().clear()

    def mark_healthy(self) -> None:
        with self._lock:
            self.degraded = False
            self.degraded_since = 0.0
            self.reason = ""

    def maybe_probe(self, probe_fn=None) -> bool:
        """While DEGRADED, re-probe the device at most every probe
        interval; returns True when the probe healed the backend (the
        caller should dispatch to the device again).  Healthy state
        returns True without probing."""
        with self._lock:
            if not self.degraded:
                return True
            if self.probe_interval_ms <= 0:
                return False
            now = time.monotonic()
            if (now - self._last_probe) * 1000.0 < self.probe_interval_ms:
                return False
            self._last_probe = now
            self.probes += 1
            cold = self._probe_cold
            self._probe_cold = False
        try:
            # the probe runs on a SUBMITTER'S data path, so after the
            # first attempt of an episode it gets a deadline much
            # shorter than real launches: a still-wedged device costs
            # that submitter ~the probe interval, not the full launch
            # timeout, and leaks at most one abandoned thread per
            # interval instead of stacking them.  The FIRST probe keeps
            # the full deadline — it may carry the probe kernel's
            # compile, and even a timed-out attempt warms the compile
            # cache in its abandoned thread so later probes fit the
            # short window.
            probe_ms = self.timeout_ms
            if probe_ms > 0 and not cold:
                probe_ms = min(probe_ms, max(250, self.probe_interval_ms))
            self.call(probe_fn or _default_probe, what="probe",
                      timeout_ms=probe_ms)
        except BaseException:
            with self._lock:
                self.probe_failures += 1
            return False
        self.mark_healthy()
        return True

    def snapshot(self) -> dict[str, object]:
        with self._lock:
            return {
                "degraded": int(self.degraded),
                "degraded_for_sec": (
                    time.monotonic() - self.degraded_since
                    if self.degraded
                    else 0.0
                ),
                "degraded_total": self.degraded_total,
                "reason": self.reason,
                "probes": self.probes,
                "probe_failures": self.probe_failures,
            }


_GUARD: DeviceGuard | None = None


def device_guard() -> DeviceGuard:
    """The process-wide guard (built lazily from option defaults, like
    the default aggregators; daemons with a live Config re-configure it
    through their runtime observers)."""
    global _GUARD
    if _GUARD is None:
        _GUARD = DeviceGuard()
    return _GUARD
