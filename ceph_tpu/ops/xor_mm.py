"""Device GF(2^8) coding as bitsliced XOR-matmuls (jax.numpy reference path).

Role after the packed-bitplane rework (ceph_tpu.ops.packed_gf): this module
is the byte-exact REFERENCE formulation and the small-input/one-off-matrix
path.  Its bit-matrix is a runtime operand, so one compiled kernel serves
every matrix at a given shape — the right trade for tiny decodes against
freshly inverted matrices.  Bulk coding dispatches to packed_gf.PackedPlan
(planes kept packed 8-per-byte; 8x smaller operand) or the Pallas kernel;
see _DeviceCoder in codec/matrix_codec.py for the dispatch rule.

This is the TPU replacement for the reference's SIMD hot loop
(`ec_encode_data`, /root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:129;
`region_xor`, isa/xor_op.cc): the (m, k) GF coding matrix is expanded once on
host into an (8m, 8k) 0/1 bit-matrix (ceph_tpu.gf.bitslice) and applied to
byte chunks as

    planes  = bit-expand(data)          # (8k, L) 0/1, VPU shifts/masks
    pbits   = (B @ planes) mod 2        # MXU matmul + parity reduction
    parity  = bit-fold(pbits)           # (m, L) uint8

The bit-matrix is a runtime *argument*, not a compiled constant, so one
compiled kernel serves every erasure signature for a given (nerrs, k) shape —
the device analog of the reference's LRU decode-table cache
(isa/ErasureCodeIsaTableCache.h:48): recompilation happens per shape, table
churn is just new operand bytes.

Shapes: data is (k, L) or batched (B, k, L); L is the chunk length in bytes
and maps onto the TPU lane dimension.  All dtypes uint8 in HBM; the 8x
bit-plane expansion lives only in on-chip/intermediate form (XLA fuses the
shift/mask producers into the matmul operand; the Pallas kernel in
ceph_tpu.ops.pallas_gf keeps it entirely in VMEM).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_BIT_WEIGHTS = tuple(1 << b for b in range(8))


def _expand_planes(data: jax.Array) -> jax.Array:
    """(..., k, L) uint8 -> (..., 8k, L) 0/1 planes, LSB-first per byte."""
    *lead, k, L = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(8, 1)
    planes = (data[..., :, None, :] >> shifts) & jnp.uint8(1)
    return planes.reshape(*lead, 8 * k, L)


def _fold_planes(planes: jax.Array) -> jax.Array:
    """(..., 8m, L) parity bits (int) -> (..., m, L) uint8 bytes."""
    *lead, m8, L = planes.shape
    p = planes.reshape(*lead, m8 // 8, 8, L).astype(jnp.uint8)
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(8, 1)
    return (p << shifts).sum(axis=-2, dtype=jnp.uint8)


@jax.jit
def xor_matmul(bit_matrix: jax.Array, data: jax.Array) -> jax.Array:
    """Apply an (8m, 8k) GF(2) bit-matrix to (..., k, L) uint8 chunks.

    Returns (..., m, L) uint8.  Accumulation runs in int32 on the MXU; the
    mod-2 reduction keeps only the parity bit.  Exact for any k (sums are
    bounded by 8k <= 2^31).
    """
    planes = _expand_planes(data).astype(jnp.int8)
    bm = bit_matrix.astype(jnp.int8)
    # (..., 8k, L) x (8m, 8k) -> (..., 8m, L)
    acc = jnp.einsum(
        "pq,...ql->...pl", bm, planes, preferred_element_type=jnp.int32
    )
    return _fold_planes(acc & 1)


@jax.jit
def gf2_plane_matmul(bit_matrix: jax.Array, planes: jax.Array) -> jax.Array:
    """XOR-accumulate matmul at PLANE granularity: B (R, Q) 0/1 applied to
    (..., Q, P) uint8 planes -> (..., R, P), out[r] = XOR of planes[q]
    where B[r, q] = 1.

    The packetized coding step of the jerasure bit-matrix family
    (liberation / blaum_roth / liber8tion; jerasure_schedule_encode in the
    reference's submodule): a "bit" selects a whole packet, and XOR is a
    carryless bytewise add, so each of a byte's 8 bit-lanes rides the same
    MXU matmul independently.

    NOT redundant with `xor_matmul(expand_matrix(B), planes)`: that is
    bit-for-bit equivalent (coeff 1 expands to an 8x8 identity block) but
    contracts over an 8x longer axis with an 8x taller matrix — 8x the MXU
    FLOPs and 64x the matrix operand — because byte-granular selection
    doesn't need per-bit matrix rows.
    """
    shifts = jnp.arange(8, dtype=jnp.uint8).reshape(8, 1)
    bits = (planes[..., :, None, :] >> shifts) & jnp.uint8(1)  # (..., Q, 8, P)
    acc = jnp.einsum(
        "rq,...qbp->...rbp",
        bit_matrix.astype(jnp.int8),
        bits.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    )
    return ((acc & 1).astype(jnp.uint8) << shifts).sum(axis=-2, dtype=jnp.uint8)


@jax.jit
def xor_reduce(data: jax.Array) -> jax.Array:
    """XOR-fold chunks: (..., k, L) uint8 -> (..., L) uint8.

    Device analog of the reference's `region_xor` (isa/xor_op.cc) used for the
    m == 1 parity and single-erasure fast paths (ErasureCodeIsa.cc:125-131,
    :196-216).  Pure VPU work; XLA fuses the reduction tree.
    """
    return jax.lax.reduce(
        data, jnp.uint8(0), jax.lax.bitwise_xor, dimensions=(data.ndim - 2,)
    )


@functools.partial(jax.jit, static_argnames=("k", "m"))
def encode_full(bit_matrix: jax.Array, data: jax.Array, *, k: int, m: int) -> jax.Array:
    """Encode: (..., k, L) data -> (..., k+m, L) all chunks (systematic)."""
    parity = xor_matmul(bit_matrix, data)
    return jnp.concatenate([data, parity], axis=-2)


def as_device_bit_matrix(gf_matrix: np.ndarray) -> jax.Array:
    """Expand an (m, k) GF matrix on host and place the bit-matrix on device."""
    from ceph_tpu.gf.bitslice import expand_matrix

    return jnp.asarray(expand_matrix(gf_matrix), dtype=jnp.uint8)
