"""Launch flight recorder — a bounded, lock-cheap ring of per-launch
records for the coding hot path (ISSUE 8 tentpole).

The launch counters (ops/dispatch.py) answer "how many dispatches"; the
perf histograms answer "how were they distributed"; neither can show a
TIMELINE.  Closing the per-chip gap to the ≥40 GB/s north star is an
overlap problem — the next H2D must run under the current kernel — and
an overlap problem is invisible without per-launch spans.  Each record
carries:

- identity: monotone ``seq``, ``kind`` (encode/decode/...), the
  aggregator ``group`` key, ticket/stripe/batch/byte counts, the device
  count the dispatch spanned (annotated by ops/dispatch.record_launch);
- the timeline: ``submit_ts`` (first submission into the window),
  ``dispatch_ts``, ``settle_ts``, and derived spans — ``queue_wait_s``
  (submit→dispatch: time spent windowed), ``h2d_s`` (the synchronous
  part of the dispatch: host→device staging + launch enqueue; JAX
  dispatch is async so this is NOT kernel time), ``kernel_s`` (how long
  the reaper blocked in ``block_until_ready`` — 0 when the kernel
  finished under other work, i.e. perfect overlap), ``d2h_s`` (the
  device→host copy of the materialization);
- flags: ``sharded``, ``fallback`` (completed on the host oracle),
  ``degraded_bypass`` (device skipped entirely while DEGRADED),
  ``timeout`` (a DeviceGuard deadline fired), ``throttle_stall`` (a
  submitter hit the inflight-byte bound), ``error`` (sticky failure),
  ``hedged`` (ISSUE 17: the decode's shard set includes a speculative
  hedged sub-read that beat a straggler — gray-failure mitigation is
  visible on the same timeline as the launches it saved).

Producers hold the record through a contextvar scope
(``active_scope``): ops/dispatch.py annotates devices/kind on the
record its dispatch runs under, and ops/guard.py flags deadline hits —
neither needs aggregator plumbing.  Dispatches with no active record
(eager bulk paths, bench loops) get a lightweight span-less record from
``record_launch`` so the ring still shows them.

The ring is a ``collections.deque(maxlen=...)``; a commit takes one
short lock to bank the utilization accumulators and append (the append
must share the lock with ``configure``'s deque swap), and readers
snapshot without blocking writers.  Consumers:

- OSD asok ``dump_flight`` → ``dump()`` (records + utilization),
- ``tools/trace_export.py`` → Chrome trace-event JSON (Perfetto lanes
  per device / per aggregator group with explicit idle gaps),
- ``ops/dispatch.perf_dump()`` → ``device_busy_seconds`` /
  ``device_occupancy`` scalars (the mgr Prometheus scrape re-exports
  them as ``ceph_tpu_ec_device_busy_seconds`` /
  ``ceph_tpu_ec_device_occupancy``),
- ``bench.py`` / ``tools/chaos.py`` fold ``summary()`` into their JSON.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque

from ceph_tpu.common.lockdep import make_lock

DEFAULT_CAPACITY = 512

# the record the CURRENT dispatch runs under (a plain mutable dict):
# set by LaunchAggregator._launch around its guarded dispatch, read by
# ops/dispatch.record_launch and ops/guard.DeviceGuard.call.  A
# contextvar (not a thread-local) so the guard's watchdog worker —
# which runs the dispatch under contextvars.copy_context() — sees and
# mutates the SAME dict.
import contextvars

_ACTIVE: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "flight_record", default=None
)

# hedge hint (ISSUE 17): set by ECBackend around a reconstruct whose
# shard set includes a winning hedged sub-read, read by new_record — the
# decode launch is created levels below (aggregator flush inside
# pend.result()), so a contextvar is the only plumbing-free channel.
_HEDGED: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "flight_hedged", default=False
)


@contextlib.contextmanager
def hedged_hint():
    """Mark flight records created inside this scope as ``hedged``."""
    token = _HEDGED.set(True)
    try:
        yield
    finally:
        _HEDGED.reset(token)


def new_record(
    kind: str,
    group: str = "",
    tickets: int = 1,
    stripes: int = 0,
    batch: int = 0,
    nbytes: int = 0,
    submit_ts: float | None = None,
    reason: str = "",
    sched_class: str = "",
) -> dict:
    """A fresh (uncommitted) flight record.  ``submit_ts`` is the FIRST
    submission into the launch's window (queue-wait anchors here);
    ``sched_class`` is the launch scheduler's QoS lane (client /
    recovery / background, ISSUE 9) — empty for dispatches that never
    passed through the scheduler (raw bench/bulk paths)."""
    now = time.monotonic()
    try:
        from ceph_tpu.common.mempool import ledger as _hbm_ledger

        hbm_bytes = _hbm_ledger().total_device_bytes()
    except ImportError:  # early-boot partial import: no ledger yet
        hbm_bytes = 0
    return {
        "seq": 0,  # assigned at commit
        "kind": kind,
        "group": group,
        "sched_class": sched_class,
        "tickets": int(tickets),
        "stripes": int(stripes),
        "batch": int(batch),
        "bytes": int(nbytes),
        "devices": 1,
        "reason": reason,
        "submit_ts": now if submit_ts is None else float(submit_ts),
        "dispatch_ts": 0.0,
        "settle_ts": 0.0,
        # when the device WORK finished (the blocking wait returned) —
        # the completion-ordered anchor async span attribution needs:
        # under pipelined dispatch (ISSUE 11) wall-clock around the
        # now-nonblocking calls no longer brackets the kernel
        "complete_ts": 0.0,
        # how many launches were in flight (dispatched, unsettled) the
        # moment this one dispatched — the pipeline-depth witness
        "inflight_depth": 0,
        # ledger-tracked HBM bytes resident when this launch dispatched
        # (ISSUE 13): the memory level rides the same timeline as the
        # launches, rendered as a Perfetto counter track by
        # tools/trace_export.py
        "hbm_bytes": hbm_bytes,
        "queue_wait_s": 0.0,
        "h2d_s": 0.0,
        "kernel_s": 0.0,
        "d2h_s": 0.0,
        # zero-pad stripes in `batch` (batch - stripes when the launch
        # padded to a bucket target): the per-launch waste the
        # ops/dispatch.py pad_waste slice aggregates (ISSUE 18)
        "pad_stripes": 0,
        # aggregation windows fused into this launch (ISSUE 18): > 1
        # only on super-launches that stretched past their window while
        # the in-flight ring was full (the `fused` flag mirrors it)
        "fused_windows": 0,
        "flags": {
            "sharded": False,
            "fallback": False,
            "degraded_bypass": False,
            "timeout": False,
            "throttle_stall": False,
            "error": False,
            # the launch's device work had already completed when its
            # reaper arrived (zero blocking wait): the overlap the
            # pipeline exists to create, visible per launch
            "overlap": False,
            # served from the device-resident chunk cache: no H2D, no
            # kernel, only the D2H copy (ops/device_cache.py)
            "cache_hit": False,
            # a winning hedged sub-read fed this decode (ISSUE 17)
            "hedged": _HEDGED.get(),
            # super-launch fusion (ISSUE 18): this launch carried more
            # than one aggregation window's worth of tickets
            "fused": False,
            # on-device RMW delta encode (ISSUE 18): parity updated in
            # HBM from cached operands — zero H2D, zero D2H
            "delta": False,
        },
    }


class FlightRecorder:
    """Process-wide bounded ring of completed launch records."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = make_lock("flight_recorder")
        self._ring: deque[dict] = deque(maxlen=max(1, int(capacity)))
        self._seq = itertools.count(1)
        # utilization epoch: busy-seconds accumulate from here; reset()
        # rebases it so occupancy is over the observed window, not
        # process lifetime
        self._epoch = time.monotonic()
        self._busy_s = 0.0          # sum of per-launch (h2d+kernel+d2h)
        self._device_busy_s = 0.0   # the same, weighted by device count
        self._queue_wait_s = 0.0    # sum of queue waits (span records)
        self._span_records = 0      # records that carried spans
        self._committed = 0         # records committed since reset
        self._fallbacks = 0         # cumulative, survives ring eviction

    # -- configuration ---------------------------------------------------------

    def configure(self, capacity: int | None = None) -> None:
        """Apply live config (`ec_tpu_flight_records`): resizing keeps
        the newest records, like OpTracker.resize_history."""
        if capacity is None:
            return
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(capacity)))

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    # -- producer side ---------------------------------------------------------

    @contextlib.contextmanager
    def active_scope(self, rec: dict | None):
        """Make `rec` the dispatch-context record: ops/dispatch.py and
        ops/guard.py annotate it without aggregator plumbing.  None is a
        no-op scope (callers with nothing to record keep one code path).
        """
        if rec is None:
            yield None
            return
        token = _ACTIVE.set(rec)
        try:
            yield rec
        finally:
            _ACTIVE.reset(token)

    @staticmethod
    def active() -> dict | None:
        return _ACTIVE.get()

    def annotate_active(self, **fields) -> None:
        """Merge scalar fields into the active record (no-op without
        one).  Flags go through `flag_active`."""
        rec = _ACTIVE.get()
        if rec is not None:
            rec.update(fields)

    def flag_active(self, name: str) -> None:
        rec = _ACTIVE.get()
        if rec is not None:
            rec["flags"][name] = True

    def commit(self, rec: dict) -> dict:
        """Finalize + append a record.  Derives the spans that follow
        from the timestamps, accumulates utilization, assigns the seq.
        Safe from any thread (deque append is atomic; the accumulator
        fields take the lock)."""
        now = time.monotonic()
        if not rec["dispatch_ts"]:
            rec["dispatch_ts"] = now
        if not rec["settle_ts"]:
            rec["settle_ts"] = now
        rec["queue_wait_s"] = max(0.0, rec["dispatch_ts"] - rec["submit_ts"])
        rec["seq"] = next(self._seq)
        busy = rec["h2d_s"] + rec["kernel_s"] + rec["d2h_s"]
        with self._lock:
            self._committed += 1
            if rec["flags"]["fallback"]:
                self._fallbacks += 1
            if busy or rec["flags"]["fallback"]:
                self._busy_s += busy
                self._device_busy_s += busy * max(1, rec["devices"])
                self._queue_wait_s += rec["queue_wait_s"]
                self._span_records += 1
            # append under the same lock: a concurrent configure()
            # resize swaps the deque, and an append landing on the
            # abandoned one would silently drop the record
            self._ring.append(rec)
        return rec

    def record_raw(
        self, kind: str, stripes: int, nbytes: int, devices: int = 1
    ) -> None:
        """Lightweight span-less record for a dispatch that ran OUTSIDE
        an aggregator launch (eager bulk calls, bench loops): the ring
        still shows when it happened and how big it was."""
        rec = new_record(kind, group="#raw", stripes=stripes, batch=stripes,
                         nbytes=nbytes)
        rec["devices"] = max(1, int(devices))
        rec["flags"]["sharded"] = devices > 1
        rec["dispatch_ts"] = rec["submit_ts"]
        self.commit(rec)

    # -- consumer side ---------------------------------------------------------

    def records(self) -> list[dict]:
        """Snapshot, oldest first (deque iteration is atomic enough: a
        concurrent append may or may not be included, never torn)."""
        return list(self._ring)

    def utilization(self) -> dict[str, float]:
        """Busy-seconds and occupancy derived from the span-bearing
        records since the last reset.  `device_busy_seconds` weights
        each launch's busy span by the devices it spanned; `occupancy`
        is single-lane busy time over the observation window (a proxy
        for "was the device queue ever idle"), clamped to [0, 1]."""
        now = time.monotonic()
        with self._lock:
            window = max(1e-9, now - self._epoch)
            occupancy = min(1.0, self._busy_s / window)
            mean_wait = (
                self._queue_wait_s / self._span_records
                if self._span_records
                else 0.0
            )
            return {
                "busy_seconds": self._busy_s,
                "device_busy_seconds": self._device_busy_s,
                "window_seconds": window,
                "occupancy": occupancy,
                "mean_queue_wait_s": mean_wait,
                "span_records": self._span_records,
            }

    def summary(self) -> dict:
        """The compact blob bench.py / tools/chaos.py fold into their
        JSON: counts, mean queue wait, occupancy."""
        util = self.utilization()
        return {
            "records": len(self._ring),
            # both cumulative since reset: fallbacks counted at commit,
            # NOT by scanning the ring (evicted records would undercount
            # the numerator against the full-run launch denominator)
            "launches": self._committed,
            "fallbacks": self._fallbacks,
            "mean_queue_wait_ms": round(util["mean_queue_wait_s"] * 1e3, 3),
            "occupancy": round(util["occupancy"], 6),
            "device_busy_seconds": round(util["device_busy_seconds"], 6),
        }

    def dump(self) -> dict:
        """The asok `dump_flight` payload."""
        return {
            "capacity": self.capacity,
            "utilization": self.utilization(),
            "records": self.records(),
        }

    def reset(self) -> None:
        """Drop records and rebase the utilization window (tests; bench
        stages that want per-stage occupancy)."""
        with self._lock:
            self._ring.clear()
            self._epoch = time.monotonic()
            self._busy_s = 0.0
            self._device_busy_s = 0.0
            self._queue_wait_s = 0.0
            self._span_records = 0
            self._committed = 0
            self._fallbacks = 0


_RECORDER: FlightRecorder | None = None


def flight_recorder() -> FlightRecorder:
    """The process-wide recorder (lazy, like the device guard and the
    default aggregators; daemons with a live Config re-size it through
    their runtime observers)."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = FlightRecorder()
    return _RECORDER
