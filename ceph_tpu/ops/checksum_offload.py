"""Device crc32c — the first post-EC offload-runtime service (ISSUE 20).

crc32c is GF(2)-affine: with a fixed message length L,

    crc(data) = crc(0^L)  XOR  (+) over set input bits of  C[i, t]

where C[i, t] is the final-register contribution of bit t of byte i —
the same linearity the XOR-program generators exploit for RS coding
(arXiv:2108.02692), so per-csum-block checksums compute as one packed
bit-matrix matmul on the MXU: transpose a (S, L) block batch so byte
position rides the contraction axis and the block index rides the lane
axis, apply the (32, 8L) contribution matrix through the shared
`xor_matmul` kernel, fold the four LE output byte-rows into uint32, and
XOR the zero-message constant.  One launch checksums every block of
every object that shared the aggregation window.

The host oracle is `utils/crc32c.crc32c` itself — not a reimplementation
— so the DEGRADED/fallback path is byte-identical by construction and
the device path is pinned byte-identical to it by tests across block
sizes and ragged tails.

Contribution matrix: the byte-step of the reflected-table update
``c' = T[(c ^ b) & 0xFF] ^ (c >> 8)`` is linear in (c, b) (T itself is a
linear LFSR map with T[0] = 0), so injecting bit t at byte i contributes
T[1 << t] propagated through the remaining L-1-i zero-input steps
A(c) = T[c & 0xFF] ^ (c >> 8).  One backward sweep C[L-1] = T[1 << t],
C[i-1] = A(C[i]) builds all L rows vectorized over the 8 bit columns;
the init/final 0xFFFFFFFF xors cancel in the delta and land in the
crc(0^L) constant.  Matrices are cached per L and placed on device once,
mempool-tracked under ``device_cache``.
"""

from __future__ import annotations

import numpy as np

from ceph_tpu.common.lockdep import make_lock as _lockdep_make_lock
from ceph_tpu.common.mempool import track_buffer as _hbm_track
from ceph_tpu.utils.crc32c import _TABLE, crc32c

from .dispatch import record_launch
from .offload_runtime import (
    AggTicket,
    LaunchAggregator,
    _AggGroup,
    register_service,
)

# Below this many total bytes a batch skips the runtime entirely: the
# host table loop beats dispatch + window latency on small metadata
# writes (the packed_gf.PACKED_MIN_BYTES reasoning, applied to csum).
CSUM_OFFLOAD_MIN_BYTES = 16 * 1024

_MATRIX_LOCK = _lockdep_make_lock("csum_matrix_cache")
_HOST_MATRICES: dict[int, np.ndarray] = {}  # L -> (32, 8L) uint8
_DEVICE_MATRICES: dict[int, object] = {}    # L -> device operand
_CONSTS: dict[int, int] = {}                # L -> crc32c(b"\x00" * L)
# distinct Ls are bounded in practice (BLOCK plus the compressed-length
# tail population); a pathological length churn must not pin HBM
_MATRIX_CACHE_CAP = 64


def _contribution_matrix(L: int) -> np.ndarray:
    """(32, 8L) GF(2) matrix in xor_matmul's LSB-first convention:
    row 8r+s = bit s of output LE byte r, column 8i+t = bit t of input
    byte i."""
    with _MATRIX_LOCK:
        bm = _HOST_MATRICES.get(L)
        if bm is not None:
            return bm
    rows = np.empty((L, 8), dtype=np.uint32)
    c = _TABLE[np.left_shift(1, np.arange(8))].astype(np.uint32)
    rows[L - 1] = c
    for i in range(L - 1, 0, -1):
        c = _TABLE[c & 0xFF] ^ (c >> np.uint32(8))
        rows[i - 1] = c
    bits = (rows[:, :, None] >> np.arange(32, dtype=np.uint32)) & 1
    bm = np.ascontiguousarray(bits.reshape(L * 8, 32).T.astype(np.uint8))
    with _MATRIX_LOCK:
        if len(_HOST_MATRICES) >= _MATRIX_CACHE_CAP:
            _HOST_MATRICES.clear()
        _HOST_MATRICES[L] = bm
    return bm


def _zero_const(L: int) -> int:
    with _MATRIX_LOCK:
        const = _CONSTS.get(L)
    if const is None:
        const = crc32c(b"\x00" * L)
        with _MATRIX_LOCK:
            if len(_CONSTS) >= _MATRIX_CACHE_CAP:
                _CONSTS.clear()
            _CONSTS[L] = const
    return const


def _device_matrix(L: int):
    """The contribution matrix as a resident device operand (one H2D
    per L per process), ledger-tracked like every other HBM holder."""
    with _MATRIX_LOCK:
        dev = _DEVICE_MATRICES.get(L)
        if dev is not None:
            return dev
    import jax.numpy as jnp

    dev = _hbm_track(
        jnp.asarray(_contribution_matrix(L)), "device_cache",
        site="csum_matrix",
    )
    with _MATRIX_LOCK:
        if len(_DEVICE_MATRICES) >= _MATRIX_CACHE_CAP:
            _DEVICE_MATRICES.clear()
        _DEVICE_MATRICES[L] = dev
    return dev


def crc32c_device(blocks: np.ndarray):
    """One batched device launch: (S, L) uint8 blocks -> (S,) uint32
    crc32c digests (device array; np.asarray forces it)."""
    import jax.numpy as jnp

    from .xor_mm import xor_matmul

    S, L = blocks.shape
    bm = _device_matrix(L)
    # byte position -> contraction rows, block index -> lanes: the
    # whole batch is ONE (32, 8L) x (8L, S) MXU matmul
    out = xor_matmul(bm, jnp.asarray(blocks).T)  # (4, S) LE crc bytes
    crcs = (
        out[0].astype(jnp.uint32)
        | (out[1].astype(jnp.uint32) << 8)
        | (out[2].astype(jnp.uint32) << 16)
        | (out[3].astype(jnp.uint32) << 24)
    ) ^ jnp.uint32(_zero_const(L))
    record_launch(S, blocks.nbytes)
    return crcs


def crc32c_host_rows(blocks: np.ndarray) -> np.ndarray:
    """Byte-identical host oracle: `utils/crc32c.crc32c` per row."""
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    return np.fromiter(
        (crc32c(row.tobytes()) for row in blocks),
        dtype=np.uint32,
        count=blocks.shape[0],
    )


class ChecksumAggregator(LaunchAggregator):
    """Cross-block / cross-object crc32c launch aggregation: every
    same-length csum block submitted inside one window rides ONE device
    matmul (background lane — checksums must never head-of-line-block
    client encodes).  Tickets resolve to (stripes,) uint32 digests."""

    PERF_NAME = "csum_aggregator"
    WHAT = "csum"
    SCHED_CLASS = "background"
    MEM_POOL = "offload_inflight"

    def submit_blocks(self, blocks: np.ndarray) -> AggTicket:
        """Queue one (S, L) uint8 block batch; returns its ticket."""
        shaped = np.ascontiguousarray(blocks, dtype=np.uint8)
        if shaped.ndim != 2:
            raise ValueError(f"expected (S, L) blocks, got {shaped.shape}")
        return self._submit(
            ("#csum", shaped.shape[1]), None, None, shaped[:, None, :]
        )

    def _dispatch(self, g: _AggGroup, data: np.ndarray, donate):
        S = data.shape[0]
        return crc32c_device(data.reshape(S, -1))

    def _dispatch_host(self, g: _AggGroup, data: np.ndarray) -> np.ndarray:
        return crc32c_host_rows(data.reshape(data.shape[0], -1))

    def _out_shape(self, g: _AggGroup, data_shape) -> tuple:
        return (data_shape[0],)

    def _donate_ok(self, g: _AggGroup, data_shape) -> bool:
        return False  # 4 output bytes per block; pooling buys nothing


_DEFAULT_CSUM_AGGREGATOR: ChecksumAggregator | None = None


def default_csum_aggregator() -> ChecksumAggregator:
    """Process-wide checksum aggregator shared by every BlueStore (and
    the EC-transaction fusion hook) in the process, so concurrent
    writers' csum blocks coalesce exactly like their encodes do."""
    global _DEFAULT_CSUM_AGGREGATOR
    if _DEFAULT_CSUM_AGGREGATOR is None:
        from ceph_tpu.common.options import OPTIONS

        _DEFAULT_CSUM_AGGREGATOR = ChecksumAggregator(
            window=int(OPTIONS["bluestore_csum_offload_window"].default),
            max_bytes=int(
                OPTIONS["bluestore_csum_offload_max_bytes"].default
            ),
        )
    return _DEFAULT_CSUM_AGGREGATOR


register_service(
    "csum", default_csum_aggregator, lane="background",
    oracle="utils/crc32c.crc32c",
    doc="BlueStore per-block crc32c as packed bit-matrix matmuls",
)


def checksum_blocks(
    chunks: list[bytes], offload: bool = True
) -> list[int]:
    """crc32c for each chunk, batched through the offload runtime when
    armed and profitable (chunks grouped by length — each length group
    is one submission riding the shared window), else the host loop.
    Returns digests in input order; the fallback matrix (device error,
    DEGRADED bypass, fault injection) yields identical values because
    the aggregator's host oracle IS `utils/crc32c`."""
    if not chunks:
        return []
    if not offload or sum(len(c) for c in chunks) < CSUM_OFFLOAD_MIN_BYTES:
        return [crc32c(c) for c in chunks]
    agg = default_csum_aggregator()
    by_len: dict[int, list[int]] = {}
    for i, c in enumerate(chunks):
        by_len.setdefault(len(c), []).append(i)
    out: list[int] = [0] * len(chunks)
    tickets = []
    for L, idxs in by_len.items():
        if L == 0:
            for i in idxs:
                out[i] = 0
            continue
        batch = np.frombuffer(
            b"".join(chunks[i] for i in idxs), dtype=np.uint8
        ).reshape(len(idxs), L)
        tickets.append((idxs, agg.submit_blocks(batch)))
    for idxs, ticket in tickets:
        crcs = ticket.result()
        for row, i in enumerate(idxs):
            out[i] = int(crcs[row])
    return out
