"""MDS daemon — server-side CephFS metadata (mirror of src/mds).

The reference's MDS (src/mds/MDSDaemon.cc, MDCache.cc 13.6k LoC,
Server.cc) owns the namespace: clients send MClientRequest metadata ops;
the MDS journals every mutation into the metadata pool BEFORE applying
it (MDLog/Journaler — metadata is never lost to an MDS crash), caches
dirfrags, writes them back lazily, and hands out **capabilities** so
clients can do file DATA I/O straight to the data pool without the MDS
in the loop.  This daemon keeps that architecture:

- **Namespace**: one object per directory in the metadata pool
  (`dir.<ino>` holding the dentry map, the CDir/CDentry/CInode dirfrag
  commit shape) — the same on-pool layout as the client-only
  fs.FileSystem library, so the two interoperate.
- **Journal (MDLog)**: every mutation appends a JSON event to
  `mds_journal` (RADOS append) before the reply is sent; dirty dirfrags
  flush lazily (tick or size threshold), then the journal trims by
  recording the flushed sequence in `mds_journal_head` and resetting the
  journal object (Journaler::flush + trim semantics).  Startup replays
  events past the flushed sequence — a crashed MDS loses nothing that
  was acknowledged.
- **Caps** (Capability.h / Locker.cc essence): open("w") needs an
  exclusive grant per inode; open("r") shares with other readers.  A
  conflicting open REVOKEs the holders' caps (MClientCaps REVOKE), waits
  for their ACKs (bounded — a dead client's session reset also releases),
  then grants.  File data I/O is client-direct; the MDS only brokers the
  right to do it.
- **Sessions**: one per client connection; a reset drops its caps and
  unblocks waiters (Server::handle_client_session teardown).

Rank scope: one ACTIVE rank (0) at a time; multi-MDS subtree
partitioning (MDCache migrator) is out of scope and documented as such.
**Standby/failover is mon-managed** (round-5): given a `monmap`, the
daemon boots as a STANDBY, beacons MMDSBeacon to the mons, and only
activates — load + journal REPLAY + serve — when the committed FSMap
(MMDSMap) names it rank 0 (MDSDaemon::handle_mds_map state machine,
boot → standby → replay → active).  Without a monmap it activates
immediately (library/embedded use).
"""

from __future__ import annotations

import asyncio
import json
import time

from ..common.errs import EAGAIN as EAGAIN_
from ..common.errs import EEXIST, EINVAL, ENOENT, ENOTDIR, ENOTEMPTY
from ..common.log import dout
from ..msg.messages import (
    MClientCaps,
    MClientReply,
    MClientRequest,
    MMDSBeacon,
    MMDSMap,
)
from ..msg.messenger import Connection, Dispatcher, Messenger

ROOT_INO = 1  # MDS_INO_ROOT
INOTABLE_OID = "mds_inotable"
JOURNAL_OID = "mds_journal"
JOURNAL_HEAD_OID = "mds_journal_head"
COMPLETED_OID = "mds_completed"  # journaled (client, tid) reply records
COMPLETED_CAP = 1024  # retained completed-request records (oldest drop)
# ops whose re-execution is NOT idempotent: their results are journaled
# per (client, tid) so a retry after failover replays the recorded reply
# (Server::handle_client_request's completed_requests check) instead of
# re-running and surfacing spurious EEXIST/ENOENT
MUTATING_OPS = frozenset(
    ("mkdir", "create", "symlink", "unlink", "rmdir", "rename", "setattr")
)
FLUSH_INTERVAL = 0.5
JOURNAL_FLUSH_BYTES = 1 << 20
REVOKE_TIMEOUT = 3.0  # mds_session_timeout scaled down
BEACON_INTERVAL = 1.0  # mds_beacon_interval (scaled down)


class MDS(Dispatcher):
    """One metadata server daemon (standby until the FSMap says active)."""

    def __init__(self, meta_ioctx=None, data_ioctx=None,
                 addr: str = "127.0.0.1:0",
                 layout: dict | None = None, stack: str = "posix",
                 name: str = "0", monmap=None, rados=None,
                 admin_socket: str = ""):
        self._admin_socket_path = admin_socket
        self.admin_socket = None
        self.meta = meta_ioctx
        self.data = data_ioctx
        self.name = name
        self.monmap = monmap
        # with `rados`, pools bind at PROMOTION from the fsmap's
        # assignment (the reference's MDSRank opening the metadata pool
        # named by its MDSMap); fixed ioctxs are the embedded path
        self.rados = rados
        self.fs_name = ""  # filesystem this daemon holds rank 0 of
        self.monc = None
        self.state = "boot"  # boot -> standby -> replay -> active
        self.mdsmap_epoch = 0
        self._beacon_task: asyncio.Task | None = None
        self._activate_task: asyncio.Task | None = None
        self.layout = layout or {
            "stripe_unit": 64 * 1024, "stripe_count": 2, "object_size": 1 << 20
        }
        self._bind_addr = addr
        self.msgr = Messenger(f"mds.{name}", stack=stack)
        self.msgr.add_dispatcher_head(self)
        # dirfrag cache: ino -> {name: entry dict}; which are dirty
        self._dirs: dict[int, dict] = {}
        self._dirty: set[int] = set()
        self._next_ino = 0
        self._ino_dirty = False
        self._journal_seq = 0
        self._journal_bytes = 0
        # completed non-idempotent requests: (client, tid) -> recorded
        # reply.  Journaled (write-ahead) and persisted at flush, so a
        # promoted standby serves a retried mkdir/create/unlink/rename
        # its ORIGINAL result instead of re-executing it.
        from collections import OrderedDict

        self._completed: "OrderedDict[tuple[str, int], dict]" = OrderedDict()
        self._completed_dirty = False
        self._flush_task: asyncio.Task | None = None
        self._running = False
        # caps: ino -> {conn: "r"|"w"} ; waiters for revoke acks
        self.caps: dict[int, dict[Connection, str]] = {}
        # (ino, tid) -> {"ev", "want", "requester"}: grant waits pending
        # on conflicting holders acking/releasing/dying
        self._revoke_waiters: dict[tuple[int, int], dict] = {}
        self._cap_tid = 0
        # file ino -> (parent dir ino, dentry name): lets handle-held ops
        # (setattr) address the INODE, immune to concurrent renames
        self._ino_loc: dict[int, tuple[int, str]] = {}
        from ..common.lockdep import make_async_lock

        # one mutation at a time (the MDS big lock; mds_lock in the ref)
        self._lock = make_async_lock("mds_big_lock")

    # -- lifecycle -------------------------------------------------------------

    async def start(self) -> None:
        await self.msgr.bind(self._bind_addr)
        self.addr = self.msgr.addr
        await self._start_admin_socket()
        if self.monmap is None:
            # embedded/library use: no mon control plane, activate now
            await self._activate()
            return
        # Mon-managed: beacon as a standby; the FSMap decides who is
        # rank 0 (MDSDaemon boot → standby in handle_mds_map).
        from ..mon.client import MonClient

        self.state = "standby"
        self.monc = MonClient(f"mds.{self.name}", self.monmap)
        self.monc.msgr.add_dispatcher_tail(self)  # MMDSMap arrives here
        await self.monc.subscribe("mdsmap")
        self._beacon_task = asyncio.create_task(self._beacon_loop())

    async def _start_admin_socket(self) -> None:
        """MDS admin socket (MDSDaemon::asok_command): status, session
        and cap introspection — what `ceph tell mds.<x> ...` reaches."""
        if not self._admin_socket_path:
            return
        from ..common.admin_socket import AdminSocket

        sock = AdminSocket(self._admin_socket_path)
        sock.register(
            "status",
            lambda cmd: {
                "name": self.name,
                "state": f"up:{self.state}" if self.state != "boot" else "boot",
                "fs": self.fs_name,
                "mdsmap_epoch": self.mdsmap_epoch,
                "journal_seq": self._journal_seq,
                "dirty_dirfrags": len(self._dirty),
            },
            "this MDS's state (MDSDaemon::dump_status)",
        )
        sock.register(
            "session ls",
            lambda cmd: [
                {
                    "client": getattr(conn, "peer_name", ""),
                    "caps": sum(
                        1 for holders in self.caps.values() if conn in holders
                    ),
                }
                for conn in {
                    c for holders in self.caps.values() for c in holders
                }
            ],
            "connected cap-holding sessions (Server::dump_sessions)",
        )
        sock.register(
            "dump caps",
            lambda cmd: {
                str(ino): {
                    getattr(c, "peer_name", "?"): mode
                    for c, mode in holders.items()
                }
                for ino, holders in self.caps.items()
            },
            "granted capabilities per inode (Locker state)",
        )
        await sock.start()
        self.admin_socket = sock

    async def _activate(self, fs: dict | None = None) -> None:
        """standby → replay → active (MDSDaemon::boot_start / replay_done):
        bind the assigned filesystem's pools, load the on-pool state,
        replay the journal, start serving."""
        self.state = "replay"
        if fs is not None and self.rados is not None:
            self.meta = await self.rados.open_ioctx(fs["meta_pool"])
            self.data = await self.rados.open_ioctx(fs["data_pool"])
        await self._load_or_mkfs()
        await self._replay_journal()
        self._running = True
        self.state = "active"
        self._flush_task = asyncio.create_task(self._flush_loop())
        dout(
            "mds", 1,
            f"mds.{self.name}: now active (rank 0"
            + (f" of {self.fs_name}" if self.fs_name else "") + ")",
        )

    def _demote(self) -> None:
        """active → standby (fs removed / rank reassigned): stop serving
        and drop volatile state; the on-pool journal stays authoritative."""
        self._running = False
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        self._dirs.clear()
        self._dirty.clear()
        self._ino_dirty = False
        self._completed.clear()  # reloaded from pool+journal on promotion
        self._completed_dirty = False
        self.caps.clear()
        self._revoke_waiters.clear()
        self._ino_loc.clear()
        self._journal_seq = 0
        self._journal_bytes = 0
        self.fs_name = ""
        self.state = "standby"
        dout("mds", 1, f"mds.{self.name}: demoted to standby")

    async def _beacon_loop(self) -> None:
        while True:
            # the daemon's RADOS client instance rides the beacon so the
            # mon can fence exactly this instance's pool I/O on failover
            client = ""
            if self.rados is not None and getattr(self.rados, "objecter", None):
                client = self.rados.objecter.reqid_name
            beacon = MMDSBeacon(
                name=self.name, addr=self.msgr.addr, state=self.state,
                client=client,
            )
            for mon_name in self.monmap.ranks:
                try:
                    await self.monc.msgr.send_to(
                        self.monmap.addrs[mon_name], beacon
                    )
                except ConnectionError:
                    continue
            try:
                await self.monc.resubscribe()
            except ConnectionError:
                pass
            await asyncio.sleep(BEACON_INTERVAL)

    def _handle_mds_map(self, msg: MMDSMap) -> None:
        if msg.epoch <= self.mdsmap_epoch:
            return
        self.mdsmap_epoch = msg.epoch
        mine = ""
        my_fs = None
        for fs_name, fs in msg.filesystems().items():
            if fs.get("active_name") == self.name:
                mine, my_fs = fs_name, fs
                break
        if mine and self.state == "standby" and self._activate_task is None:
            self.fs_name = mine
            task = asyncio.create_task(self._activate(my_fs))
            task.add_done_callback(lambda _t: setattr(self, "_activate_task", None))
            self._activate_task = task
        elif not mine and self.state in ("replay", "active"):
            if self._activate_task is not None:
                self._activate_task.cancel()
                self._activate_task = None
            self._demote()

    async def stop(self, flush: bool = True) -> None:
        """flush=False models a CRASH: dirty dirfrags are abandoned and
        the journal must make the next active whole (replay test hook)."""
        was_active = self._running
        self._running = False
        for t in (self._flush_task, self._beacon_task, self._activate_task):
            if t is not None:
                t.cancel()
        self._flush_task = self._beacon_task = self._activate_task = None
        if was_active and flush:
            await self._flush()
        if self.admin_socket is not None:
            await self.admin_socket.stop()
            self.admin_socket = None
        if self.monc is not None:
            await self.monc.msgr.shutdown()
            self.monc = None
        await self.msgr.shutdown()

    async def _load_or_mkfs(self) -> None:
        try:
            table = json.loads((await self.meta.read(INOTABLE_OID)).decode())
            self._next_ino = table["next"]
        except Exception:
            # fresh fs (ceph fs new): root dir + inotable
            self._next_ino = 2
            await self.meta.write_full(
                INOTABLE_OID, json.dumps({"next": 2}).encode()
            )
            await self.meta.write_full(f"dir.{ROOT_INO}", b"{}")
        try:
            raw = await self.meta.read(COMPLETED_OID)
            for client, tid, rec in json.loads(raw.decode() or "[]"):
                self._completed[(client, int(tid))] = rec
        except Exception:
            pass  # fresh fs / pre-upgrade pool: no completed table yet

    # -- journal (MDLog) -------------------------------------------------------

    async def _replay_journal(self) -> None:
        """Apply journaled events past the flushed sequence (MDLog replay:
        a crash between journal append and dirfrag write-back must lose
        nothing that was acknowledged to a client)."""
        flushed = 0
        try:
            head = json.loads((await self.meta.read(JOURNAL_HEAD_OID)).decode())
            flushed = head.get("flushed", 0)
        except Exception:
            pass
        try:
            raw = await self.meta.read(JOURNAL_OID)
        except Exception:
            return
        replayed = 0
        for line in raw.splitlines():
            if not line.strip():
                continue
            try:
                ev = json.loads(line.decode())
            except json.JSONDecodeError:
                break  # torn tail: a partial append never acked, drop it
            self._journal_seq = max(self._journal_seq, ev["seq"])
            if ev["seq"] <= flushed:
                continue
            await self._apply_event(ev)
            replayed += 1
        if replayed:
            dout("mds", 1, f"mds.0: replayed {replayed} journal events")
            self._journal_bytes = len(raw)

    async def _apply_event(self, ev: dict) -> None:
        op = ev["op"]
        if op == "set_dentry":
            d = await self._dir(ev["dir"])
            d[ev["name"]] = ev["entry"]
            self._dirty.add(ev["dir"])
            if ev["entry"].get("type") == "file":
                self._ino_loc[ev["entry"]["ino"]] = (ev["dir"], ev["name"])
        elif op == "rm_dentry":
            d = await self._dir(ev["dir"])
            gone = d.pop(ev["name"], None)
            self._dirty.add(ev["dir"])
            if gone and gone.get("type") == "file":
                # a rename's set_dentry already retargeted the map: only
                # drop it when it still points at the removed location
                if self._ino_loc.get(gone["ino"]) == (ev["dir"], ev["name"]):
                    del self._ino_loc[gone["ino"]]
        elif op == "mkdir_obj":
            self._dirs.setdefault(ev["ino"], {})
            self._dirty.add(ev["ino"])
        elif op == "rmdir_obj":
            self._dirs.pop(ev["ino"], None)
            self._dirty.discard(ev["ino"])
            try:
                await self.meta.remove(f"dir.{ev['ino']}")
            except Exception as e:
                # replayed rmdir of an already-gone object: expected on
                # re-replay, logged so real pool errors stay visible
                dout("mds", 4,
                     f"mds.{self.name}: replay rmdir {ev['ino']}: {e!r}")
        elif op == "inotable":
            self._next_ino = ev["next"]
            self._ino_dirty = True
        elif op == "completed_req":
            key = (ev["client"], int(ev["tid"]))
            self._completed[key] = {
                "result": ev.get("result", 0),
                "payload": ev["payload"],
            }
            self._completed.move_to_end(key)
            while len(self._completed) > COMPLETED_CAP:
                self._completed.popitem(last=False)
            self._completed_dirty = True

    async def _journal(self, *events: dict) -> None:
        """Append events durably BEFORE applying/replying (MDLog::submit +
        flush: the write-ahead property)."""
        lines = []
        for ev in events:
            self._journal_seq += 1
            ev["seq"] = self._journal_seq
            lines.append(json.dumps(ev).encode() + b"\n")
        blob = b"".join(lines)
        await self.meta.append(JOURNAL_OID, blob)
        self._journal_bytes += len(blob)
        for ev in events:
            await self._apply_event(ev)
        if self._journal_bytes > JOURNAL_FLUSH_BYTES and self._running:
            # size-triggered early flush (Journaler's segment threshold);
            # scheduled, not inline: _flush takes the big lock we hold
            asyncio.get_event_loop().create_task(self._flush())

    async def _flush_loop(self) -> None:
        while self._running:
            await asyncio.sleep(FLUSH_INTERVAL)
            try:
                await self._flush()
            except Exception as e:  # pool hiccup: retry next tick
                dout("mds", 1, f"mds.0: flush failed: {e}")

    async def _flush(self) -> None:
        """Write back dirty dirfrags, then trim the journal
        (Journaler::flush + LogSegment trim).  Runs under the big lock:
        a mutation journaled between the dirty-set snapshot and the trim
        would otherwise be cleared unwritten and trimmed — losing acked
        metadata, the exact thing the journal exists to prevent."""
        async with self._lock:
            if (
                not self._dirty
                and not self._ino_dirty
                and not self._completed_dirty
            ):
                return
            for ino in sorted(self._dirty):
                await self.meta.write_full(
                    f"dir.{ino}", json.dumps(self._dirs.get(ino, {})).encode()
                )
            self._dirty.clear()
            if self._ino_dirty:
                await self.meta.write_full(
                    INOTABLE_OID, json.dumps({"next": self._next_ino}).encode()
                )
                self._ino_dirty = False
            if self._completed_dirty:
                # the completed-request table must survive the journal
                # trim below: a trimmed completed_req event can no longer
                # be replayed, so the table itself is the durable record
                await self.meta.write_full(
                    COMPLETED_OID,
                    json.dumps(
                        [[c, t, rec] for (c, t), rec in self._completed.items()]
                    ).encode(),
                )
                self._completed_dirty = False
            await self.meta.write_full(
                JOURNAL_HEAD_OID,
                json.dumps({"flushed": self._journal_seq}).encode(),
            )
            await self.meta.write_full(JOURNAL_OID, b"")
            self._journal_bytes = 0

    # -- namespace helpers -----------------------------------------------------

    async def _dir(self, ino: int) -> dict:
        d = self._dirs.get(ino)
        if d is None:
            try:
                raw = await self.meta.read(f"dir.{ino}")
                d = json.loads(raw.decode() or "{}")
            except Exception as e:
                # an unreadable/undecodable dirfrag treated as empty is
                # potential METADATA LOSS — never swallow it silently
                dout("mds", 1, f"mds.{self.name}: dirfrag {ino} "
                               f"unreadable, treating as empty: {e!r}")
                d = {}
            self._dirs[ino] = d
            for name, entry in d.items():
                if entry.get("type") == "file":
                    self._ino_loc.setdefault(entry["ino"], (ino, name))
        return d

    @staticmethod
    def _split(path: str) -> list[str]:
        return [p for p in path.split("/") if p]

    async def _walk(self, path: str) -> tuple[int, dict]:
        ino = ROOT_INO
        d = await self._dir(ino)
        for part in self._split(path):
            entry = d.get(part)
            if entry is None:
                raise _Err(ENOENT, f"{path}: no such entry {part!r}")
            if entry["type"] != "dir":
                raise _Err(ENOTDIR, f"{path}: {part!r} is a file")
            ino = entry["ino"]
            d = await self._dir(ino)
        return ino, d

    async def _walk_parent(self, path: str) -> tuple[int, dict, str]:
        parts = self._split(path)
        if not parts:
            raise _Err(EINVAL, "root has no parent")
        ino, d = await self._walk("/".join(parts[:-1]))
        return ino, d, parts[-1]

    # -- dispatch --------------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MMDSMap):
            self._handle_mds_map(msg)
            return True
        if isinstance(msg, MClientRequest):
            if self.state not in ("active",) and self.monmap is not None:
                # not rank 0 (standby, or mid-replay): clients must
                # re-resolve the active from the mdsmap and retry
                # (the reference returns CEPH_MDS_STATE-gated ESTALE)
                async def _reject() -> None:
                    try:
                        await conn.send_message(
                            MClientReply(
                                tid=msg.tid, result=-EAGAIN_, payload=b"{}"
                            )
                        )
                    except ConnectionError:
                        pass

                asyncio.get_event_loop().create_task(_reject())
                return True
            asyncio.get_event_loop().create_task(self._handle(conn, msg))
            return True
        if isinstance(msg, MClientCaps):
            if msg.op in (MClientCaps.ACK, MClientCaps.RELEASE):
                # a revoke-ack IS the release of the revoked caps
                self._drop_cap(msg.ino, conn)
            return True
        return False

    def ms_handle_reset(self, conn: Connection) -> None:
        """Session death releases its caps (Session teardown in Server.cc)."""
        for ino in list(self.caps):
            self._drop_cap(ino, conn)

    def _invalidate_caps(self, ino: int) -> None:
        """The inode is gone (unlink / rename-over): revoke every holder
        (fire-and-forget; the handle is invalid regardless) and clear the
        cap table so nothing leaks."""
        for holder in list(self.caps.pop(ino, {})):
            self._cap_tid += 1
            push = MClientCaps(
                op=MClientCaps.REVOKE, ino=ino, caps="", tid=self._cap_tid
            )

            async def _send(holder=holder, push=push) -> None:
                try:
                    await holder.send_message(push)
                except ConnectionError:
                    pass

            asyncio.get_event_loop().create_task(_send())
        self._check_grant_waiters(ino)

    def _drop_cap(self, ino: int, conn: Connection) -> None:
        holders = self.caps.get(ino)
        if holders and conn in holders:
            del holders[conn]
            if not holders:
                del self.caps[ino]
        self._check_grant_waiters(ino)

    def _check_grant_waiters(self, ino: int) -> None:
        """Wake grant waits whose conflicts are gone (acked, released, or
        session-reset)."""
        for (w_ino, _tid), w in list(self._revoke_waiters.items()):
            if w_ino != ino:
                continue
            remaining = [
                c
                for c in self._conflicting_holders(ino, w["want"])
                if c is not w["requester"]
            ]
            if not remaining:
                w["ev"].set()

    async def _handle(self, conn: Connection, msg: MClientRequest) -> None:
        try:
            args = json.loads(msg.args.decode() or "{}")
            key = None
            client = getattr(msg, "client", "") or ""
            if client:
                key = (client, int(msg.tid))
            async with self._lock:
                done = self._completed.get(key) if key is not None else None
                if done is not None:
                    # a retry of an already-applied request (stable reqid
                    # across resends): replay the recorded reply instead
                    # of re-executing — re-running mkdir/create/unlink/
                    # rename would return spurious EEXIST/ENOENT after a
                    # failover even though the ORIGINAL attempt succeeded
                    payload = done["payload"]
                    await self._reissue_caps(conn, payload)
                else:
                    payload = await self._dispatch_op(conn, msg.op, args)
                    if key is not None and msg.op in MUTATING_OPS:
                        # journal the completion write-ahead of the reply:
                        # a crash between apply and this record at worst
                        # re-executes (today's behavior); a crash after it
                        # replays the right answer
                        await self._journal(
                            {
                                "op": "completed_req",
                                "client": client,
                                "tid": int(msg.tid),
                                "result": 0,
                                "payload": payload,
                            }
                        )
            reply = MClientReply(
                tid=msg.tid, result=0, payload=json.dumps(payload).encode()
            )
        except _Err as e:
            reply = MClientReply(tid=msg.tid, result=e.errno, payload=b"{}")
        except Exception as e:  # a server bug must not wedge the client
            dout("mds", 0, f"mds.0: {msg.op} raised {e!r}")
            reply = MClientReply(tid=msg.tid, result=-EINVAL, payload=b"{}")
        try:
            await conn.send_message(reply)
        except ConnectionError:
            pass

    async def _reissue_caps(self, conn: Connection, payload: dict) -> None:
        """A replayed create/open result promised capabilities: grant
        them to the retrying session (the original grant died with the
        failed-over daemon), or the client's next data op would bounce
        off the cap check it believes it passed."""
        entry = payload.get("entry") if isinstance(payload, dict) else None
        caps = payload.get("caps") if isinstance(payload, dict) else None
        if entry and caps:
            await self._acquire_caps(conn, entry["ino"], caps)

    async def _dispatch_op(self, conn, op: str, args: dict) -> dict:
        if op == "mkdir":
            return await self._op_mkdir(args)
        if op == "create":
            return await self._op_create(conn, args)
        if op == "lookup":
            return await self._op_lookup(args)
        if op == "readdir":
            ino, d = await self._walk(args["path"])
            return {"entries": sorted(d)}
        if op == "readdirplus":
            # Server::handle_client_readdir with stat records inline (the
            # reference's readdir returns full InodeStats per dentry)
            ino, d = await self._walk(args["path"])
            return {"entries": {n: d[n] for n in sorted(d)}}
        if op == "unlink":
            return await self._op_unlink(args)
        if op == "rmdir":
            return await self._op_rmdir(args)
        if op == "rename":
            return await self._op_rename(args)
        if op == "setattr":
            return await self._op_setattr(conn, args)
        if op == "open":
            return await self._op_open(conn, args)
        if op == "symlink":
            return await self._op_symlink(args)
        if op == "readlink":
            return await self._op_readlink(args)
        raise _Err(EINVAL, f"unknown mds op {op!r}")

    async def _op_mkdir(self, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        if name in pdir:
            raise _Err(EEXIST, f"{args['path']} exists")
        ino = self._next_ino
        entry = {"ino": ino, "type": "dir", "mtime": time.time()}
        await self._journal(
            {"op": "inotable", "next": ino + 1},
            {"op": "mkdir_obj", "ino": ino},
            {"op": "set_dentry", "dir": pino, "name": name, "entry": entry},
        )
        return {"ino": ino}

    async def _op_create(self, conn, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        if name in pdir:
            raise _Err(EEXIST, f"{args['path']} exists")
        ino = self._next_ino
        entry = {
            "ino": ino,
            "type": "file",
            "size": 0,
            "mtime": time.time(),
            "layout": dict(self.layout),
        }
        await self._journal(
            {"op": "inotable", "next": ino + 1},
            {"op": "set_dentry", "dir": pino, "name": name, "entry": entry},
        )
        caps = await self._acquire_caps(conn, ino, args.get("caps", "w"))
        return {"entry": entry, "caps": caps}

    async def _op_symlink(self, args) -> dict:
        """Server::handle_client_symlink: a dentry of type symlink whose
        target string lives in the entry (CInode symlink member)."""
        pino, pdir, name = await self._walk_parent(args["path"])
        if name in pdir:
            raise _Err(EEXIST, f"{args['path']} exists")
        ino = self._next_ino
        entry = {
            "ino": ino, "type": "symlink", "target": args["target"],
            "mtime": time.time(),
        }
        await self._journal(
            {"op": "inotable", "next": ino + 1},
            {"op": "set_dentry", "dir": pino, "name": name, "entry": entry},
        )
        return {"entry": entry}

    async def _op_readlink(self, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        entry = pdir.get(name)
        if entry is None:
            raise _Err(ENOENT, args["path"])
        if entry["type"] != "symlink":
            raise _Err(EINVAL, f"{args['path']} is not a symlink")
        return {"target": entry["target"]}

    async def _op_lookup(self, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        entry = pdir.get(name)
        if entry is None:
            raise _Err(ENOENT, args["path"])
        return {"entry": entry}

    async def _op_unlink(self, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        entry = pdir.get(name)
        if entry is None:
            raise _Err(ENOENT, args["path"])
        if entry["type"] == "dir":
            raise _Err(EINVAL, f"{args['path']} is a directory (use rmdir)")
        await self._journal(
            {"op": "rm_dentry", "dir": pino, "name": name}
        )
        # open holders lose their caps: the inode is gone and the client
        # will purge its data objects (cap invalidation on unlink)
        self._invalidate_caps(entry["ino"])
        return {"entry": entry}  # client purges the data objects

    async def _op_rmdir(self, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        entry = pdir.get(name)
        if entry is None:
            raise _Err(ENOENT, args["path"])
        if entry["type"] != "dir":
            raise _Err(ENOTDIR, args["path"])
        if await self._dir(entry["ino"]):
            raise _Err(ENOTEMPTY, args["path"])
        await self._journal(
            {"op": "rm_dentry", "dir": pino, "name": name},
            {"op": "rmdir_obj", "ino": entry["ino"]},
        )
        return {}

    async def _op_rename(self, args) -> dict:
        sparts = self._split(args["src"])
        dparts = self._split(args["dst"])
        if sparts == dparts:
            # self-rename is a no-op, NOT set+remove of the same dentry
            _pino, pdir, name = await self._walk_parent(args["src"])
            entry = pdir.get(name)
            if entry is None:
                raise _Err(ENOENT, args["src"])
            return {"entry": entry, "replaced": None}
        if dparts[: len(sparts)] == sparts:
            # moving a directory into its own subtree detaches it into an
            # unreachable cycle (fs.py guards identically)
            raise _Err(EINVAL, f"cannot move {args['src']} into itself")
        spino, spdir, sname = await self._walk_parent(args["src"])
        entry = spdir.get(sname)
        if entry is None:
            raise _Err(ENOENT, args["src"])
        dpino, dpdir, dname = await self._walk_parent(args["dst"])
        existing = dpdir.get(dname)
        events = []
        if existing is not None:
            if existing["type"] == "dir" and await self._dir(existing["ino"]):
                raise _Err(ENOTEMPTY, args["dst"])
            if existing["type"] != entry["type"]:
                raise _Err(EINVAL, "rename across entry types")
            if existing["type"] == "dir":
                # reclaim the replaced empty directory's dirfrag object
                events.append({"op": "rmdir_obj", "ino": existing["ino"]})
            else:
                self._invalidate_caps(existing["ino"])  # replaced-over file
        events += [
            {"op": "set_dentry", "dir": dpino, "name": dname, "entry": entry},
            {"op": "rm_dentry", "dir": spino, "name": sname},
        ]
        await self._journal(*events)
        return {"entry": entry, "replaced": existing}

    async def _op_setattr(self, conn, args) -> dict:
        """Handle-held attribute updates address the INODE when the client
        supplies it: a concurrent rename (or replace-by-create at the old
        path) must never let one file's setattr land on another."""
        want_ino = args.get("ino")
        if want_ino is not None and conn not in self.caps.get(want_ino, {}):
            # a revoked holder's straggling size update must not land
            # after the new holder's grant (Locker's cap check on flush)
            raise _Err(EAGAIN_, f"ino {want_ino}: caps not held")
        if want_ino is not None and want_ino in self._ino_loc:
            pino, name = self._ino_loc[want_ino]
            pdir = await self._dir(pino)
        else:
            pino, pdir, name = await self._walk_parent(args["path"])
        entry = pdir.get(name)
        if entry is None:
            raise _Err(ENOENT, args["path"])
        if want_ino is not None and entry["ino"] != want_ino:
            raise _Err(ENOENT, f"{args['path']}: stale handle (renamed over)")
        entry = dict(entry)
        for field in ("size", "mtime"):
            if field in args:
                entry[field] = args[field]
        await self._journal(
            {"op": "set_dentry", "dir": pino, "name": name, "entry": entry}
        )
        return {"entry": entry}

    # -- capabilities (Locker.cc essence) --------------------------------------

    def _conflicting_holders(self, ino: int, want: str) -> list:
        holders = self.caps.get(ino, {})
        if want == "w":
            return list(holders)  # exclusive: anyone conflicts
        return [c for c, m in holders.items() if m == "w"]

    async def _acquire_caps(self, conn, ino: int, want: str) -> str:
        """Grant caps, revoking conflicting holders first (Locker's
        issue/revoke cycle).  The grant WAITS for every conflicting holder
        to ack/release (or die, or time out) — granting early would let
        the old holder's in-flight writes land after the new holder's
        open returns, the exact race revocation exists to prevent."""
        conflicts = [
            c for c in self._conflicting_holders(ino, want) if c is not conn
        ]
        if conflicts:
            self._cap_tid += 1
            tid = self._cap_tid
            ev = asyncio.Event()
            self._revoke_waiters[(ino, tid)] = {
                "ev": ev, "want": want, "requester": conn
            }
            for holder in conflicts:
                try:
                    await holder.send_message(
                        MClientCaps(
                            op=MClientCaps.REVOKE, ino=ino, caps="", tid=tid
                        )
                    )
                except ConnectionError:
                    self._drop_cap(ino, holder)  # dead session forfeits now
            self._check_grant_waiters(ino)
            try:
                await asyncio.wait_for(ev.wait(), REVOKE_TIMEOUT)
            except asyncio.TimeoutError:
                # unresponsive holders forfeit (mds_session_timeout)
                for holder in [
                    c
                    for c in self._conflicting_holders(ino, want)
                    if c is not conn
                ]:
                    self._drop_cap(ino, holder)
            finally:
                self._revoke_waiters.pop((ino, tid), None)
        self.caps.setdefault(ino, {})[conn] = want
        return want

    async def _op_open(self, conn, args) -> dict:
        pino, pdir, name = await self._walk_parent(args["path"])
        entry = pdir.get(name)
        if entry is None:
            raise _Err(ENOENT, args["path"])
        if entry["type"] != "file":
            raise _Err(EINVAL, f"{args['path']} is a directory")
        caps = await self._acquire_caps(conn, entry["ino"], args.get("caps", "r"))
        return {"entry": entry, "caps": caps}


class _Err(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(msg)
