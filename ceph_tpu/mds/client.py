"""CephFS client against a live MDS — mirror of src/client/Client.cc.

Metadata ops go to the MDS over MClientRequest/MClientReply; file DATA
I/O goes straight to the data pool through the striper using the layout
the MDS handed back (Client.cc file_to_extents → Objecter) — the MDS is
never in the data path.  Capabilities gate file access: open() acquires
them, a revoke push (MClientCaps REVOKE, when another client wants a
conflicting open) invalidates the handle, and the next use raises so the
caller re-opens (the reference's cap-wait loop, surfaced as an explicit
error in this async library).
"""

from __future__ import annotations

import asyncio
import json

from ..common.errs import EAGAIN, EEXIST
from ..msg.messages import MClientCaps, MClientReply, MClientRequest, MMDSMap
from ..msg.messenger import Connection, Dispatcher, Messenger
from ..striper import StripedObject, StripePolicy


class FsClientError(Exception):
    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(f"{msg} (errno {self.errno})")


class FileHandle:
    """An open file: inode record + held caps (the Fh/Inode pair)."""

    def __init__(self, client: "CephFSClient", path: str, entry: dict, caps: str):
        self.client = client
        self.path = path
        self.entry = entry
        self.caps = caps
        self.valid = True
        self._inflight = 0
        self._idle = asyncio.Event()
        self._idle.set()

    def _require(self, need: str) -> None:
        if not self.valid:
            raise FsClientError(
                EAGAIN, f"{self.path}: caps revoked; re-open the file"
            )
        if need == "w" and self.caps != "w":
            raise FsClientError(EAGAIN, f"{self.path}: no write caps")

    def _data(self) -> StripedObject:
        lay = self.entry["layout"]
        return StripedObject(
            self.client.data,
            f"{self.entry['ino']:x}",
            StripePolicy(
                stripe_unit=lay["stripe_unit"],
                stripe_count=lay["stripe_count"],
                object_size=lay["object_size"],
            ),
        )

    def _op_started(self) -> None:
        self._inflight += 1
        self._idle.clear()

    def _op_done(self) -> None:
        self._inflight -= 1
        if self._inflight == 0:
            self._idle.set()

    async def write(self, data: bytes, off: int = 0) -> None:
        self._require("w")
        self._op_started()
        try:
            await self._data().write(data, off)
            new_size = max(self.entry.get("size", 0), off + len(data))
            if new_size != self.entry.get("size", 0):
                # ino-addressed: a concurrent rename must not land this on
                # a different file that now occupies our old path
                rep = await self.client._request(
                    "setattr",
                    {
                        "path": self.path,
                        "ino": self.entry["ino"],
                        "size": new_size,
                    },
                )
                self.entry = rep["entry"]
        finally:
            self._op_done()

    async def read(self, length: int = 0, off: int = 0) -> bytes:
        self._require("r")
        size = self.entry.get("size", 0)
        if off >= size:
            return b""
        length = min(length or size - off, size - off)
        return await self._data().read(length, off)

    async def truncate(self, size: int) -> None:
        """Shrink/extend: data objects truncate first, then the inode size
        (Client::ll_truncate ordering — stale striped bytes must never
        reappear on a later extension)."""
        self._require("w")
        self._op_started()
        try:
            await self._data().truncate(size)
            rep = await self.client._request(
                "setattr",
                {"path": self.path, "ino": self.entry["ino"], "size": size},
            )
            self.entry = rep["entry"]
        finally:
            self._op_done()

    async def close(self) -> None:
        if self.valid:
            self.valid = False
            ino = self.entry["ino"]
            held = self.client._handles.get(ino)
            if held is not None:
                try:
                    held.remove(self)
                except ValueError:
                    pass
                if not held:
                    del self.client._handles[ino]
            await self.client._release_caps(ino)


class CephFSClient(Dispatcher):
    """libcephfs-like handle to the fs: active MDS + a data pool.

    Two addressing modes: a fixed `mds_addr` (embedded/single-MDS use),
    or `monmap=` — the client subscribes to the mdsmap, resolves rank 0
    from the FSMap, and RE-resolves on failover, retrying the op against
    the promoted standby (Client::handle_mds_map + request resend)."""

    def __init__(
        self, mds_addr: str = "", data_ioctx=None, name: str = "client.fs",
        stack: str = "posix", monmap=None, fs_name: str = "",
    ):
        self.mds_addr = mds_addr
        self.fs_name = fs_name  # "" = the first filesystem in the fsmap
        self.data = data_ioctx
        self.monmap = monmap
        # per-instance identity for MDS request dedup: (client_id, tid)
        # is stable across retries, so a resent non-idempotent op replays
        # the MDS's recorded result instead of re-executing (the
        # reference's session-scoped completed_requests)
        import secrets

        self.client_id = f"{name}.{secrets.token_hex(4)}"
        self.monc = None
        self._mdsmap_epoch = 0
        self._mds_changed = asyncio.Event()
        self.msgr = Messenger(name, stack=stack)
        self.msgr.add_dispatcher_head(self)
        self._tid = 0
        self._replies: dict[int, asyncio.Future] = {}
        self._handles: dict[int, list[FileHandle]] = {}  # ino -> open fhs

    async def connect(self, timeout: float = 10.0) -> None:
        """Mon mode: subscribe to the mdsmap and wait for an active MDS."""
        if self.monmap is None:
            return
        from ..mon.client import MonClient

        self.monc = MonClient(self.msgr.name + ".monc", self.monmap)
        self.monc.msgr.add_dispatcher_tail(self)
        deadline = asyncio.get_event_loop().time() + timeout
        while not self.mds_addr:
            await self.monc.subscribe("mdsmap")
            if asyncio.get_event_loop().time() > deadline:
                raise FsClientError(EAGAIN, "no active MDS in the fsmap")
            try:
                await asyncio.wait_for(self._mds_changed.wait(), 0.5)
            except asyncio.TimeoutError:
                pass
            self._mds_changed.clear()

    async def shutdown(self) -> None:
        if self.monc is not None:
            await self.monc.msgr.shutdown()
            self.monc = None
        await self.msgr.shutdown()

    # -- dispatch --------------------------------------------------------------

    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if isinstance(msg, MMDSMap):
            if msg.epoch > self._mdsmap_epoch:
                self._mdsmap_epoch = msg.epoch
                fss = msg.filesystems()
                if self.fs_name:
                    fs = fss.get(self.fs_name, {})
                else:
                    fs = fss[sorted(fss)[0]] if fss else {}
                addr = fs.get("active_addr", "")
                if addr != self.mds_addr:
                    self.mds_addr = addr
                    if addr:
                        self._mds_changed.set()
            return True
        if isinstance(msg, MClientReply):
            fut = self._replies.pop(msg.tid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            return True
        if isinstance(msg, MClientCaps) and msg.op == MClientCaps.REVOKE:
            # the MDS wants these caps back: invalidate local handles, then
            # ack only after their in-flight data ops DRAIN — acking while
            # a write coroutine is suspended mid-striper would let our
            # bytes land after the new holder's grant (Client::handle_caps
            # flush-before-ack)
            handles = self._handles.pop(msg.ino, [])
            for fh in handles:
                fh.valid = False
            ack = MClientCaps(
                op=MClientCaps.ACK, ino=msg.ino, caps="", tid=msg.tid
            )

            async def _drain_then_ack() -> None:
                for fh in handles:
                    await fh._idle.wait()
                try:
                    await conn.send_message(ack)
                except ConnectionError:
                    pass

            asyncio.get_event_loop().create_task(_drain_then_ack())
            return True
        return False

    async def _request(self, op: str, args: dict, timeout: float = 10.0) -> dict:
        """One metadata op with failover retry in mon mode: a dead or
        not-yet-active MDS (-EAGAIN / connection loss / reply timeout)
        re-resolves rank 0 from the mdsmap and resends (Client request
        resend on mds_map, Client.cc).

        The reqid (client_id, tid) is allocated ONCE and reused on every
        retry — a fresh tid per attempt would defeat the MDS's completed-
        request dedup and re-execute non-idempotent ops (mkdir/create/
        unlink/rename), surfacing spurious EEXIST/ENOENT after failover."""
        deadline = asyncio.get_event_loop().time() + timeout
        attempt = 0
        self._tid += 1
        tid = self._tid
        while True:
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._replies[tid] = fut
            msg = MClientRequest(
                tid=tid, op=op, args=json.dumps(args).encode(),
                client=self.client_id,
            )
            reply: MClientReply | None = None
            try:
                await self.msgr.send_to(self.mds_addr, msg)
                step = 10.0 if self.monc is None else 1.0
                left = deadline - asyncio.get_event_loop().time()
                reply = await asyncio.wait_for(fut, max(min(step, left), 0.05))
            except (ConnectionError, asyncio.TimeoutError, OSError):
                reply = None
            finally:
                self._replies.pop(tid, None)
            if reply is not None and reply.result != -EAGAIN:
                if reply.result < 0:
                    raise FsClientError(reply.result, f"{op} {args}")
                return json.loads(reply.payload.decode() or "{}")
            if self.monc is None or asyncio.get_event_loop().time() > deadline:
                err = reply.result if reply is not None else EAGAIN
                raise FsClientError(err, f"{op} {args}: mds unavailable")
            # wait for a newer fsmap (or just retry after a beat)
            attempt += 1
            try:
                await self.monc.subscribe("mdsmap", self._mdsmap_epoch + 1)
            except ConnectionError:
                pass
            try:
                await asyncio.wait_for(self._mds_changed.wait(), 0.5)
            except asyncio.TimeoutError:
                pass
            self._mds_changed.clear()

    async def _release_caps(self, ino: int) -> None:
        rel = MClientCaps(op=MClientCaps.RELEASE, ino=ino, caps="", tid=0)
        try:
            await self.msgr.get_connection(self.mds_addr).send_message(rel)
        except ConnectionError:
            pass

    # -- namespace -------------------------------------------------------------

    async def mkdir(self, path: str) -> None:
        await self._request("mkdir", {"path": path})

    async def listdir(self, path: str = "/") -> list[str]:
        return (await self._request("readdir", {"path": path}))["entries"]

    async def listdir_plus(self, path: str = "/") -> dict[str, dict]:
        """readdirplus: name -> entry stat record in one round trip
        (Client::readdirplus; saves the per-entry lookup storm)."""
        return (await self._request("readdirplus", {"path": path}))["entries"]

    async def stat(self, path: str) -> dict:
        return (await self._request("lookup", {"path": path}))["entry"]

    async def rename(self, src: str, dst: str) -> None:
        rep = await self._request("rename", {"src": src, "dst": dst})
        replaced = rep.get("replaced")
        if replaced and replaced.get("type") == "file":
            await self._purge(replaced)

    async def symlink(self, target: str, path: str) -> None:
        """ceph_symlink: create `path` pointing at `target`."""
        await self._request("symlink", {"path": path, "target": target})

    async def readlink(self, path: str) -> str:
        return (await self._request("readlink", {"path": path}))["target"]

    async def rmdir(self, path: str) -> None:
        await self._request("rmdir", {"path": path})

    async def unlink(self, path: str) -> None:
        rep = await self._request("unlink", {"path": path})
        await self._purge(rep["entry"])

    async def _purge(self, entry: dict) -> None:
        """Delete a file's data objects (the client-driven purge the
        reference delegates to the MDS PurgeQueue; same pool effect)."""
        lay = entry.get("layout")
        if not lay:
            return
        await StripedObject(
            self.data,
            f"{entry['ino']:x}",
            StripePolicy(
                stripe_unit=lay["stripe_unit"],
                stripe_count=lay["stripe_count"],
                object_size=lay["object_size"],
            ),
        ).remove()

    # -- files -----------------------------------------------------------------

    async def create(self, path: str) -> FileHandle:
        rep = await self._request("create", {"path": path, "caps": "w"})
        fh = FileHandle(self, path, rep["entry"], rep["caps"])
        self._handles.setdefault(rep["entry"]["ino"], []).append(fh)
        return fh

    async def open(self, path: str, mode: str = "r") -> FileHandle:
        rep = await self._request("open", {"path": path, "caps": mode})
        fh = FileHandle(self, path, rep["entry"], rep["caps"])
        self._handles.setdefault(rep["entry"]["ino"], []).append(fh)
        return fh

    # -- convenience (whole-file ops) ------------------------------------------

    async def write_file(self, path: str, data: bytes) -> None:
        try:
            fh = await self.create(path)
        except FsClientError as e:
            if e.errno != -EEXIST:
                raise
            fh = await self.open(path, "w")
        try:
            if len(data) < fh.entry.get("size", 0):
                await fh.truncate(len(data))
            if data:
                await fh.write(data, 0)
        finally:
            await fh.close()

    async def read_file(self, path: str) -> bytes:
        fh = await self.open(path, "r")
        try:
            return await fh.read()
        finally:
            await fh.close()
