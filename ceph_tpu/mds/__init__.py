"""MDS — the CephFS metadata server (mirror of src/mds)."""

from .mds import MDS
from .client import CephFSClient, FsClientError

__all__ = ["MDS", "CephFSClient", "FsClientError"]
