"""Object gateway — S3 semantics over RADOS (src/rgw)."""

from .rgw import RgwError, ObjectGateway
from .http import S3Server

__all__ = ["ObjectGateway", "RgwError", "S3Server"]
