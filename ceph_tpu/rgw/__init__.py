"""Object gateway — S3 + Swift semantics over RADOS (src/rgw)."""

from .rgw import RgwError, ObjectGateway
from .http import S3Server
from .swift import SwiftServer

__all__ = ["ObjectGateway", "RgwError", "S3Server", "SwiftServer"]
