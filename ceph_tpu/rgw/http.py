"""S3 REST front end — mirror of src/rgw's REST layer (rgw_rest_s3).

A minimal HTTP/1.1 responder exposing the S3 surface the gateway core
implements: bucket create/delete/list, object PUT/GET/HEAD/DELETE, and
bucket listing with prefix/delimiter.  Requests authenticate with the
AWS v2-style header `Authorization: AWS <access_key>:<signature>`, the
signature being HMAC-SHA1 over the canonical string — the same scheme
rgw_auth_s3.cc verifies (v4 is out of scope).

Path-style addressing only: /<bucket>/<key>.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
from urllib.parse import parse_qs, unquote, urlparse
from xml.sax.saxutils import escape as _x

from .rgw import ObjectGateway, RgwError


def sign_v2(
    secret_key: str,
    method: str,
    path: str,
    date: str,
    content_md5: str = "",
    content_type: str = "",
    amz_date: str = "",
) -> str:
    """AWS signature v2 string-to-sign, as rgw_auth_s3 canonicalizes it:
    Method, Content-MD5, Content-Type, Date, CanonicalizedAmzHeaders,
    CanonicalizedResource.  Covering Content-MD5 binds the signature to
    the request body.  When the client authenticates with x-amz-date
    instead of Date, v2 uses an empty Date line and the x-amz-date value
    rides in the canonicalized amz headers — so the freshness timestamp
    is still signature-covered either way."""
    amz = f"x-amz-date:{amz_date}\n" if amz_date else ""
    string_to_sign = f"{method}\n{content_md5}\n{content_type}\n{date}\n{amz}{path}"
    mac = hmac.new(secret_key.encode(), string_to_sign.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


# AWS rejects requests whose Date is more than 15 minutes off the server
# clock (rgw's RGW_AUTH_GRACE); limits replay of a captured signature.
DATE_SKEW_S = 15 * 60


class S3Server:
    def __init__(self, gateway: ObjectGateway, require_auth: bool = False):
        self.gw = gateway
        self.require_auth = require_auth
        self._server: asyncio.AbstractServer | None = None
        self.addr = ""

    async def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(self._handle, host, port)
        sock = self._server.sockets[0].getsockname()
        self.addr = f"{sock[0]}:{sock[1]}"
        return self.addr

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- request handling ------------------------------------------------------

    async def _handle(self, reader, writer) -> None:
        try:
            request = await reader.readline()
            if not request:
                return
            method, target, _version = request.decode().split(" ", 2)
            headers: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                headers[name.strip().lower()] = value.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))
            status, resp_headers, resp_body = await self._route(
                method, target, headers, body
            )
            writer.write(f"HTTP/1.1 {status}\r\n".encode())
            resp_headers.setdefault("Content-Length", str(len(resp_body)))
            resp_headers.setdefault("Connection", "close")
            for k, v in resp_headers.items():
                writer.write(f"{k}: {v}\r\n".encode())
            writer.write(b"\r\n")
            writer.write(resp_body)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            writer.close()

    async def _authenticate(
        self, method: str, path: str, headers: dict, body: bytes
    ) -> bool:
        if not self.require_auth:
            return True
        auth = headers.get("authorization", "")
        if not auth.startswith("AWS "):
            return False
        try:
            access_key, signature = auth[4:].split(":", 1)
        except ValueError:
            return False
        date = headers.get("date", "")
        amz_date = headers.get("x-amz-date", "")
        if amz_date:
            # v2: x-amz-date overrides Date; the Date line in the
            # string-to-sign becomes empty and freshness is checked on
            # the amz header instead (rgw accepts either).
            date = ""
            if not self._date_fresh(amz_date):
                return False
        elif not self._date_fresh(date):
            return False
        # The signature covers Content-MD5; when the client sends it, the
        # body must actually hash to it, or an attacker could replay a
        # captured signature with a different body attached.  (v2 treats
        # Content-MD5 as optional — stock clients omit it on PUT — so a
        # body without the header is accepted, as rgw/AWS do; transport
        # security covers that gap.)
        content_md5 = headers.get("content-md5", "")
        if content_md5:
            actual = base64.b64encode(hashlib.md5(body).digest()).decode()
            if not hmac.compare_digest(content_md5, actual):
                return False
        user = await self.gw.user_by_access_key(access_key)
        if user is None:
            return False
        expect = sign_v2(
            user["secret_key"],
            method,
            path,
            date,
            content_md5=content_md5,
            content_type=headers.get("content-type", ""),
            amz_date=amz_date,
        )
        return hmac.compare_digest(signature, expect)

    @staticmethod
    def _date_fresh(date: str) -> bool:
        from email.utils import parsedate_to_datetime

        try:
            sent = parsedate_to_datetime(date)
        except (TypeError, ValueError):
            return False
        import datetime

        if sent.tzinfo is None:
            sent = sent.replace(tzinfo=datetime.timezone.utc)
        now = datetime.datetime.now(datetime.timezone.utc)
        return abs((now - sent).total_seconds()) <= DATE_SKEW_S

    async def _route(self, method: str, target: str, headers: dict, body: bytes):
        url = urlparse(target)
        path = unquote(url.path)
        query = parse_qs(url.query, keep_blank_values=True)
        if not await self._authenticate(method, path, headers, body):
            return "403 Forbidden", {}, _error_xml("AccessDenied")
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        try:
            if not bucket:  # service level: list buckets
                if method == "GET":
                    names = await self.gw.list_buckets()
                    xml = "".join(f"<Bucket><Name>{_x(n)}</Name></Bucket>" for n in names)
                    return (
                        "200 OK",
                        {"Content-Type": "application/xml"},
                        f"<ListAllMyBucketsResult><Buckets>{xml}</Buckets>"
                        f"</ListAllMyBucketsResult>".encode(),
                    )
                return "405 Method Not Allowed", {}, b""
            if not key:
                return await self._bucket_op(method, bucket, query)
            return await self._object_op(method, bucket, key, body)
        except RgwError as e:
            status = {
                "NoSuchBucket": "404 Not Found",
                "NoSuchKey": "404 Not Found",
                "NoSuchUpload": "404 Not Found",
                "NoSuchUser": "404 Not Found",
                "BucketAlreadyExists": "409 Conflict",
                "BucketNotEmpty": "409 Conflict",
                "UserAlreadyExists": "409 Conflict",
            }.get(e.code, "400 Bad Request")
            return status, {"Content-Type": "application/xml"}, _error_xml(e.code)

    async def _bucket_op(self, method: str, bucket: str, query: dict):
        if method == "PUT":
            await self.gw.create_bucket(bucket)
            return "200 OK", {}, b""
        if method == "DELETE":
            await self.gw.delete_bucket(bucket)
            return "204 No Content", {}, b""
        if method == "GET":
            listing = await self.gw.list_objects(
                bucket,
                prefix=query.get("prefix", [""])[0],
                delimiter=query.get("delimiter", [""])[0],
                marker=query.get("marker", [""])[0],
                max_keys=_int_arg(query.get("max-keys", ["1000"])[0]),
            )
            contents = "".join(
                f"<Contents><Key>{_x(c['key'])}</Key><Size>{c['size']}</Size>"
                f"<ETag>&quot;{c['etag']}&quot;</ETag></Contents>"
                for c in listing["contents"]
            )
            prefixes = "".join(
                f"<CommonPrefixes><Prefix>{_x(p)}</Prefix></CommonPrefixes>"
                for p in listing["common_prefixes"]
            )
            trunc = "true" if listing["is_truncated"] else "false"
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<ListBucketResult><Name>{_x(bucket)}</Name>"
                f"<IsTruncated>{trunc}</IsTruncated>"
                f"{contents}{prefixes}</ListBucketResult>".encode(),
            )
        return "405 Method Not Allowed", {}, b""

    async def _object_op(self, method: str, bucket: str, key: str, body: bytes):
        if method == "PUT":
            etag = await self.gw.put_object(bucket, key, body)
            return "200 OK", {"ETag": f'"{etag}"'}, b""
        if method == "GET":
            data = await self.gw.get_object(bucket, key)
            meta = await self.gw.head_object(bucket, key)
            return (
                "200 OK",
                {
                    "ETag": f'"{meta["etag"]}"',
                    "Content-Type": "application/octet-stream",
                },
                data,
            )
        if method == "HEAD":
            meta = await self.gw.head_object(bucket, key)
            return (
                "200 OK",
                {"ETag": f'"{meta["etag"]}"', "Content-Length": str(meta["size"])},
                b"",
            )
        if method == "DELETE":
            await self.gw.delete_object(bucket, key)
            return "204 No Content", {}, b""
        return "405 Method Not Allowed", {}, b""


def _error_xml(code: str) -> bytes:
    return f"<Error><Code>{_x(code)}</Code></Error>".encode()


def _int_arg(value: str) -> int:
    """Query-string int with S3's InvalidArgument error (not a dropped
    connection) on junk."""
    try:
        return int(value)
    except ValueError:
        from ..common.errs import EINVAL

        raise RgwError(EINVAL, "InvalidArgument", f"bad integer {value!r}")
