"""S3 REST front end — mirror of src/rgw's REST layer (rgw_rest_s3).

A minimal HTTP/1.1 responder exposing the S3 surface the gateway core
implements: bucket create/delete/list, object PUT/GET/HEAD/DELETE, and
bucket listing with prefix/delimiter.  Requests authenticate with the
AWS v2-style header `Authorization: AWS <access_key>:<signature>`, the
signature being HMAC-SHA1 over the canonical string — the same scheme
rgw_auth_s3.cc verifies (v4 is out of scope).

Path-style addressing only: /<bucket>/<key>.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
from urllib.parse import parse_qs, unquote, urlparse
from xml.sax.saxutils import escape as _x

from ..common.log import dout
from .rgw import ObjectGateway, RgwError


def sign_v2(
    secret_key: str,
    method: str,
    path: str,
    date: str,
    content_md5: str = "",
    content_type: str = "",
    amz_date: str = "",
) -> str:
    """AWS signature v2 string-to-sign, as rgw_auth_s3 canonicalizes it:
    Method, Content-MD5, Content-Type, Date, CanonicalizedAmzHeaders,
    CanonicalizedResource.  Covering Content-MD5 binds the signature to
    the request body.  When the client authenticates with x-amz-date
    instead of Date, v2 uses an empty Date line and the x-amz-date value
    rides in the canonicalized amz headers — so the freshness timestamp
    is still signature-covered either way."""
    amz = f"x-amz-date:{amz_date}\n" if amz_date else ""
    string_to_sign = f"{method}\n{content_md5}\n{content_type}\n{date}\n{amz}{path}"
    mac = hmac.new(secret_key.encode(), string_to_sign.encode(), hashlib.sha1)
    return base64.b64encode(mac.digest()).decode()


# AWS rejects requests whose Date is more than 15 minutes off the server
# clock (rgw's RGW_AUTH_GRACE); limits replay of a captured signature.
DATE_SKEW_S = 15 * 60


class S3Server:
    def __init__(
        self, gateway: ObjectGateway, require_auth: bool = False,
        lc_interval: float = 0.0,
    ):
        self.gw = gateway
        self.require_auth = require_auth
        self.lc_interval = lc_interval  # seconds; 0 disables the LC worker
        self._server: asyncio.AbstractServer | None = None
        self._lc_task: asyncio.Task | None = None
        self.addr = ""
        self.lc_errors = 0  # failed lifecycle passes (visible, not silent)

    async def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(self._handle, host, port)
        sock = self._server.sockets[0].getsockname()
        self.addr = f"{sock[0]}:{sock[1]}"
        if self.lc_interval > 0:
            self._lc_task = asyncio.create_task(self._lc_loop())
        return self.addr

    async def _lc_loop(self) -> None:
        """Background lifecycle worker (the RGWLC thread; interval is
        rgw_lc_debug_interval's role in the reference's QA runs)."""
        while True:
            await asyncio.sleep(self.lc_interval)
            try:
                await self.gw.process_lifecycle()
            except Exception as e:
                # a pool hiccup must not kill the worker — but a
                # lifecycle pass that silently fails every tick would
                # never expire anything and never say so
                self.lc_errors += 1
                dout("rgw", 1, f"lifecycle pass failed: {e!r}")

    async def shutdown(self) -> None:
        if self._lc_task is not None:
            self._lc_task.cancel()
            self._lc_task = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- request handling ------------------------------------------------------

    async def _handle(self, reader, writer) -> None:
        try:
            request = await reader.readline()
            if not request:
                return
            method, target, _version = request.decode().split(" ", 2)
            headers: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                headers[name.strip().lower()] = value.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))
            status, resp_headers, resp_body = await self._route(
                method, target, headers, body
            )
            writer.write(f"HTTP/1.1 {status}\r\n".encode())
            resp_headers.setdefault("Content-Length", str(len(resp_body)))
            resp_headers.setdefault("Connection", "close")
            for k, v in resp_headers.items():
                writer.write(f"{k}: {v}\r\n".encode())
            writer.write(b"\r\n")
            writer.write(resp_body)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            writer.close()

    # sentinel: request carried bad credentials (vs None = anonymous)
    _BAD_AUTH = object()

    async def _authenticate(
        self, method: str, path: str, headers: dict, body: bytes
    ):
        """Returns the authenticated uid, None for anonymous, or
        _BAD_AUTH when credentials were presented and failed
        (rgw_auth_s3.cc authorize; SignatureDoesNotMatch)."""
        auth = headers.get("authorization", "")
        if not auth:
            return self._BAD_AUTH if self.require_auth else None
        if not auth.startswith("AWS "):
            return self._BAD_AUTH
        try:
            access_key, signature = auth[4:].split(":", 1)
        except ValueError:
            return self._BAD_AUTH
        date = headers.get("date", "")
        amz_date = headers.get("x-amz-date", "")
        if amz_date:
            # v2: x-amz-date overrides Date; the Date line in the
            # string-to-sign becomes empty and freshness is checked on
            # the amz header instead (rgw accepts either).
            date = ""
            if not self._date_fresh(amz_date):
                return self._BAD_AUTH
        elif not self._date_fresh(date):
            return self._BAD_AUTH
        # The signature covers Content-MD5; when the client sends it, the
        # body must actually hash to it, or an attacker could replay a
        # captured signature with a different body attached.  (v2 treats
        # Content-MD5 as optional — stock clients omit it on PUT — so a
        # body without the header is accepted, as rgw/AWS do; transport
        # security covers that gap.)
        content_md5 = headers.get("content-md5", "")
        if content_md5:
            actual = base64.b64encode(hashlib.md5(body).digest()).decode()
            if not hmac.compare_digest(content_md5, actual):
                return self._BAD_AUTH
        user = await self.gw.user_by_access_key(access_key)
        if user is None:
            return self._BAD_AUTH
        expect = sign_v2(
            user["secret_key"],
            method,
            path,
            date,
            content_md5=content_md5,
            content_type=headers.get("content-type", ""),
            amz_date=amz_date,
        )
        if not hmac.compare_digest(signature, expect):
            return self._BAD_AUTH
        return user["uid"]

    @staticmethod
    def _date_fresh(date: str) -> bool:
        from email.utils import parsedate_to_datetime

        try:
            sent = parsedate_to_datetime(date)
        except (TypeError, ValueError):
            return False
        import datetime

        if sent.tzinfo is None:
            sent = sent.replace(tzinfo=datetime.timezone.utc)
        now = datetime.datetime.now(datetime.timezone.utc)
        return abs((now - sent).total_seconds()) <= DATE_SKEW_S

    async def _route(self, method: str, target: str, headers: dict, body: bytes):
        url = urlparse(target)
        path = unquote(url.path)
        query = parse_qs(url.query, keep_blank_values=True)
        actor = await self._authenticate(method, path, headers, body)
        if actor is self._BAD_AUTH:
            return "403 Forbidden", {}, _error_xml("AccessDenied")
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        try:
            if not bucket:  # service level: list the caller's buckets
                if method == "GET":
                    names = await self.gw.list_buckets(
                        owner=actor if actor else None
                    )
                    xml = "".join(f"<Bucket><Name>{_x(n)}</Name></Bucket>" for n in names)
                    return (
                        "200 OK",
                        {"Content-Type": "application/xml"},
                        f"<ListAllMyBucketsResult><Buckets>{xml}</Buckets>"
                        f"</ListAllMyBucketsResult>".encode(),
                    )
                return "405 Method Not Allowed", {}, b""
            if not key:
                return await self._bucket_op(method, bucket, query, headers, body, actor)
            return await self._object_op(method, bucket, key, body, query, headers, actor)
        except RgwError as e:
            status = {
                "NoSuchBucket": "404 Not Found",
                "NoSuchKey": "404 Not Found",
                "NoSuchVersion": "404 Not Found",
                "NoSuchUpload": "404 Not Found",
                "NoSuchUser": "404 Not Found",
                "NoSuchLifecycleConfiguration": "404 Not Found",
                "AccessDenied": "403 Forbidden",
                "MethodNotAllowed": "405 Method Not Allowed",
                "BucketAlreadyExists": "409 Conflict",
                "BucketNotEmpty": "409 Conflict",
                "UserAlreadyExists": "409 Conflict",
            }.get(e.code, "400 Bad Request")
            return status, {"Content-Type": "application/xml"}, _error_xml(e.code)

    @staticmethod
    def _canned_grants(headers: dict) -> dict:
        """x-amz-acl canned ACL -> grant map (rgw_acl_s3.cc canned
        policies; private is the empty grant set — owner only).  READ and
        WRITE are independent permissions, so public-read-write grants
        both explicitly."""
        canned = headers.get("x-amz-acl", "private")
        if canned == "public-read":
            return {"*": "READ"}
        if canned == "public-read-write":
            return {"*": ["READ", "WRITE"]}
        return {}

    async def _bucket_op(
        self, method: str, bucket: str, query: dict, headers: dict,
        body: bytes, actor,
    ):
        if "acl" in query:
            return await self._acl_op(method, bucket, headers, actor)
        if "versioning" in query:
            return await self._versioning_op(method, bucket, body, actor)
        if "lifecycle" in query:
            return await self._lifecycle_op(method, bucket, body, actor)
        if "uploads" in query and method == "GET":
            ups = await self.gw.list_multipart_uploads(bucket, actor=actor)
            rows = "".join(
                f"<Upload><Key>{_x(u['key'])}</Key>"
                f"<UploadId>{_x(u['upload_id'])}</UploadId></Upload>"
                for u in ups
            )
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<ListMultipartUploadsResult>{rows}"
                f"</ListMultipartUploadsResult>".encode(),
            )
        if "versions" in query and method == "GET":
            versions = await self.gw.list_object_versions(
                bucket, prefix=query.get("prefix", [""])[0], actor=actor
            )
            rows = "".join(
                (
                    f"<DeleteMarker><Key>{_x(v['key'])}</Key>"
                    f"<VersionId>{_x(v.get('version_id', 'null'))}</VersionId>"
                    f"<IsLatest>{str(v['is_latest']).lower()}</IsLatest>"
                    f"</DeleteMarker>"
                    if v.get("delete_marker")
                    else f"<Version><Key>{_x(v['key'])}</Key>"
                    f"<VersionId>{_x(v.get('version_id', 'null'))}</VersionId>"
                    f"<IsLatest>{str(v['is_latest']).lower()}</IsLatest>"
                    f"<Size>{v.get('size', 0)}</Size>"
                    f"<ETag>&quot;{v.get('etag', '')}&quot;</ETag></Version>"
                )
                for v in versions
            )
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<ListVersionsResult><Name>{_x(bucket)}</Name>{rows}"
                f"</ListVersionsResult>".encode(),
            )
        if method == "PUT":
            await self.gw.create_bucket(
                bucket, owner=actor or "", grants=self._canned_grants(headers)
            )
            return "200 OK", {}, b""
        if method == "DELETE":
            await self.gw._require_access(bucket, actor, "FULL_CONTROL")
            await self.gw.delete_bucket(bucket)
            return "204 No Content", {}, b""
        if method == "GET":
            listing = await self.gw.list_objects(
                bucket,
                prefix=query.get("prefix", [""])[0],
                delimiter=query.get("delimiter", [""])[0],
                marker=query.get("marker", [""])[0],
                max_keys=_int_arg(query.get("max-keys", ["1000"])[0]),
                actor=actor,
            )
            contents = "".join(
                f"<Contents><Key>{_x(c['key'])}</Key><Size>{c['size']}</Size>"
                f"<ETag>&quot;{c['etag']}&quot;</ETag></Contents>"
                for c in listing["contents"]
            )
            prefixes = "".join(
                f"<CommonPrefixes><Prefix>{_x(p)}</Prefix></CommonPrefixes>"
                for p in listing["common_prefixes"]
            )
            trunc = "true" if listing["is_truncated"] else "false"
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<ListBucketResult><Name>{_x(bucket)}</Name>"
                f"<IsTruncated>{trunc}</IsTruncated>"
                f"{contents}{prefixes}</ListBucketResult>".encode(),
            )
        return "405 Method Not Allowed", {}, b""

    async def _acl_op(self, method: str, bucket: str, headers: dict, actor):
        """?acl subresource: GET dumps the policy, PUT applies a canned
        ACL (x-amz-acl), both owner-gated (RGWGetACLs / RGWPutACLs)."""
        if method == "GET":
            acl = await self.gw.get_bucket_acl(bucket, actor=actor)
            grants = "".join(
                f"<Grant><Grantee>{_x(g)}</Grantee>"
                f"<Permission>{_x(p if isinstance(p, str) else '+'.join(sorted(p)))}"
                f"</Permission></Grant>"
                for g, p in sorted(acl["grants"].items())
            )
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<AccessControlPolicy><Owner><ID>{_x(acl['owner'])}</ID>"
                f"</Owner><AccessControlList>{grants}</AccessControlList>"
                f"</AccessControlPolicy>".encode(),
            )
        if method == "PUT":
            await self.gw.set_bucket_acl(
                bucket, self._canned_grants(headers), actor=actor
            )
            return "200 OK", {}, b""
        return "405 Method Not Allowed", {}, b""

    async def _lifecycle_op(self, method: str, bucket: str, body: bytes, actor):
        """?lifecycle subresource (RGWPutLC/RGWGetLC): expiration rules
        as <Rule><ID/><Prefix/><Expiration><Days/></Expiration></Rule>."""
        import re

        if method == "GET":
            rules = await self.gw.get_lifecycle(bucket, actor=actor)
            xml = "".join(
                f"<Rule><ID>{_x(r['id'])}</ID><Prefix>{_x(r['prefix'])}</Prefix>"
                f"<Status>Enabled</Status><Expiration><Days>{r['days']}</Days>"
                f"</Expiration></Rule>"
                for r in rules
            )
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<LifecycleConfiguration>{xml}</LifecycleConfiguration>".encode(),
            )
        if method == "PUT":
            rules = []
            for rule in re.findall(rb"<Rule>(.*?)</Rule>", body, re.S):
                def field(tag, blob=rule):
                    m = re.search(
                        rb"<" + tag + rb">\s*(.*?)\s*</" + tag + rb">", blob, re.S
                    )
                    return m.group(1).decode() if m else ""

                days = field(rb"Days")
                if not days:
                    continue
                rules.append(
                    {"id": field(rb"ID"), "prefix": field(rb"Prefix"),
                     "days": days}
                )
            await self.gw.set_lifecycle(bucket, rules, actor=actor)
            return "200 OK", {}, b""
        if method == "DELETE":
            await self.gw.set_lifecycle(bucket, [], actor=actor)
            return "204 No Content", {}, b""
        return "405 Method Not Allowed", {}, b""

    async def _versioning_op(self, method: str, bucket: str, body: bytes, actor):
        if method == "GET":
            status = await self.gw.get_versioning(bucket, actor=actor)
            inner = f"<Status>{_x(status)}</Status>" if status else ""
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<VersioningConfiguration>{inner}"
                f"</VersioningConfiguration>".encode(),
            )
        if method == "PUT":
            import re

            m = re.search(rb"<Status>\s*(\w+)\s*</Status>", body)
            status = m.group(1).decode() if m else ""
            await self.gw.set_versioning(bucket, status, actor=actor)
            return "200 OK", {}, b""
        return "405 Method Not Allowed", {}, b""

    async def _object_op(
        self, method: str, bucket: str, key: str, body: bytes, query: dict,
        headers: dict, actor,
    ):
        if "acl" in query:
            # object ?acl subresource (RGWGetACLs/RGWPutACLs on objects)
            if method == "GET":
                acl = await self.gw.get_object_acl(bucket, key, actor=actor)
                grants = "".join(
                    f"<Grant><Grantee>{_x(g)}</Grantee>"
                    f"<Permission>"
                    f"{_x(p if isinstance(p, str) else '+'.join(sorted(p)))}"
                    f"</Permission></Grant>"
                    for g, p in sorted(acl["grants"].items())
                )
                return (
                    "200 OK",
                    {"Content-Type": "application/xml"},
                    f"<AccessControlPolicy><Owner><ID>{_x(acl['owner'])}</ID>"
                    f"</Owner><AccessControlList>{grants}</AccessControlList>"
                    f"</AccessControlPolicy>".encode(),
                )
            if method == "PUT":
                await self.gw.set_object_acl(
                    bucket, key, self._canned_grants(headers), actor=actor
                )
                return "200 OK", {}, b""
            return "405 Method Not Allowed", {}, b""
        version_id = query.get("versionId", [""])[0]
        upload_id = query.get("uploadId", [""])[0]
        if "uploads" in query and method == "POST":
            # InitiateMultipartUpload (RGWInitMultipart)
            uid = await self.gw.initiate_multipart(bucket, key, actor=actor)
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<InitiateMultipartUploadResult><Bucket>{_x(bucket)}</Bucket>"
                f"<Key>{_x(key)}</Key><UploadId>{_x(uid)}</UploadId>"
                f"</InitiateMultipartUploadResult>".encode(),
            )
        if upload_id and method == "PUT":
            # UploadPart
            pn = _int_arg(query.get("partNumber", ["0"])[0])
            etag = await self.gw.upload_part(upload_id, pn, body)
            return "200 OK", {"ETag": f'"{etag}"'}, b""
        if upload_id and method == "GET":
            parts = await self.gw.list_parts(upload_id)
            rows = "".join(
                f"<Part><PartNumber>{p['part_number']}</PartNumber>"
                f"<Size>{p['size']}</Size>"
                f"<ETag>&quot;{p['etag']}&quot;</ETag></Part>"
                for p in parts
            )
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<ListPartsResult>{rows}</ListPartsResult>".encode(),
            )
        if upload_id and method == "POST":
            # CompleteMultipartUpload
            etag = await self.gw.complete_multipart(upload_id, actor=actor)
            return (
                "200 OK",
                {"Content-Type": "application/xml"},
                f"<CompleteMultipartUploadResult><ETag>&quot;{etag}&quot;"
                f"</ETag></CompleteMultipartUploadResult>".encode(),
            )
        if upload_id and method == "DELETE":
            await self.gw.abort_multipart(upload_id)
            return "204 No Content", {}, b""
        if method == "PUT":
            meta = {
                name[len("x-amz-meta-"):]: value
                for name, value in headers.items()
                if name.startswith("x-amz-meta-")
            }
            ct = headers.get("content-type", "")
            if ct:
                meta["content-type"] = ct
            etag, vid = await self.gw.put_object(
                bucket, key, body, meta=meta or None, actor=actor
            )
            hdrs = {"ETag": f'"{etag}"'}
            if vid:
                hdrs["x-amz-version-id"] = vid
            return "200 OK", hdrs, b""
        if method == "GET":
            data = await self.gw.get_object(
                bucket, key, actor=actor, version_id=version_id
            )
            meta = await self.gw.head_object(
                bucket, key, actor=actor, version_id=version_id
            )
            user_meta = meta.get("meta", {})
            hdrs = {
                "ETag": f'"{meta["etag"]}"',
                "Content-Type": user_meta.get(
                    "content-type", "application/octet-stream"
                ),
            }
            for mk, mv in user_meta.items():
                if mk != "content-type":
                    hdrs[f"x-amz-meta-{mk}"] = mv
            if meta.get("version_id"):
                hdrs["x-amz-version-id"] = meta["version_id"]
            return "200 OK", hdrs, data
        if method == "HEAD":
            meta = await self.gw.head_object(
                bucket, key, actor=actor, version_id=version_id
            )
            return (
                "200 OK",
                {"ETag": f'"{meta["etag"]}"', "Content-Length": str(meta["size"])},
                b"",
            )
        if method == "DELETE":
            vid = await self.gw.delete_object(
                bucket, key, actor=actor, version_id=version_id
            )
            hdrs = {}
            if vid:
                hdrs["x-amz-version-id"] = vid
                if not version_id:
                    hdrs["x-amz-delete-marker"] = "true"
            return "204 No Content", hdrs, b""
        return "405 Method Not Allowed", {}, b""


def _error_xml(code: str) -> bytes:
    return f"<Error><Code>{_x(code)}</Code></Error>".encode()


def _int_arg(value: str) -> int:
    """Query-string int with S3's InvalidArgument error (not a dropped
    connection) on junk."""
    try:
        return int(value)
    except ValueError:
        from ..common.errs import EINVAL

        raise RgwError(EINVAL, "InvalidArgument", f"bad integer {value!r}")
