"""Swift-compatible API front end — mirror of src/rgw/rgw_swift_auth.cc +
the RGWHandler_REST_*_SWIFT family.

The reference's radosgw speaks both S3 and Swift over the same RGWRados
core; this module is the Swift personality over the same ObjectGateway
the S3 server uses (buckets ARE containers — rgw's own model):

- **TempAuth** (`rgw_swift_auth.cc` swift auth v1): `GET /auth/v1.0` with
  `X-Auth-User: <uid>:swift` + `X-Auth-Key: <secret>` returns an
  `X-Auth-Token` and the account's `X-Storage-Url`; requests present the
  token.  Tokens are HMAC-signed, expiring blobs (not a server-side
  session table), like rgw's swift token encoding.
- **Account**: `GET /v1/AUTH_<acct>` lists containers (plain or
  `?format=json`).
- **Container**: PUT creates, DELETE removes (409 when non-empty), GET
  lists objects with `prefix`/`marker`/`limit`, plain or JSON.
- **Object**: PUT stores (`X-Object-Meta-*` headers persist as user
  metadata), GET returns bytes + ETag + meta, HEAD the same without the
  body, DELETE removes.  ETags are MD5 hex like Swift's.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import time
from urllib.parse import parse_qs, unquote, urlparse

from .rgw import ObjectGateway, RgwError

TOKEN_TTL = 3600.0


class SwiftServer:
    def __init__(self, gateway: ObjectGateway, require_auth: bool = True):
        self.gw = gateway
        self.require_auth = require_auth
        self._server: asyncio.AbstractServer | None = None
        self.addr = ""
        import secrets

        self._token_secret = secrets.token_bytes(16)

    async def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(self._handle, host, port)
        sock = self._server.sockets[0].getsockname()
        self.addr = f"{sock[0]}:{sock[1]}"
        return self.addr

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- tokens (TempAuth) -----------------------------------------------------

    def _mint_token(self, uid: str) -> str:
        expires = time.time() + TOKEN_TTL
        body = f"{uid}:{expires}"
        sig = hmac.new(
            self._token_secret, body.encode(), hashlib.sha256
        ).hexdigest()
        return f"AUTH_tk_{body}:{sig}"

    def _verify_token(self, token: str) -> str | None:
        if not token.startswith("AUTH_tk_"):
            return None
        try:
            uid, expires, sig = token[len("AUTH_tk_"):].rsplit(":", 2)
            body = f"{uid}:{expires}"
            expect = hmac.new(
                self._token_secret, body.encode(), hashlib.sha256
            ).hexdigest()
            if not hmac.compare_digest(sig, expect):
                return None
            if float(expires) < time.time():
                return None
            return uid
        except ValueError:
            return None

    # -- http plumbing (shares the S3 server's minimal HTTP shape) -------------

    async def _handle(self, reader, writer) -> None:
        try:
            request = await reader.readline()
            if not request:
                return
            method, target, _version = request.decode().split(" ", 2)
            headers: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                headers[name.strip().lower()] = value.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))
            status, resp_headers, resp_body = await self._route(
                method, target, headers, body
            )
            writer.write(f"HTTP/1.1 {status}\r\n".encode())
            resp_headers.setdefault("Content-Length", str(len(resp_body)))
            resp_headers.setdefault("Connection", "close")
            for k, v in resp_headers.items():
                writer.write(f"{k}: {v}\r\n".encode())
            writer.write(b"\r\n")
            writer.write(resp_body)
            await writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            writer.close()

    # -- routing ---------------------------------------------------------------

    async def _route(self, method: str, target: str, headers: dict, body: bytes):
        url = urlparse(target)
        path = unquote(url.path)
        query = parse_qs(url.query, keep_blank_values=True)

        if path == "/auth/v1.0":
            return await self._auth(method, headers)

        if not path.startswith("/v1/AUTH_"):
            return "404 Not Found", {}, b"not a swift path"
        account_path = path[len("/v1/AUTH_"):]
        parts = account_path.split("/", 2)
        account = parts[0]
        container = parts[1] if len(parts) > 1 else ""
        obj = parts[2] if len(parts) > 2 else ""

        uid = None
        if self.require_auth:
            uid = self._verify_token(headers.get("x-auth-token", ""))
            anonymous_read = (
                uid is None and container and method in ("GET", "HEAD")
            )
            if uid is None and not anonymous_read:
                # anonymous traffic may only attempt reads — which a
                # container's .r:* (AllUsers) READ grant can then allow;
                # everything else needs a token (rgw_swift anon handling)
                return "401 Unauthorized", {}, b""
            # account-level ops and container CREATION belong to the
            # account's owner; other container/object access across
            # accounts is decided by container ACLs (rgw_swift's
            # read/write ACL model)
            if not container and uid != account:
                return "403 Forbidden", {}, b""
            if container and not obj and method == "PUT" and uid != account:
                return "403 Forbidden", {}, b""

        try:
            if not container:
                return await self._account_op(method, account, query, uid)
            if not obj:
                return await self._container_op(method, container, query,
                                                headers, uid)
            return await self._object_op(
                method, container, obj, headers, body, uid
            )
        except RgwError as e:
            status = {
                "NoSuchBucket": "404 Not Found",
                "NoSuchKey": "404 Not Found",
                "AccessDenied": "403 Forbidden",
                "BucketNotEmpty": "409 Conflict",
            }.get(e.code, "400 Bad Request")
            return status, {}, b""

    @staticmethod
    def _acl_grantees(value: str, perm: str) -> list[str]:
        """X-Container-Read/Write -> grantee list: ".r:*" is world READ,
        otherwise a comma list of account uids (rgw_swift ACL parsing).
        Referrer tokens are READ-only — the reference rejects them in
        write ACLs, where a world-WRITE would be catastrophic."""
        out = []
        for tok in (t.strip() for t in value.split(",")):
            if not tok:
                continue
            if tok in (".r:*", ".referrer:*"):
                if perm != "READ":
                    from ..common.errs import EINVAL

                    raise RgwError(
                        EINVAL, "InvalidArgument",
                        "referrer tokens are read-only",
                    )
                out.append("*")
            else:
                out.append(tok)
        return out

    def _merge_acl_headers(self, grants: dict, headers: dict) -> dict:
        """Apply X-Container-Read/Write headers onto a grant map keeping
        READ and WRITE lists INDEPENDENT per grantee (swift's two ACL
        lists): setting one list never disturbs the other."""
        merged: dict[str, set] = {
            g: set(p if isinstance(p, (list, set)) else [p])
            for g, p in grants.items()
        }
        for hdr, perm in (
            ("x-container-read", "READ"), ("x-container-write", "WRITE")
        ):
            if hdr not in headers:
                continue
            for perms in merged.values():
                perms.discard(perm)
            for grantee in self._acl_grantees(headers[hdr], perm):
                merged.setdefault(grantee, set()).add(perm)
        return {g: sorted(p) for g, p in merged.items() if p}

    async def _auth(self, method: str, headers: dict):
        if method != "GET":
            return "405 Method Not Allowed", {}, b""
        user_hdr = headers.get("x-auth-user", "")
        key = headers.get("x-auth-key", "")
        uid = user_hdr.split(":", 1)[0]
        try:
            user = await self.gw.get_user(uid)
        except RgwError:
            return "401 Unauthorized", {}, b""
        # TempAuth checks the swift key; the gateway's secret_key plays it
        if not hmac.compare_digest(key, user["secret_key"]):
            return "401 Unauthorized", {}, b""
        token = self._mint_token(uid)
        return (
            "200 OK",
            {
                "X-Auth-Token": token,
                "X-Storage-Token": token,
                "X-Storage-Url": f"http://{self.addr}/v1/AUTH_{uid}",
            },
            b"",
        )

    async def _account_op(self, method: str, account: str, query: dict, uid):
        if method not in ("GET", "HEAD"):
            return "405 Method Not Allowed", {}, b""
        names = await self.gw.list_buckets(owner=uid if uid else None)
        if method == "HEAD":
            return "204 No Content", {"X-Account-Container-Count": str(len(names))}, b""
        if query.get("format", [""])[0] == "json":
            return (
                "200 OK",
                {"Content-Type": "application/json"},
                json.dumps([{"name": n} for n in names]).encode(),
            )
        return (
            "200 OK",
            {"Content-Type": "text/plain"},
            ("\n".join(names) + "\n" if names else "").encode(),
        )

    async def _container_op(
        self, method: str, container: str, query: dict, headers: dict, uid
    ):
        if method == "PUT":
            try:
                await self.gw.create_bucket(
                    container, owner=uid or "",
                    grants=self._merge_acl_headers({}, headers),
                )
                return "201 Created", {}, b""
            except RgwError as e:
                if e.code != "BucketAlreadyExists":
                    raise
            # existing container: swift's PUT is a metadata update — ACL
            # headers apply, gated on FULL_CONTROL like any ACL change
            # (a non-owner gets 403, not a silent 202)
            acl = await self.gw.get_bucket_acl(container, actor=uid)
            await self.gw.set_bucket_acl(
                container, self._merge_acl_headers(acl["grants"], headers),
                actor=uid,
            )
            return "202 Accepted", {}, b""
        if method == "POST":
            # update container ACLs (swift POST metadata semantics)
            acl = await self.gw.get_bucket_acl(container, actor=uid)
            await self.gw.set_bucket_acl(
                container, self._merge_acl_headers(acl["grants"], headers),
                actor=uid,
            )
            return "204 No Content", {}, b""
        if method == "DELETE":
            await self.gw._require_access(container, uid, "FULL_CONTROL")
            await self.gw.delete_bucket(container)
            return "204 No Content", {}, b""
        if method in ("GET", "HEAD"):
            listing = await self.gw.list_objects(
                container,
                prefix=query.get("prefix", [""])[0],
                marker=query.get("marker", [""])[0],
                max_keys=int(query.get("limit", ["10000"])[0]),
                actor=uid,
            )
            if method == "HEAD":
                return (
                    "204 No Content",
                    {"X-Container-Object-Count": str(len(listing["contents"]))},
                    b"",
                )
            if query.get("format", [""])[0] == "json":
                return (
                    "200 OK",
                    {"Content-Type": "application/json"},
                    json.dumps(
                        [
                            {
                                "name": c["key"],
                                "bytes": c["size"],
                                "hash": c["etag"],
                            }
                            for c in listing["contents"]
                        ]
                    ).encode(),
                )
            names = [c["key"] for c in listing["contents"]]
            return (
                "200 OK",
                {"Content-Type": "text/plain"},
                ("\n".join(names) + "\n" if names else "").encode(),
            )
        return "405 Method Not Allowed", {}, b""

    async def _object_op(
        self, method: str, container: str, obj: str, headers: dict,
        body: bytes, uid,
    ):
        if method == "PUT":
            meta = {
                name[len("x-object-meta-"):]: value
                for name, value in headers.items()
                if name.startswith("x-object-meta-")
            }
            etag, _vid = await self.gw.put_object(
                container, obj, body, meta=meta, actor=uid
            )
            return "201 Created", {"ETag": etag}, b""
        if method in ("GET", "HEAD"):
            info = await self.gw.head_object(container, obj, actor=uid)
            resp_headers = {
                "ETag": info["etag"],
                "Content-Type": "application/octet-stream",
                "X-Timestamp": str(info.get("mtime", 0)),
            }
            for mk, mv in info.get("meta", {}).items():
                resp_headers[f"X-Object-Meta-{mk}"] = mv
            if method == "HEAD":
                resp_headers["Content-Length"] = str(info["size"])
                return "200 OK", resp_headers, b""
            data = await self.gw.get_object(container, obj, actor=uid)
            return "200 OK", resp_headers, data
        if method == "DELETE":
            await self.gw.head_object(container, obj, actor=uid)  # 404 if absent
            await self.gw.delete_object(container, obj, actor=uid)
            return "204 No Content", {}, b""
        return "405 Method Not Allowed", {}, b""
