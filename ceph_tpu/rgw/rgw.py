"""Object gateway core — mirror of src/rgw's storage layer (rgw_rados /
the SAL RadosStore).

The reference (236k LoC; SURVEY.md §2.7) layers S3/Swift semantics over
RADOS: buckets with an index, objects whose head holds metadata and
whose data stripes over tail objects, multipart uploads assembled from
parts, users with access keys.  The same shapes here:

- **Users** live in a registry object (`user.<id>` in the reference's
  user pool; one JSON registry object here) carrying access/secret keys
  (RGWUserInfo).
- **Buckets**: a bucket record plus a **bucket index** object listing
  keys → {size, etag, mtime} (the reference's bucket index omap,
  cls_rgw); listing with prefix/marker/delimiter walks it exactly like
  RGWRados::Bucket::List with CommonPrefixes.
- **Objects**: data stripes over RADOS via the striper (the reference's
  head+tail manifest, rgw_obj_manifest); etag = md5 of the body as S3
  requires (RGWPutObj_ObjProcessor).
- **Multipart**: parts upload as their own striped objects; complete
  concatenates them into the final object and drops the parts
  (RGWCompleteMultipart).
"""

from __future__ import annotations

import hashlib
import json
import secrets
import time

from ..common.errs import EEXIST, EINVAL, ENOENT
from ..striper import StripedObject, StripePolicy

USERS_OID = "rgw.users"
BUCKETS_OID = "rgw.buckets"


class RgwError(Exception):
    def __init__(self, err: int, code: str, msg: str = ""):
        self.errno = -abs(err)
        self.code = code  # S3 error code (NoSuchBucket, ...)
        super().__init__(f"{code}: {msg}")


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


class ObjectGateway:
    """The gateway's storage operations (rgw::sal::RadosStore analog);
    one instance per pool-backed zone."""

    def __init__(self, ioctx, policy: StripePolicy | None = None):
        self.ioctx = ioctx
        self.policy = policy or StripePolicy(
            stripe_unit=512 * 1024, stripe_count=1, object_size=4 * 1024 * 1024
        )

    # -- registries ------------------------------------------------------------

    async def _load(self, oid: str) -> dict:
        try:
            raw = await self.ioctx.read(oid)
            return json.loads(raw.decode() or "{}")
        except Exception:
            return {}

    async def _store(self, oid: str, data: dict) -> None:
        await self.ioctx.write_full(oid, json.dumps(data).encode())

    # -- users (RGWUserInfo) ---------------------------------------------------

    async def create_user(self, uid: str, display_name: str = "") -> dict:
        users = await self._load(USERS_OID)
        if uid in users:
            raise RgwError(EEXIST, "UserAlreadyExists", uid)
        user = {
            "uid": uid,
            "display_name": display_name or uid,
            "access_key": secrets.token_hex(10).upper(),
            "secret_key": secrets.token_hex(20),
        }
        users[uid] = user
        await self._store(USERS_OID, users)
        return user

    async def get_user(self, uid: str) -> dict:
        users = await self._load(USERS_OID)
        if uid not in users:
            raise RgwError(ENOENT, "NoSuchUser", uid)
        return users[uid]

    async def user_by_access_key(self, access_key: str) -> dict | None:
        users = await self._load(USERS_OID)
        for user in users.values():
            if user["access_key"] == access_key:
                return user
        return None

    # -- buckets ---------------------------------------------------------------

    def _index_oid(self, bucket: str) -> str:
        return f"rgw.bucket.index.{bucket}"

    async def create_bucket(self, bucket: str, owner: str = "") -> None:
        buckets = await self._load(BUCKETS_OID)
        if bucket in buckets:
            raise RgwError(EEXIST, "BucketAlreadyExists", bucket)
        buckets[bucket] = {"owner": owner, "created": time.time()}
        await self._store(BUCKETS_OID, buckets)
        await self._store(self._index_oid(bucket), {})

    async def list_buckets(self, owner: str | None = None) -> list[str]:
        buckets = await self._load(BUCKETS_OID)
        return sorted(
            b for b, info in buckets.items()
            if owner is None or info["owner"] == owner
        )

    async def delete_bucket(self, bucket: str) -> None:
        buckets = await self._load(BUCKETS_OID)
        if bucket not in buckets:
            raise RgwError(ENOENT, "NoSuchBucket", bucket)
        index = await self._load(self._index_oid(bucket))
        if index:
            raise RgwError(EINVAL, "BucketNotEmpty", bucket)
        del buckets[bucket]
        await self._store(BUCKETS_OID, buckets)
        try:
            await self.ioctx.remove(self._index_oid(bucket))
        except Exception:
            pass

    async def _require_bucket(self, bucket: str) -> None:
        buckets = await self._load(BUCKETS_OID)
        if bucket not in buckets:
            raise RgwError(ENOENT, "NoSuchBucket", bucket)

    # -- objects ---------------------------------------------------------------

    def _data(self, bucket: str, key: str) -> StripedObject:
        return StripedObject(
            self.ioctx, f"rgw.obj.{bucket}/{key}", policy=self.policy
        )

    async def put_object(
        self, bucket: str, key: str, data: bytes, meta: dict | None = None
    ) -> str:
        """PutObject; returns the etag (RGWPutObj).  `meta` carries user
        metadata (x-amz-meta-* / X-Object-Meta-*, RGWObjManifest attrs)."""
        await self._require_bucket(bucket)
        obj = self._data(bucket, key)
        await obj.remove()  # overwrite semantics
        await obj.write(data)
        etag = _etag(data)
        index = await self._load(self._index_oid(bucket))
        entry = {"size": len(data), "etag": etag, "mtime": time.time()}
        if meta:
            entry["meta"] = dict(meta)
        index[key] = entry
        await self._store(self._index_oid(bucket), index)
        return etag

    async def get_object(self, bucket: str, key: str) -> bytes:
        await self._require_bucket(bucket)
        index = await self._load(self._index_oid(bucket))
        if key not in index:
            raise RgwError(ENOENT, "NoSuchKey", key)
        return await self._data(bucket, key).read()

    async def head_object(self, bucket: str, key: str) -> dict:
        await self._require_bucket(bucket)
        index = await self._load(self._index_oid(bucket))
        if key not in index:
            raise RgwError(ENOENT, "NoSuchKey", key)
        return index[key]

    async def delete_object(self, bucket: str, key: str) -> None:
        await self._require_bucket(bucket)
        index = await self._load(self._index_oid(bucket))
        if key in index:
            del index[key]
            await self._store(self._index_oid(bucket), index)
        await self._data(bucket, key).remove()

    async def list_objects(
        self,
        bucket: str,
        prefix: str = "",
        delimiter: str = "",
        marker: str = "",
        max_keys: int = 1000,
    ) -> dict:
        """ListObjects with CommonPrefixes rollup
        (RGWRados::Bucket::List::list_objects)."""
        await self._require_bucket(bucket)
        index = await self._load(self._index_oid(bucket))
        keys = sorted(k for k in index if k.startswith(prefix) and k > marker)
        contents: list[dict] = []
        common: list[str] = []
        truncated = False
        for key in keys:
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = key[len(prefix):]
                idx = rest.find(delimiter)
                if idx >= 0:
                    cp = prefix + rest[: idx + len(delimiter)]
                    if cp not in common:
                        common.append(cp)
                    continue
            contents.append({"key": key, **index[key]})
        return {
            "contents": contents,
            "common_prefixes": common,
            "is_truncated": truncated,
        }

    # -- multipart (RGWCompleteMultipart) --------------------------------------

    async def initiate_multipart(self, bucket: str, key: str) -> str:
        await self._require_bucket(bucket)
        upload_id = secrets.token_hex(8)
        await self._store(
            f"rgw.multipart.{upload_id}",
            {"bucket": bucket, "key": key, "parts": {}},
        )
        return upload_id

    async def upload_part(
        self, upload_id: str, part_number: int, data: bytes
    ) -> str:
        meta = await self._load(f"rgw.multipart.{upload_id}")
        if not meta:
            raise RgwError(ENOENT, "NoSuchUpload", upload_id)
        part_obj = StripedObject(
            self.ioctx, f"rgw.part.{upload_id}.{part_number}", policy=self.policy
        )
        await part_obj.remove()
        await part_obj.write(data)
        etag = _etag(data)
        meta["parts"][str(part_number)] = {"size": len(data), "etag": etag}
        await self._store(f"rgw.multipart.{upload_id}", meta)
        return etag

    async def complete_multipart(self, upload_id: str) -> str:
        meta = await self._load(f"rgw.multipart.{upload_id}")
        if not meta:
            raise RgwError(ENOENT, "NoSuchUpload", upload_id)
        bucket, key = meta["bucket"], meta["key"]
        obj = self._data(bucket, key)
        await obj.remove()
        off = 0
        md5s = []
        for pn in sorted(meta["parts"], key=int):
            part_obj = StripedObject(
                self.ioctx, f"rgw.part.{upload_id}.{pn}", policy=self.policy
            )
            data = await part_obj.read()
            await obj.write(data, off)
            off += len(data)
            md5s.append(bytes.fromhex(meta["parts"][pn]["etag"]))
            await part_obj.remove()
        # S3 multipart etag convention: md5-of-md5s + "-<nparts>"
        etag = f"{hashlib.md5(b''.join(md5s)).hexdigest()}-{len(md5s)}"
        index = await self._load(self._index_oid(bucket))
        index[key] = {"size": off, "etag": etag, "mtime": time.time()}
        await self._store(self._index_oid(bucket), index)
        await self.ioctx.remove(f"rgw.multipart.{upload_id}")
        return etag

    async def abort_multipart(self, upload_id: str) -> None:
        meta = await self._load(f"rgw.multipart.{upload_id}")
        for pn in meta.get("parts", {}):
            await StripedObject(
                self.ioctx, f"rgw.part.{upload_id}.{pn}", policy=self.policy
            ).remove()
        try:
            await self.ioctx.remove(f"rgw.multipart.{upload_id}")
        except Exception:
            pass
