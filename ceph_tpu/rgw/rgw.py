"""Object gateway core — mirror of src/rgw's storage layer (rgw_rados /
the SAL RadosStore).

The reference (236k LoC; SURVEY.md §2.7) layers S3/Swift semantics over
RADOS: buckets with an index, objects whose head holds metadata and
whose data stripes over tail objects, multipart uploads assembled from
parts, users with access keys.  The same shapes here:

- **Users** live in a registry object (`user.<id>` in the reference's
  user pool; one JSON registry object here) carrying access/secret keys
  (RGWUserInfo).
- **Buckets**: a bucket record plus a **bucket index** object listing
  keys → {size, etag, mtime} (the reference's bucket index omap,
  cls_rgw); listing with prefix/marker/delimiter walks it exactly like
  RGWRados::Bucket::List with CommonPrefixes.
- **Objects**: data stripes over RADOS via the striper (the reference's
  head+tail manifest, rgw_obj_manifest); etag = md5 of the body as S3
  requires (RGWPutObj_ObjProcessor).
- **Multipart**: parts upload as their own striped objects; complete
  concatenates them into the final object and drops the parts
  (RGWCompleteMultipart).
"""

from __future__ import annotations

import hashlib
import json
import secrets
import time

from ..common.errs import EEXIST, EINVAL, ENOENT, EPERM
from ..striper import StripedObject, StripePolicy

USERS_OID = "rgw.users"
BUCKETS_OID = "rgw.buckets"

# ACL permissions (rgw_acl.h RGW_PERM_*): READ and WRITE are INDEPENDENT
# bits, as in the reference — a write-only grant must not disclose object
# bytes (the Swift drop-box pattern) and a read grant must not allow
# writes.  FULL_CONTROL implies both plus ACL administration.  A grant
# value is one permission or a list of them.
ALL_USERS = "*"  # the AllUsers group grantee (anonymous included)


def _perm_set(value) -> set[str]:
    perms = {value} if isinstance(value, str) else set(value)
    if "FULL_CONTROL" in perms:
        perms |= {"READ", "WRITE"}
    return perms


class RgwError(Exception):
    def __init__(self, err: int, code: str, msg: str = ""):
        self.errno = -abs(err)
        self.code = code  # S3 error code (NoSuchBucket, ...)
        super().__init__(f"{code}: {msg}")


def _etag(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


class ObjectGateway:
    """The gateway's storage operations (rgw::sal::RadosStore analog);
    one instance per pool-backed zone."""

    def __init__(self, ioctx, policy: StripePolicy | None = None):
        self.ioctx = ioctx
        self.policy = policy or StripePolicy(
            stripe_unit=512 * 1024, stripe_count=1, object_size=4 * 1024 * 1024
        )

    # -- registries ------------------------------------------------------------

    async def _load(self, oid: str) -> dict:
        try:
            raw = await self.ioctx.read(oid)
            return json.loads(raw.decode() or "{}")
        except Exception:
            return {}

    async def _store(self, oid: str, data: dict) -> None:
        await self.ioctx.write_full(oid, json.dumps(data).encode())

    # -- users (RGWUserInfo) ---------------------------------------------------

    async def create_user(self, uid: str, display_name: str = "") -> dict:
        users = await self._load(USERS_OID)
        if uid in users:
            raise RgwError(EEXIST, "UserAlreadyExists", uid)
        user = {
            "uid": uid,
            "display_name": display_name or uid,
            "access_key": secrets.token_hex(10).upper(),
            "secret_key": secrets.token_hex(20),
        }
        users[uid] = user
        await self._store(USERS_OID, users)
        return user

    async def get_user(self, uid: str) -> dict:
        users = await self._load(USERS_OID)
        if uid not in users:
            raise RgwError(ENOENT, "NoSuchUser", uid)
        return users[uid]

    async def user_by_access_key(self, access_key: str) -> dict | None:
        users = await self._load(USERS_OID)
        for user in users.values():
            if user["access_key"] == access_key:
                return user
        return None

    # -- buckets ---------------------------------------------------------------

    def _index_oid(self, bucket: str) -> str:
        return f"rgw.bucket.index.{bucket}"

    async def create_bucket(
        self, bucket: str, owner: str = "", grants: dict | None = None
    ) -> None:
        """`grants` maps grantee (uid or "*" AllUsers) -> permission —
        the RGWAccessControlPolicy essence (rgw_acl.cc); canned-ACL
        translation lives in the REST layer."""
        buckets = await self._load(BUCKETS_OID)
        if bucket in buckets:
            raise RgwError(EEXIST, "BucketAlreadyExists", bucket)
        buckets[bucket] = {
            "owner": owner,
            "created": time.time(),
            "grants": dict(grants or {}),
            "versioning": "",
        }
        await self._store(BUCKETS_OID, buckets)
        await self._store(self._index_oid(bucket), {})

    # -- ACLs (RGWAccessControlPolicy; verify_bucket_permission) ---------------

    @staticmethod
    def _allowed(info: dict, actor: str | None, need: str) -> bool:
        owner = info.get("owner", "")
        if not owner:
            return True  # legacy/open bucket (no owner recorded)
        if actor == owner:
            return True  # owner always has FULL_CONTROL
        grants = info.get("grants", {})
        for grantee, perm in grants.items():
            if grantee == ALL_USERS or grantee == actor:
                if need in _perm_set(perm):
                    return True
        return False

    async def _require_access(
        self, bucket: str, actor: str | None, need: str
    ) -> dict:
        """Bucket record if `actor` holds `need`, else AccessDenied
        (rgw_op.cc verify_bucket_permission → -EACCES)."""
        buckets = await self._load(BUCKETS_OID)
        if bucket not in buckets:
            raise RgwError(ENOENT, "NoSuchBucket", bucket)
        info = buckets[bucket]
        if not self._allowed(info, actor, need):
            raise RgwError(EPERM, "AccessDenied", f"{actor} lacks {need} on {bucket}")
        return info

    async def get_bucket_acl(self, bucket: str, actor: str | None = None) -> dict:
        info = await self._require_access(bucket, actor, "FULL_CONTROL")
        return {"owner": info.get("owner", ""), "grants": info.get("grants", {})}

    async def set_bucket_acl(
        self, bucket: str, grants: dict, actor: str | None = None
    ) -> None:
        await self._require_access(bucket, actor, "FULL_CONTROL")
        buckets = await self._load(BUCKETS_OID)
        buckets[bucket]["grants"] = dict(grants)
        await self._store(BUCKETS_OID, buckets)

    # -- lifecycle (RGWLC / RGWPutLC; cls_lc essence) --------------------------

    async def set_lifecycle(
        self, bucket: str, rules: list[dict], actor: str | None = None
    ) -> None:
        """rules: [{"id", "prefix", "days"}] — expiration-only scope (the
        reference's transition rules need storage classes, out of scope)."""
        await self._require_access(bucket, actor, "FULL_CONTROL")
        for r in rules:
            if int(r.get("days", -1)) < 0:
                raise RgwError(EINVAL, "InvalidArgument", "Days must be >= 0")
        buckets = await self._load(BUCKETS_OID)
        buckets[bucket]["lifecycle"] = [
            {"id": r.get("id", ""), "prefix": r.get("prefix", ""),
             "days": int(r["days"])}
            for r in rules
        ]
        await self._store(BUCKETS_OID, buckets)

    async def get_lifecycle(
        self, bucket: str, actor: str | None = None
    ) -> list[dict]:
        info = await self._require_access(bucket, actor, "READ")
        rules = info.get("lifecycle", [])
        if not rules:
            raise RgwError(ENOENT, "NoSuchLifecycleConfiguration", bucket)
        return rules

    async def process_lifecycle(self, now: float | None = None) -> int:
        """One LC pass over every bucket (RGWLC::process): expire objects
        whose latest mtime is older than a matching rule's Days.  On a
        versioning-enabled bucket expiration lays a delete marker, as S3
        does.  Returns the number of keys expired."""
        now = time.time() if now is None else now
        buckets = await self._load(BUCKETS_OID)
        expired = 0
        for bucket, info in buckets.items():
            rules = info.get("lifecycle")
            if not rules:
                continue
            owner = info.get("owner", "") or None
            index = await self._load(self._index_oid(bucket))
            for key in sorted(index):
                live = self._live(index[key])
                if live is None:
                    continue
                for rule in rules:
                    if not key.startswith(rule["prefix"]):
                        continue
                    if now - live.get("mtime", now) >= rule["days"] * 86400:
                        await self.delete_object(bucket, key, actor=owner)
                        expired += 1
                        break
        return expired

    # -- versioning (RGWBucketVersioning; rgw_op RGWSetBucketVersioning) -------

    async def set_versioning(
        self, bucket: str, status: str, actor: str | None = None
    ) -> None:
        if status not in ("Enabled", "Suspended"):
            raise RgwError(EINVAL, "IllegalVersioningConfigurationException", status)
        # S3 PutBucketVersioning is a bucket-configuration change: owner /
        # FULL_CONTROL only, like set_lifecycle — a WRITE (object upload)
        # grant must not be able to flip versioning off
        await self._require_access(bucket, actor, "FULL_CONTROL")
        buckets = await self._load(BUCKETS_OID)
        buckets[bucket]["versioning"] = status
        await self._store(BUCKETS_OID, buckets)

    async def get_versioning(self, bucket: str, actor: str | None = None) -> str:
        info = await self._require_access(bucket, actor, "READ")
        return info.get("versioning", "")

    async def list_buckets(self, owner: str | None = None) -> list[str]:
        buckets = await self._load(BUCKETS_OID)
        return sorted(
            b for b, info in buckets.items()
            if owner is None or info["owner"] == owner
        )

    async def delete_bucket(self, bucket: str) -> None:
        buckets = await self._load(BUCKETS_OID)
        if bucket not in buckets:
            raise RgwError(ENOENT, "NoSuchBucket", bucket)
        index = await self._load(self._index_oid(bucket))
        if index:
            raise RgwError(EINVAL, "BucketNotEmpty", bucket)
        del buckets[bucket]
        await self._store(BUCKETS_OID, buckets)
        try:
            await self.ioctx.remove(self._index_oid(bucket))
        except Exception:
            pass

    async def _require_bucket(self, bucket: str) -> None:
        buckets = await self._load(BUCKETS_OID)
        if bucket not in buckets:
            raise RgwError(ENOENT, "NoSuchBucket", bucket)

    # -- objects ---------------------------------------------------------------

    def _data(self, bucket: str, key: str, vid: str = "") -> StripedObject:
        # versioned data lives under its own prefix keyed by version id
        # ("@" is reserved for snap clones in the RADOS flat namespace)
        oid = (
            f"rgw.ver.{vid}.{bucket}/{key}" if vid else f"rgw.obj.{bucket}/{key}"
        )
        return StripedObject(self.ioctx, oid, policy=self.policy)

    @staticmethod
    def _latest(entry: dict) -> dict | None:
        """Latest version record of a versioned entry (None = plain)."""
        versions = entry.get("versions")
        return versions[-1] if versions else None

    @staticmethod
    def _live(entry: dict) -> dict | None:
        """The record a plain GET serves: the entry itself (plain), or
        the latest version when it is not a delete marker."""
        if "versions" not in entry:
            return entry
        latest = entry["versions"][-1]
        return None if latest.get("delete_marker") else latest

    async def put_object(
        self,
        bucket: str,
        key: str,
        data: bytes,
        meta: dict | None = None,
        actor: str | None = None,
    ) -> tuple[str, str]:
        """PutObject; returns (etag, version_id) — version_id "" on an
        unversioned bucket (RGWPutObj).  `meta` carries user metadata
        (x-amz-meta-* / X-Object-Meta-*, RGWObjManifest attrs)."""
        info = await self._require_access(bucket, actor, "WRITE")
        versioning = info.get("versioning", "")
        etag = _etag(data)
        index = await self._load(self._index_oid(bucket))
        entry = index.get(key, {})
        record = {"size": len(data), "etag": etag, "mtime": time.time()}
        if actor:
            record["owner"] = actor  # the uploader (object owner in S3)
        if meta:
            record["meta"] = dict(meta)
        if versioning == "Enabled":
            vid = secrets.token_hex(8)
        elif versioning == "Suspended" or "versions" in entry:
            # suspended (or formerly-versioned): writes land on the
            # "null" version, replacing any previous null (S3 semantics)
            vid = "null"
        else:
            vid = ""
        if vid:
            record["version_id"] = vid
            versions = [
                v for v in entry.get("versions", []) if v.get("version_id") != vid
            ]
            versions.append(record)
            index[key] = {"versions": versions}
            obj = self._data(bucket, key, vid)
        else:
            index[key] = record
            obj = self._data(bucket, key)
        await obj.remove()  # overwrite semantics
        await obj.write(data)
        await self._store(self._index_oid(bucket), index)
        return etag, vid

    @staticmethod
    def _object_allowed(
        record: dict, bucket_info: dict, actor: str | None, need: str
    ) -> bool:
        """Object-level ACL check (rgw_op verify_object_permission): the
        object's own policy decides when present; otherwise the bucket's
        policy governs.  The object owner (its uploader) always has
        FULL_CONTROL, like the reference's object owner semantics."""
        acl = record.get("acl")
        if acl is None:
            return ObjectGateway._allowed(bucket_info, actor, need)
        if actor and actor == acl.get("owner"):
            return True
        if ObjectGateway._allowed(
            {"owner": acl.get("owner", ""), "grants": acl.get("grants", {})},
            actor,
            need,
        ):
            return True
        # bucket owner retains control over contained objects
        return bool(bucket_info.get("owner")) and actor == bucket_info["owner"]

    def _resolve(
        self, entry: dict, key: str, version_id: str
    ) -> dict:
        """Pick the version record a read addresses, with S3's errors:
        latest-is-marker -> NoSuchKey; explicit missing vid -> NoSuchVersion."""
        if version_id:
            for v in entry.get("versions", []):
                if v.get("version_id") == version_id:
                    if v.get("delete_marker"):
                        raise RgwError(ENOENT, "MethodNotAllowed", "delete marker")
                    return v
            raise RgwError(ENOENT, "NoSuchVersion", version_id)
        live = self._live(entry)
        if live is None:
            raise RgwError(ENOENT, "NoSuchKey", key)
        return live

    async def get_object(
        self,
        bucket: str,
        key: str,
        actor: str | None = None,
        version_id: str = "",
    ) -> bytes:
        info = await self._object_access(bucket, key, actor, "READ")
        index = await self._load(self._index_oid(bucket))
        if key not in index:
            raise RgwError(ENOENT, "NoSuchKey", key)
        record = self._resolve(index[key], key, version_id)
        return await self._data(
            bucket, key, record.get("version_id", "")
        ).read()

    async def _object_access(
        self, bucket: str, key: str, actor: str | None, need: str
    ) -> dict:
        """Bucket info after the object-level check: an object ACL (when
        set) overrides the bucket policy for this object."""
        buckets = await self._load(BUCKETS_OID)
        if bucket not in buckets:
            raise RgwError(ENOENT, "NoSuchBucket", bucket)
        info = buckets[bucket]
        index = await self._load(self._index_oid(bucket))
        entry = index.get(key)
        live = self._live(entry) if entry else None
        record = live if live is not None else {}
        if not self._object_allowed(record, info, actor, need):
            raise RgwError(
                EPERM, "AccessDenied", f"{actor} lacks {need} on {bucket}/{key}"
            )
        return info

    async def head_object(
        self,
        bucket: str,
        key: str,
        actor: str | None = None,
        version_id: str = "",
    ) -> dict:
        await self._object_access(bucket, key, actor, "READ")
        index = await self._load(self._index_oid(bucket))
        if key not in index:
            raise RgwError(ENOENT, "NoSuchKey", key)
        return self._resolve(index[key], key, version_id)

    async def delete_object(
        self,
        bucket: str,
        key: str,
        actor: str | None = None,
        version_id: str = "",
    ) -> str:
        """DeleteObject.  On a versioning-enabled bucket a plain delete
        lays down a DELETE MARKER (returns its version id); deleting a
        specific version removes that version's bytes (RGWDeleteObj)."""
        info = await self._require_access(bucket, actor, "WRITE")
        versioning = info.get("versioning", "")
        index = await self._load(self._index_oid(bucket))
        entry = index.get(key)
        if entry is None:
            # deleting a missing key succeeds (S3), marker only if enabled
            if versioning != "Enabled":
                await self._data(bucket, key).remove()
                return ""
            entry = {"versions": []}
        if version_id:
            versions = entry.get("versions", [])
            keep = [v for v in versions if v.get("version_id") != version_id]
            if len(keep) == len(versions):
                raise RgwError(ENOENT, "NoSuchVersion", version_id)
            await self._data(bucket, key, version_id).remove()
            if keep:
                index[key] = {"versions": keep}
            else:
                del index[key]
            await self._store(self._index_oid(bucket), index)
            return version_id
        if versioning == "Enabled":
            vid = secrets.token_hex(8)
            versions = entry.get("versions", [])
            versions.append(
                {"version_id": vid, "delete_marker": True, "mtime": time.time()}
            )
            index[key] = {"versions": versions}
            await self._store(self._index_oid(bucket), index)
            return vid
        if "versions" in entry:
            # suspended: plain delete replaces the null version with a
            # null delete marker
            versions = [
                v for v in entry["versions"] if v.get("version_id") != "null"
            ]
            await self._data(bucket, key, "null").remove()
            versions.append(
                {"version_id": "null", "delete_marker": True, "mtime": time.time()}
            )
            index[key] = {"versions": versions}
            await self._store(self._index_oid(bucket), index)
            return "null"
        del index[key]
        await self._store(self._index_oid(bucket), index)
        await self._data(bucket, key).remove()
        return ""

    async def set_object_acl(
        self, bucket: str, key: str, grants: dict, actor: str | None = None
    ) -> None:
        """PutObjectAcl: per-object grants, owner-gated (the object's
        uploader or the bucket owner)."""
        info = await self._require_access(bucket, actor, "READ")
        index = await self._load(self._index_oid(bucket))
        entry = index.get(key)
        live = self._live(entry) if entry else None
        if live is None:
            raise RgwError(ENOENT, "NoSuchKey", key)
        current = live.get("acl") or {"owner": live.get("owner", ""), "grants": {}}
        admin = (
            actor
            and (
                actor == current.get("owner")
                or actor == info.get("owner")
                or not info.get("owner")
            )
        )
        if not admin:
            raise RgwError(EPERM, "AccessDenied", f"{actor} cannot set acl")
        live["acl"] = {"owner": current.get("owner") or (actor or ""), "grants": dict(grants)}
        await self._store(self._index_oid(bucket), index)

    async def get_object_acl(
        self, bucket: str, key: str, actor: str | None = None
    ) -> dict:
        await self._object_access(bucket, key, actor, "READ")
        index = await self._load(self._index_oid(bucket))
        live = self._live(index.get(key, {}))
        if live is None:
            raise RgwError(ENOENT, "NoSuchKey", key)
        return live.get("acl") or {"owner": "", "grants": {}}

    async def list_object_versions(
        self, bucket: str, prefix: str = "", actor: str | None = None
    ) -> list[dict]:
        """ListObjectVersions: every version + delete marker, newest
        first per key (RGWListBucketVersions)."""
        await self._require_access(bucket, actor, "READ")
        index = await self._load(self._index_oid(bucket))
        out: list[dict] = []
        for key in sorted(k for k in index if k.startswith(prefix)):
            entry = index[key]
            versions = entry.get("versions")
            if versions is None:
                out.append({"key": key, "version_id": "null", "is_latest": True, **entry})
                continue
            for i, v in enumerate(reversed(versions)):
                out.append({"key": key, "is_latest": i == 0, **v})
        return out

    async def list_objects(
        self,
        bucket: str,
        prefix: str = "",
        delimiter: str = "",
        marker: str = "",
        max_keys: int = 1000,
        actor: str | None = None,
    ) -> dict:
        """ListObjects with CommonPrefixes rollup
        (RGWRados::Bucket::List::list_objects).  Versioned entries show
        their latest LIVE version; keys whose latest is a delete marker
        are hidden (as S3 lists them)."""
        await self._require_access(bucket, actor, "READ")
        index = await self._load(self._index_oid(bucket))
        keys = sorted(k for k in index if k.startswith(prefix) and k > marker)
        contents: list[dict] = []
        common: list[str] = []
        truncated = False
        for key in keys:
            live = self._live(index[key])
            if live is None:
                continue  # latest is a delete marker
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = key[len(prefix):]
                idx = rest.find(delimiter)
                if idx >= 0:
                    cp = prefix + rest[: idx + len(delimiter)]
                    if cp not in common:
                        common.append(cp)
                    continue
            contents.append({"key": key, **live})
        return {
            "contents": contents,
            "common_prefixes": common,
            "is_truncated": truncated,
        }

    # -- multipart (RGWCompleteMultipart) --------------------------------------

    async def initiate_multipart(
        self, bucket: str, key: str, actor: str | None = None
    ) -> str:
        await self._require_access(bucket, actor, "WRITE")
        upload_id = secrets.token_hex(8)
        await self._store(
            f"rgw.multipart.{upload_id}",
            {"bucket": bucket, "key": key, "parts": {}},
        )
        return upload_id

    async def upload_part(
        self, upload_id: str, part_number: int, data: bytes
    ) -> str:
        meta = await self._load(f"rgw.multipart.{upload_id}")
        if not meta:
            raise RgwError(ENOENT, "NoSuchUpload", upload_id)
        part_obj = StripedObject(
            self.ioctx, f"rgw.part.{upload_id}.{part_number}", policy=self.policy
        )
        await part_obj.remove()
        await part_obj.write(data)
        etag = _etag(data)
        meta["parts"][str(part_number)] = {"size": len(data), "etag": etag}
        await self._store(f"rgw.multipart.{upload_id}", meta)
        return etag

    async def complete_multipart(
        self, upload_id: str, actor: str | None = None
    ) -> str:
        meta = await self._load(f"rgw.multipart.{upload_id}")
        if not meta:
            raise RgwError(ENOENT, "NoSuchUpload", upload_id)
        bucket, key = meta["bucket"], meta["key"]
        info = await self._require_access(bucket, actor, "WRITE")
        versioning = info.get("versioning", "")
        index = await self._load(self._index_oid(bucket))
        if versioning == "Enabled":
            vid = secrets.token_hex(8)
        elif versioning == "Suspended" or "versions" in index.get(key, {}):
            vid = "null"
        else:
            vid = ""
        obj = self._data(bucket, key, vid)
        await obj.remove()
        off = 0
        md5s = []
        for pn in sorted(meta["parts"], key=int):
            part_obj = StripedObject(
                self.ioctx, f"rgw.part.{upload_id}.{pn}", policy=self.policy
            )
            data = await part_obj.read()
            await obj.write(data, off)
            off += len(data)
            md5s.append(bytes.fromhex(meta["parts"][pn]["etag"]))
            await part_obj.remove()
        # S3 multipart etag convention: md5-of-md5s + "-<nparts>"
        etag = f"{hashlib.md5(b''.join(md5s)).hexdigest()}-{len(md5s)}"
        record = {"size": off, "etag": etag, "mtime": time.time()}
        if vid:
            record["version_id"] = vid
            entry = index.get(key, {})
            versions = [
                v for v in entry.get("versions", []) if v.get("version_id") != vid
            ]
            versions.append(record)
            index[key] = {"versions": versions}
        else:
            index[key] = record
        await self._store(self._index_oid(bucket), index)
        await self.ioctx.remove(f"rgw.multipart.{upload_id}")
        return etag

    async def list_multipart_uploads(
        self, bucket: str, actor: str | None = None
    ) -> list[dict]:
        """ListMultipartUploads (RGWListBucketMultiparts)."""
        await self._require_access(bucket, actor, "READ")
        out = []
        for oid in await self.ioctx.list_objects():
            if not oid.startswith("rgw.multipart."):
                continue
            meta = await self._load(oid)
            if meta.get("bucket") == bucket:
                out.append(
                    {"upload_id": oid[len("rgw.multipart."):],
                     "key": meta.get("key", "")}
                )
        return sorted(out, key=lambda u: (u["key"], u["upload_id"]))

    async def list_parts(self, upload_id: str) -> list[dict]:
        """ListParts (RGWListMultipart)."""
        meta = await self._load(f"rgw.multipart.{upload_id}")
        if not meta:
            raise RgwError(ENOENT, "NoSuchUpload", upload_id)
        return [
            {"part_number": int(pn), **info}
            for pn, info in sorted(meta["parts"].items(), key=lambda kv: int(kv[0]))
        ]

    async def abort_multipart(self, upload_id: str) -> None:
        meta = await self._load(f"rgw.multipart.{upload_id}")
        for pn in meta.get("parts", {}):
            await StripedObject(
                self.ioctx, f"rgw.part.{upload_id}.{pn}", policy=self.policy
            ).remove()
        try:
            await self.ioctx.remove(f"rgw.multipart.{upload_id}")
        except Exception:
            pass
