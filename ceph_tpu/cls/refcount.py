"""cls_refcount — reference counting for shared objects
(src/cls/refcount/cls_refcount.cc; RGW dedupes tail objects with it):
put/get tags; when the last tag drops, the object deletes itself."""

from __future__ import annotations

import json

from ..common.errs import EINVAL, ENOENT
from .objclass import RD, WR, ClsError, HCtx, cls_method

ATTR = "refcount"


def _refs(ctx: HCtx) -> list[str]:
    raw = ctx.getxattr(ATTR)
    return json.loads(raw.decode()) if raw else []


@cls_method("refcount", "get", RD | WR)
def get(ctx: HCtx, indata: bytes) -> bytes:
    """Take a reference (tag must be unique per referrer)."""
    tag = json.loads(indata.decode())["tag"]
    if not tag:
        raise ClsError(EINVAL, "empty tag")
    refs = _refs(ctx)
    if tag not in refs:
        refs.append(tag)
    ctx.setxattr(ATTR, json.dumps(refs).encode())
    return b""


@cls_method("refcount", "put", RD | WR)
def put(ctx: HCtx, indata: bytes) -> bytes:
    """Drop a reference; reports whether the object should be reaped
    (the reference class deletes it server-side; here the caller issues
    the delete on {"last": true} — same two-phase shape RGW gc uses)."""
    tag = json.loads(indata.decode())["tag"]
    refs = _refs(ctx)
    if tag not in refs:
        raise ClsError(ENOENT, f"tag {tag!r} holds no reference")
    refs.remove(tag)
    if refs:
        ctx.setxattr(ATTR, json.dumps(refs).encode())
        return json.dumps({"last": False}).encode()
    ctx.rmxattr(ATTR)
    return json.dumps({"last": True}).encode()


@cls_method("refcount", "read", RD)
def read(ctx: HCtx, indata: bytes) -> bytes:
    return json.dumps(_refs(ctx)).encode()
