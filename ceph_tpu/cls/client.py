"""Client-side class wrappers (src/cls/lock/cls_lock_client.h and
siblings): typed helpers over IoCtx.exec for the in-tree classes."""

from __future__ import annotations

import json


async def lock(ioctx, oid: str, name: str, *, cookie: str = "",
               lock_type: str = "exclusive", description: str = "") -> None:
    await ioctx.exec(oid, "lock", "lock", json.dumps({
        "name": name, "type": lock_type, "cookie": cookie,
        "description": description,
    }).encode())


async def unlock(ioctx, oid: str, name: str, *, cookie: str = "") -> None:
    await ioctx.exec(oid, "lock", "unlock", json.dumps(
        {"name": name, "cookie": cookie}
    ).encode())


async def break_lock(ioctx, oid: str, name: str, entity: str,
                     *, cookie: str = "") -> None:
    await ioctx.exec(oid, "lock", "break_lock", json.dumps(
        {"name": name, "entity": entity, "cookie": cookie}
    ).encode())


async def get_lock_info(ioctx, oid: str, name: str) -> dict:
    out = await ioctx.exec(oid, "lock", "get_info",
                           json.dumps({"name": name}).encode())
    return json.loads(out.decode())


async def version_inc(ioctx, oid: str) -> int:
    out = await ioctx.exec(oid, "version", "inc", b"{}")
    return int(json.loads(out.decode())["ver"])


async def version_read(ioctx, oid: str) -> int:
    out = await ioctx.exec(oid, "version", "read", b"{}")
    return int(json.loads(out.decode())["ver"])


async def version_check(ioctx, oid: str, ver: int, cond: str = "eq") -> None:
    await ioctx.exec(oid, "version", "check", json.dumps(
        {"ver": ver, "cond": cond}
    ).encode())


async def numops_add(ioctx, oid: str, key: str, value: float) -> float:
    out = await ioctx.exec(oid, "numops", "add", json.dumps(
        {"key": key, "value": value}
    ).encode())
    return float(out.decode())


async def refcount_get(ioctx, oid: str, tag: str) -> None:
    await ioctx.exec(oid, "refcount", "get", json.dumps({"tag": tag}).encode())


async def refcount_put(ioctx, oid: str, tag: str) -> bool:
    """Drop a reference; True when it was the LAST one (caller reaps)."""
    out = await ioctx.exec(oid, "refcount", "put",
                           json.dumps({"tag": tag}).encode())
    return bool(json.loads(out.decode())["last"])
