"""cls_version — object version counters with guards
(src/cls/version/cls_version.cc; RGW builds bucket-index consistency on
it).  Version = (ver: u64, tag: str) in xattr "ver"."""

from __future__ import annotations

import json

from ..common.errs import ECANCELED, EINVAL
from .objclass import RD, WR, ClsError, HCtx, cls_method

ATTR = "ver"


def _read(ctx: HCtx) -> dict:
    raw = ctx.getxattr(ATTR)
    return json.loads(raw.decode()) if raw else {"ver": 0, "tag": ""}


@cls_method("version", "set", RD | WR)
def set_(ctx: HCtx, indata: bytes) -> bytes:
    req = json.loads(indata.decode())
    ctx.setxattr(ATTR, json.dumps(
        {"ver": int(req["ver"]), "tag": req.get("tag", "")}
    ).encode())
    return b""


@cls_method("version", "inc", RD | WR)
def inc(ctx: HCtx, indata: bytes) -> bytes:
    v = _read(ctx)
    v["ver"] += 1
    ctx.setxattr(ATTR, json.dumps(v).encode())
    return json.dumps(v).encode()


@cls_method("version", "read", RD)
def read(ctx: HCtx, indata: bytes) -> bytes:
    return json.dumps(_read(ctx)).encode()


@cls_method("version", "check", RD)
def check(ctx: HCtx, indata: bytes) -> bytes:
    """Guard (cls_version check_conds): -ECANCELED unless the stored
    version satisfies every condition (eq | gt | ge vs `ver`)."""
    req = json.loads(indata.decode())
    have = _read(ctx)["ver"]
    want = int(req["ver"])
    op = req.get("cond", "eq")
    ok = {"eq": have == want, "gt": have > want, "ge": have >= want}.get(op)
    if ok is None:
        # malformed input, NOT a guard mismatch: retry loops keyed on
        # -ECANCELED must be able to tell the two apart
        raise ClsError(EINVAL, f"unknown cond {op!r}")
    if not ok:
        raise ClsError(ECANCELED, f"version {have} fails {op} {want}")
    return b""
