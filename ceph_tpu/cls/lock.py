"""cls_lock — advisory object locks (src/cls/lock/cls_lock.cc).

Lock state lives in the object xattr "lock.<name>": a JSON record of
type (exclusive | shared) and holders [(entity, cookie, description)].
Semantics mirrored from the reference:

- `lock`: acquire; -EBUSY when held incompatibly; re-acquiring YOUR OWN
  (entity, cookie) succeeds (renewal, cls_lock.cc lock_obj).
- `unlock`: release (entity, cookie); -ENOENT when not held by you.
- `break_lock`: forcibly drop ANOTHER entity's hold (the recovery path
  rbd mirroring uses when a holder dies).
- `get_info`: dump holders.

RBD image exclusive ownership and mirroring fencing build on exactly
this class in the reference (librbd ManagedLock).
"""

from __future__ import annotations

import json

from ..common.errs import EBUSY, ENOENT
from .objclass import RD, WR, ClsError, HCtx, cls_method

LOCK_PREFIX = "lock."

EXCLUSIVE = "exclusive"
SHARED = "shared"


def _state(ctx: HCtx, name: str) -> dict:
    raw = ctx.getxattr(LOCK_PREFIX + name)
    if not raw:
        return {"type": "", "holders": []}
    return json.loads(raw.decode())


def _store(ctx: HCtx, name: str, st: dict) -> None:
    if st["holders"]:
        ctx.setxattr(LOCK_PREFIX + name, json.dumps(st).encode())
    else:
        ctx.rmxattr(LOCK_PREFIX + name)


@cls_method("lock", "lock", RD | WR)
def lock(ctx: HCtx, indata: bytes) -> bytes:
    req = json.loads(indata.decode())
    name, ltype = req["name"], req.get("type", EXCLUSIVE)
    cookie, desc = req.get("cookie", ""), req.get("description", "")
    st = _state(ctx, name)
    me = [ctx.entity, cookie]
    holders = st["holders"]
    if holders:
        if me in [h[:2] for h in holders]:
            # renewal of our own hold; escalation (shared -> exclusive)
            # only when we are the SOLE holder, else the
            # exclusive-implies-single-holder invariant would break
            if ltype != st["type"] and len(holders) > 1:
                raise ClsError(EBUSY, f"lock {name} held shared by others")
        elif st["type"] == SHARED and ltype == SHARED:
            pass  # compatible share
        else:
            raise ClsError(EBUSY, f"lock {name} held")
    if me not in [h[:2] for h in holders]:
        holders.append([ctx.entity, cookie, desc])
    st["type"] = ltype
    _store(ctx, name, st)
    return b""


@cls_method("lock", "unlock", RD | WR)
def unlock(ctx: HCtx, indata: bytes) -> bytes:
    req = json.loads(indata.decode())
    name, cookie = req["name"], req.get("cookie", "")
    st = _state(ctx, name)
    before = len(st["holders"])
    st["holders"] = [
        h for h in st["holders"] if h[:2] != [ctx.entity, cookie]
    ]
    if len(st["holders"]) == before:
        raise ClsError(ENOENT, f"lock {name} not held by caller")
    _store(ctx, name, st)
    return b""


@cls_method("lock", "break_lock", RD | WR)
def break_lock(ctx: HCtx, indata: bytes) -> bytes:
    req = json.loads(indata.decode())
    name = req["name"]
    victim = [req["entity"], req.get("cookie", "")]
    st = _state(ctx, name)
    before = len(st["holders"])
    st["holders"] = [h for h in st["holders"] if h[:2] != victim]
    if len(st["holders"]) == before:
        raise ClsError(ENOENT, f"no such holder on {name}")
    _store(ctx, name, st)
    return b""


@cls_method("lock", "get_info", RD)
def get_info(ctx: HCtx, indata: bytes) -> bytes:
    name = json.loads(indata.decode())["name"]
    return json.dumps(_state(ctx, name)).encode()
