"""Object-class runtime: registry, method decorator, handler context.

Mirrors src/objclass/objclass.h: `cls_register` / `cls_register_cxx_method`
with CLS_METHOD_RD / CLS_METHOD_WR flags, and the `cls_method_context_t`
handle through which a method reads and mutates ITS object (never other
objects — the reference's isolation rule).  Methods return non-negative
on success (becomes the op result) or raise ClsError(errno).

Mutations accumulate into the enclosing op's PGTransaction — the same
replication/journaling path as plain writes — with a read-your-writes
overlay so a later method in the same op observes earlier staged state.
"""

from __future__ import annotations

import importlib
from typing import Callable

from ..common.errs import ENOENT, EOPNOTSUPP

RD = 1  # method reads the object (CLS_METHOD_RD)
WR = 2  # method mutates the object (CLS_METHOD_WR)


class ClsError(Exception):
    """Negative-errno failure from a class method (CLS_... error return)."""

    def __init__(self, err: int, msg: str = ""):
        self.errno = -abs(err)
        super().__init__(msg or f"cls error {self.errno}")


class MethodNotFound(ClsError):
    def __init__(self, what: str):
        super().__init__(EOPNOTSUPP, f"no such class method {what}")


# cls name -> method name -> (flags, fn(ctx, indata) -> bytes | (rc, bytes))
registry: dict[str, dict[str, tuple[int, Callable]]] = {}

_BUILTIN_PKG = __name__.rsplit(".", 1)[0]  # ceph_tpu.cls


def cls_method(cls_name: str, method: str, flags: int):
    """Register a method (objclass.h cls_register_cxx_method)."""

    def deco(fn):
        registry.setdefault(cls_name, {})[method] = (flags, fn)
        return fn

    return deco


def load_class(name: str) -> None:
    """The dlopen analog: import ceph_tpu.cls.<name>, whose module body
    registers its methods (a `libcls_<name>.so` __cls_init)."""
    if name in registry:
        return
    importlib.import_module(f"{_BUILTIN_PKG}.{name}")
    if name not in registry:
        raise MethodNotFound(f"{name} (module registered no methods)")


def get_method(cls_name: str, method: str) -> tuple[int, Callable]:
    """Resolve, loading the class on first use (PrimaryLogPG CALL path:
    osd->class_handler->open_class)."""
    methods = registry.get(cls_name)
    if methods is None:
        try:
            load_class(cls_name)
        except (ImportError, MethodNotFound):
            raise MethodNotFound(f"{cls_name}.{method}") from None
        methods = registry.get(cls_name, {})
    entry = methods.get(method)
    if entry is None:
        raise MethodNotFound(f"{cls_name}.{method}")
    return entry


class HCtx:
    """cls_method_context_t: the method's window onto its object.

    Reads see the object's pre-op state overlaid with writes staged
    earlier in the same op; writes stage into `attrs` / `data` and are
    folded into the PGTransaction by the PG after the method returns.
    `entity` is the calling client (reqid), the identity cls_lock keys on.
    """

    def __init__(
        self,
        *,
        exists: bool,
        read_fn: Callable[[], bytes],
        getattr_fn: Callable[[str], bytes | None],
        entity: str = "",
        writable: bool = False,
        omap_fn: Callable[[], dict] | None = None,
    ):
        self._exists = exists
        self._read_fn = read_fn
        self._getattr_fn = getattr_fn
        self._omap_fn = omap_fn  # None: pool has no omap (EC)
        self.entity = entity
        self.writable = writable
        # staged state (read-your-writes overlay; None value = removed)
        self.attrs: dict[str, bytes | None] = {}
        self.omap: dict[str, bytes | None] = {}
        self.omap_cleared = False
        self.data: bytes | None = None
        # whole-object view already folded into the enclosing transaction
        # by an earlier method in the same op (set by the PG)
        self.folded_data: bytes | None = None
        self.created = False

    # -- reads ----------------------------------------------------------------

    def exists(self) -> bool:
        return self._exists or self.created

    def read(self) -> bytes:
        """cls_cxx_read (whole object)."""
        if self.data is not None:
            return self.data
        if self.folded_data is not None:
            return self.folded_data
        if not self._exists:
            raise ClsError(ENOENT, "object does not exist")
        return self._read_fn()

    def getxattr(self, name: str) -> bytes | None:
        """cls_cxx_getxattr; None when absent."""
        if name in self.attrs:
            return self.attrs[name]
        return self._getattr_fn(name)

    # -- omap (cls_cxx_map_* family; cls_rgw's bucket-index substrate) ---------

    def _omap_view(self) -> dict[str, bytes]:
        if self._omap_fn is None:
            raise ClsError(EOPNOTSUPP, "omap on an EC pool")
        base = {} if self.omap_cleared else dict(self._omap_fn())
        for k, v in self.omap.items():
            if v is None:
                base.pop(k, None)
            else:
                base[k] = v
        return base

    def map_get_val(self, key: str) -> bytes:
        """cls_cxx_map_get_val; raises ENOENT when absent."""
        view = self._omap_view()
        if key not in view:
            raise ClsError(ENOENT, f"omap key {key!r}")
        return view[key]

    def map_get_keys(self) -> list[str]:
        return sorted(self._omap_view())

    def map_get_all(self) -> dict[str, bytes]:
        return self._omap_view()

    # -- writes (WR methods only) ---------------------------------------------

    def _need_wr(self) -> None:
        if not self.writable:
            raise ClsError(EOPNOTSUPP, "RD method attempted a write")

    def create(self) -> None:
        """cls_cxx_create: materialize the object (touch)."""
        self._need_wr()
        self.created = True

    def write_full(self, data: bytes) -> None:
        self._need_wr()
        self.data = bytes(data)
        self.created = True

    def setxattr(self, name: str, value: bytes) -> None:
        self._need_wr()
        self.attrs[name] = bytes(value)
        self.created = True

    def rmxattr(self, name: str) -> None:
        self._need_wr()
        self.attrs[name] = None

    def map_set_val(self, key: str, value: bytes) -> None:
        """cls_cxx_map_set_val."""
        self._need_wr()
        if self._omap_fn is None:
            raise ClsError(EOPNOTSUPP, "omap on an EC pool")
        self.omap[key] = bytes(value)
        self.created = True

    def map_set_vals(self, kv: dict[str, bytes]) -> None:
        for k, v in kv.items():
            self.map_set_val(k, v)

    def map_remove_key(self, key: str) -> None:
        self._need_wr()
        if self._omap_fn is None:
            raise ClsError(EOPNOTSUPP, "omap on an EC pool")
        self.omap[key] = None

    def map_clear(self) -> None:
        self._need_wr()
        if self._omap_fn is None:
            raise ClsError(EOPNOTSUPP, "omap on an EC pool")
        self.omap_cleared = True
        self.omap.clear()

    def dirty(self) -> bool:
        return (
            bool(self.attrs)
            or bool(self.omap)
            or self.omap_cleared
            or self.data is not None
            or self.created
        )
