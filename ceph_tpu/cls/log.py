"""cls_log — timestamped log entries in an object's omap.

Mirror of src/cls/log/cls_log.cc: RGW's metadata/data logs append
entries keyed `1_<sec>.<usec>_<counter>` into omap; readers page with a
from/to window + marker, and trim deletes a prefix window.  This is also
the first omap-backed class in the tree, exercising the cls_cxx_map_*
surface end to end (the reference's bucket index lives on the same
substrate).

Input/output are JSON blobs (the dynamic shape of the reference's
cls_log_ops.h structs).
"""

from __future__ import annotations

import json

from .objclass import RD, WR, ClsError, HCtx, cls_method
from ..common.errs import EINVAL

MAX_TRIM = 1000  # cls_log trims in bounded chunks, as the reference does


def _ts_prefix(ts: float) -> str:
    sec = int(ts)
    usec = round((ts - sec) * 1e6)
    return f"1_{sec:011d}.{usec:06d}"


def _key(ts: float, counter: int) -> str:
    return f"{_ts_prefix(ts)}_{counter:010d}"


@cls_method("log", "add", WR)
def add(ctx: HCtx, indata: bytes) -> bytes:
    """{"entries": [{"ts": float, "section": str, "name": str,
    "data": str}]} — each entry lands under its timestamp key."""
    req = json.loads(indata.decode())
    entries = req.get("entries")
    if not isinstance(entries, list) or not entries:
        raise ClsError(EINVAL, "no entries")
    # counter disambiguates same-timestamp appends; continue from the
    # current key population so replayed adds keep monotonic keys
    counter = len(ctx.map_get_keys()) if ctx.exists() else 0
    for e in entries:
        key = _key(float(e["ts"]), counter)
        counter += 1
        ctx.map_set_val(key, json.dumps(e).encode())
    return b""


@cls_method("log", "list", RD)
def list_(ctx: HCtx, indata: bytes) -> bytes:
    """{"from": ts, "to": ts, "marker": str, "max": n} ->
    {"entries": [...], "marker": str, "truncated": bool}"""
    req = json.loads(indata.decode() or "{}")
    lo = _key(float(req.get("from", 0)), 0)
    to = req.get("to", 0)
    hi = _key(float(to), 0) if to else "2"  # "2" > every "1_..." key
    marker = req.get("marker", "")
    limit = int(req.get("max", 100))
    omap = ctx.map_get_all()
    keys = sorted(k for k in omap if lo <= k < hi)
    if marker:
        keys = [k for k in keys if k > marker]
    page = keys[:limit]
    out = [json.loads(omap[k].decode()) for k in page]
    return json.dumps(
        {
            "entries": out,
            "marker": page[-1] if page else marker,
            "truncated": len(keys) > limit,
        }
    ).encode()


@cls_method("log", "trim", WR)
def trim(ctx: HCtx, indata: bytes) -> bytes:
    """{"to": ts} — drop entries at or before the timestamp (bounded per
    call; callers loop, as RGW's log trimmer does)."""
    req = json.loads(indata.decode() or "{}")
    pfx = _ts_prefix(float(req.get("to", 0)))
    # "at or before `to`": timestamp-prefix comparison sidesteps float
    # rounding at the boundary (the counter suffix never participates)
    doomed = [
        k for k in ctx.map_get_keys() if k[: len(pfx)] <= pfx
    ][:MAX_TRIM]
    if not doomed:
        from ..common.errs import ENODATA

        raise ClsError(ENODATA, "nothing to trim")
    for k in doomed:
        ctx.map_remove_key(k)
    return b""
