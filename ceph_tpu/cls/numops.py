"""cls_numops — server-side arithmetic on xattr-stored numbers
(src/cls/numops/cls_numops.cc): read-modify-write WITHOUT a client
round trip, the class-family's canonical example."""

from __future__ import annotations

import json

from ..common.errs import EINVAL
from .objclass import RD, WR, ClsError, HCtx, cls_method


def _apply(ctx: HCtx, indata: bytes, op) -> bytes:
    req = json.loads(indata.decode())
    key, operand = req["key"], float(req["value"])
    raw = ctx.getxattr(key)
    try:
        current = float(raw.decode()) if raw else 0.0
    except ValueError:
        raise ClsError(EINVAL, f"xattr {key!r} is not numeric") from None
    result = op(current, operand)
    # integers stay integers (the reference stores decimal strings too)
    if result == int(result):
        result = int(result)
    out = repr(result).encode()
    ctx.setxattr(key, out)
    return out


@cls_method("numops", "add", RD | WR)
def add(ctx: HCtx, indata: bytes) -> bytes:
    return _apply(ctx, indata, lambda a, b: a + b)


@cls_method("numops", "sub", RD | WR)
def sub(ctx: HCtx, indata: bytes) -> bytes:
    return _apply(ctx, indata, lambda a, b: a - b)


@cls_method("numops", "mul", RD | WR)
def mul(ctx: HCtx, indata: bytes) -> bytes:
    return _apply(ctx, indata, lambda a, b: a * b)


@cls_method("numops", "div", RD | WR)
def div(ctx: HCtx, indata: bytes) -> bytes:
    def _div(a: float, b: float) -> float:
        if b == 0:
            raise ClsError(EINVAL, "division by zero")
        return a / b

    return _apply(ctx, indata, _div)
