"""RADOS object classes — mirror of src/objclass + src/cls.

The reference's third plugin family (beside erasure-code and compressor):
shared libraries `libcls_<name>.so` loaded into the OSD register named
METHODS that execute server-side against one object, invoked by clients
through the CEPH_OSD_OP_CALL op (`ioctx.exec(oid, cls, method, in)` ->
(rc, out)).  Methods declare RD/WR flags; WR methods mutate the object
through the op's transaction, so class side effects replicate exactly
like plain writes (PrimaryLogPG::do_osd_ops CALL case).

Here classes are python modules under ceph_tpu.cls registered through
the same decorator surface (`objclass.py`); the dlopen analog is
importlib with a preload list (`osd_op_class_load_list`).  In-tree
classes mirror the reference's most-used ones: `lock` (cls_lock),
`version` (cls_version), `numops` (cls_numops), `refcount`
(cls_refcount).
"""

from .objclass import (
    ClsError,
    HCtx,
    MethodNotFound,
    cls_method,
    get_method,
    load_class,
    registry,
)

__all__ = [
    "ClsError",
    "HCtx",
    "MethodNotFound",
    "cls_method",
    "get_method",
    "load_class",
    "registry",
]
