"""CRUSH map model + rule execution — analog of src/crush/{crush,mapper}.c.

Reference behavior being mirrored (not translated):
- straw2 buckets (crush_bucket_straw2): every item draws
  ln(hash16/2^16)/weight; the largest draw wins, giving weight-proportional
  selection that is stable under weight changes (mapper.c
  bucket_straw2_choose).
- rule execution (crush_do_rule, mapper.c:878): take/choose/chooseleaf
  steps in `firstn` (replication) or `indep` (erasure-code) modes; indep
  keeps failed positions as CRUSH_ITEM_NONE holes rather than shifting
  later replicas — exactly what ECBackend needs for shard identity.
- weight rejection: a device survives only if
  hash16(x, device) < reweight (mapper.c is_out), so "out" OSDs drain
  proportionally.

All math is integer fixed-point so native/crush.cc reproduces identical
placements; the shared log2 table is generated once here and handed to the
native side (tests assert bit-for-bit agreement).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from .hash import M32, crush_hash32, crush_hash32_2, crush_hash32_3

CRUSH_ITEM_NONE = 0x7FFFFFFF

# Fixed-point ln table: LN16[u] = round(log2((u+1)/65536) * 65536), u16 draw
# -> scaled log2 in [-2^20, 0].  The straw2 fixed-point equivalent of the
# reference's crush_ln(); shared with native/crush.cc for determinism.
LN16 = [round(math.log2((u + 1) / 65536.0) * 65536) for u in range(65536)]

WEIGHT_ONE = 0x10000  # 16.16 fixed point, like the reference


def tdiv(a: int, b: int) -> int:
    """C-style truncated integer division (Python // floors)."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


@dataclass
class Bucket:
    """An interior node (id < 0) of the hierarchy (crush.h crush_bucket)."""

    id: int
    type_id: int
    alg: str = "straw2"  # straw2 | uniform
    items: list[int] = field(default_factory=list)
    weights: list[int] = field(default_factory=list)  # 16.16 fixed per item

    @property
    def weight(self) -> int:
        return sum(self.weights)


@dataclass(frozen=True)
class Step:
    """One rule step (crush.h crush_rule_step)."""

    op: str  # take | choose_firstn | choose_indep | chooseleaf_firstn | chooseleaf_indep | emit
    num: int = 0  # 0 => result_max; <0 => result_max + num
    arg: int = 0  # take: bucket id; choose*: type id


@dataclass
class Rule:
    id: int
    name: str
    steps: list[Step] = field(default_factory=list)


@dataclass
class CrushMap:
    """Devices are ids >= 0; buckets ids < 0 (crush.h conventions)."""

    buckets: dict[int, Bucket] = field(default_factory=dict)
    types: dict[int, str] = field(default_factory=dict)
    rules: dict[int, Rule] = field(default_factory=dict)
    choose_total_tries: int = 50  # tunable (mapper.c default 19; generous)

    def max_devices(self) -> int:
        mx = 0
        for b in self.buckets.values():
            for it in b.items:
                if it >= 0:
                    mx = max(mx, it + 1)
        return mx


# --- bucket selection --------------------------------------------------------


def _straw2_choose(bucket: Bucket, x: int, r: int) -> int:
    """Weight-proportional draw (mapper.c bucket_straw2_choose semantics)."""
    best_item = CRUSH_ITEM_NONE
    best_draw = None
    for item, w in zip(bucket.items, bucket.weights):
        if w <= 0:
            continue
        u = crush_hash32_3(x, item & M32, r) & 0xFFFF
        # draw = ln(u) / weight, both 16.16 fixed point; values <= 0 and a
        # larger weight divides the negative ln toward 0 => higher draw.
        draw = tdiv(LN16[u] << 16, w)
        if best_draw is None or draw > best_draw:
            best_draw = draw
            best_item = item
    return best_item


def _uniform_choose(bucket: Bucket, x: int, r: int) -> int:
    if not bucket.items:
        return CRUSH_ITEM_NONE
    return bucket.items[crush_hash32_3(x, bucket.id & M32, r) % len(bucket.items)]


def bucket_choose(bucket: Bucket, x: int, r: int) -> int:
    if bucket.alg == "straw2":
        return _straw2_choose(bucket, x, r)
    if bucket.alg == "uniform":
        return _uniform_choose(bucket, x, r)
    raise ValueError(f"unknown bucket alg {bucket.alg}")


# --- rule execution ----------------------------------------------------------


def _is_out(x: int, device: int, reweights: dict[int, int] | None) -> bool:
    """Reweight rejection (mapper.c is_out): survive with probability
    reweight/0x10000, hashed on (x, device)."""
    if reweights is None:
        return False
    w = reweights.get(device, WEIGHT_ONE)
    if w >= WEIGHT_ONE:
        return False
    if w <= 0:
        return True
    return (crush_hash32_2(x, device) & 0xFFFF) >= w


def _descend(cmap: CrushMap, bucket: Bucket, x: int, r: int, type_wanted: int) -> int:
    """Walk down until reaching a device (type 0) or a bucket of the wanted
    type (the in-loop descent of mapper.c crush_choose_*)."""
    for _ in range(64):  # depth guard
        item = bucket_choose(bucket, x, r)
        if item == CRUSH_ITEM_NONE:
            return CRUSH_ITEM_NONE
        if item >= 0:
            return item if type_wanted == 0 else CRUSH_ITEM_NONE
        child = cmap.buckets.get(item)
        if child is None:
            return CRUSH_ITEM_NONE
        if child.type_id == type_wanted:
            return item
        bucket = child
    return CRUSH_ITEM_NONE


def _leaf_of(
    cmap: CrushMap, item: int, x: int, rleaf: int, reweights: dict[int, int] | None
) -> int:
    """Descend from a chosen failure-domain bucket to one device
    (the chooseleaf second stage)."""
    if item >= 0:
        return CRUSH_ITEM_NONE if _is_out(x, item, reweights) else item
    bucket = cmap.buckets[item]
    dev = _descend(cmap, bucket, x, rleaf, 0)
    if dev == CRUSH_ITEM_NONE or _is_out(x, dev, reweights):
        return CRUSH_ITEM_NONE
    return dev


def _choose(
    cmap: CrushMap,
    parent: Bucket,
    x: int,
    numrep: int,
    type_wanted: int,
    chooseleaf: bool,
    indep: bool,
    reweights: dict[int, int] | None,
) -> list[int]:
    """crush_choose_firstn / crush_choose_indep semantics."""
    out: list[int] = []
    chosen_domains: set[int] = set()
    chosen_devices: set[int] = set()
    tries = cmap.choose_total_tries
    for rep in range(numrep):
        placed = CRUSH_ITEM_NONE
        for ftotal in range(tries):
            # indep strides by numrep so each position explores a disjoint
            # r-sequence and failures leave stable holes; firstn walks r
            # forward (mapper.c r' computation).
            r = rep + ftotal * numrep if indep else rep + ftotal
            item = _descend(cmap, parent, x, r, type_wanted)
            if item == CRUSH_ITEM_NONE:
                continue
            if item in chosen_domains:
                continue  # collision
            if chooseleaf:
                dev = _leaf_of(cmap, item, x, r if indep else ftotal, reweights)
                if dev == CRUSH_ITEM_NONE or dev in chosen_devices:
                    continue
                chosen_domains.add(item)
                chosen_devices.add(dev)
                placed = dev
            else:
                if item >= 0 and _is_out(x, item, reweights):
                    continue
                chosen_domains.add(item)
                if item >= 0:
                    chosen_devices.add(item)
                placed = item
            break
        if placed != CRUSH_ITEM_NONE or indep:
            out.append(placed)
        # firstn skips failed positions entirely (shorter result)
    return out


def do_rule(
    cmap: CrushMap,
    rule_id: int,
    x: int,
    result_max: int,
    reweights: dict[int, int] | None = None,
) -> list[int]:
    """Execute a placement rule (mapper.c crush_do_rule:878)."""
    rule = cmap.rules[rule_id]
    x &= M32
    working: list[int] = []
    result: list[int] = []
    for step in rule.steps:
        if step.op == "take":
            working = [step.arg]
        elif step.op == "emit":
            result.extend(working)
            working = []
        else:
            indep = step.op.endswith("indep")
            chooseleaf = step.op.startswith("chooseleaf")
            numrep = step.num
            if numrep <= 0:
                numrep = max(result_max + numrep, 0)
            if numrep == 0:
                # mapper.c: numrep <= 0 after adjustment chooses nothing
                working = []
                continue
            gathered: list[int] = []
            for w in working:
                parent = cmap.buckets.get(w)
                if parent is None:
                    continue
                gathered.extend(
                    _choose(
                        cmap, parent, x, numrep, step.arg, chooseleaf, indep, reweights
                    )
                )
            working = gathered
    return result[:result_max] if result_max else result
