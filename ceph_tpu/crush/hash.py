"""Jenkins-style 32-bit hashing for CRUSH — analog of src/crush/hash.c.

The reference's rjenkins1 hash family (crush_hash32_*) is Robert Jenkins'
public 96-bit mix specialized to 1-3 word inputs.  This implementation is
written from the published algorithm; what matters for the framework is
determinism and avalanche, and that the C++ twin (native/crush.cc)
produces identical values.
"""

from __future__ import annotations

M32 = 0xFFFFFFFF

# Arbitrary seed constant folded into every hash (hash.c crush_hash_seed).
HASH_SEED = 1315423911


def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    """Jenkins 96-bit mix (public domain lookup2 mixing step)."""
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 13
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 8)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 13
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 12
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 16)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 5
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 3
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 10)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 15
    return a, b, c


def crush_hash32(a: int) -> int:
    a &= M32
    h = (HASH_SEED ^ a) & M32
    x, y = 231232, 1232
    a2, _, h = _mix(a, x, h)
    _, _, h = _mix(y, a2, h)
    return h


def crush_hash32_2(a: int, b: int) -> int:
    a &= M32
    b &= M32
    h = (HASH_SEED ^ a ^ b) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def crush_hash32_3(a: int, b: int, c: int) -> int:
    a &= M32
    b &= M32
    c &= M32
    h = (HASH_SEED ^ a ^ b ^ c) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    return h


def str_hash(s: str | bytes) -> int:
    """Object-name hash (ceph_str_hash_rjenkins analog): fold the bytes
    through the word hash 4 bytes at a time."""
    if isinstance(s, str):
        s = s.encode("utf-8")
    h = crush_hash32(len(s))
    for i in range(0, len(s), 4):
        word = int.from_bytes(s[i : i + 4].ljust(4, b"\x00"), "little")
        h = crush_hash32_2(h, word)
    return h
