"""CRUSH placement — mirror of /root/reference/src/crush.

Deterministic pseudorandom placement: straw2 buckets, firstn/indep rule
execution, weight-based rejection (SURVEY.md §1 row 4).  Kept on the CPU
like the reference keeps it in C (§2.3): placement is latency-bound
integer hashing, not a TPU workload.  The straw2 selection core also has
a native C++ implementation (native/crush.cc) that must agree bit-for-bit
with this Python one (tests/test_crush.py).

All arithmetic is fixed-point integer so Python and C++ agree exactly.
"""

from .crush import (
    CRUSH_ITEM_NONE,
    Bucket,
    CrushMap,
    Rule,
    Step,
    do_rule,
)
from .hash import crush_hash32, crush_hash32_2, crush_hash32_3, str_hash
from .wrapper import CrushWrapper

__all__ = [
    "CRUSH_ITEM_NONE",
    "Bucket",
    "CrushMap",
    "CrushWrapper",
    "Rule",
    "Step",
    "crush_hash32",
    "crush_hash32_2",
    "crush_hash32_3",
    "do_rule",
    "str_hash",
]
