"""ctypes bridge to the native CRUSH core (native/crush.cc).

The Python and C++ straw2 implementations must pick identical winners;
the fixed-point log2 table is generated once in Python (crush.LN16) and
installed into the native library on first use.
"""

from __future__ import annotations

import ctypes

from ..utils import native as _native
from .crush import LN16


def lib() -> ctypes.CDLL | None:
    l = _native.load()
    if l is None:
        return None
    if not l.ceph_tpu_crush_ln_table_set():
        table = (ctypes.c_int32 * len(LN16))(*LN16)
        l.ceph_tpu_crush_set_ln_table(table)
    return l


def straw2_choose_native(x: int, r: int, items: list[int], weights: list[int]) -> int | None:
    """Native straw2 winner; None when the library is unavailable."""
    l = lib()
    if l is None:
        return None
    n = len(items)
    c_items = (ctypes.c_int32 * n)(*items)
    c_weights = (ctypes.c_int32 * n)(*weights)
    return int(l.ceph_tpu_straw2_choose(x, r, c_items, c_weights, n))


def hash32_3_native(a: int, b: int, c: int) -> int | None:
    l = lib()
    if l is None:
        return None
    return int(l.ceph_tpu_crush_hash32_3(a, b, c))
