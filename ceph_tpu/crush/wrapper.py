"""CrushWrapper — analog of src/crush/CrushWrapper.h.

The administrative shell over the raw map: named types, named buckets,
tree construction, and `add_simple_rule` — the call the erasure-code
interface uses to create its `indep` placement rule
(/root/reference/src/erasure-code/ErasureCode.cc:64-82 →
CrushWrapper::add_simple_rule).
"""

from __future__ import annotations

import itertools

from .crush import CRUSH_ITEM_NONE, Bucket, CrushMap, Rule, Step, WEIGHT_ONE, do_rule


class CrushWrapper:
    def __init__(self) -> None:
        self.map = CrushMap()
        self._bucket_names: dict[str, int] = {}
        self._type_names: dict[str, int] = {}
        self._bucket_ids = itertools.count(-1, -1)
        self._rule_ids = itertools.count(0)
        # Conventional type hierarchy (types.yaml-in analog); device is 0.
        for tid, name in enumerate(["osd", "host", "rack", "row", "root"]):
            self.map.types[tid] = name
            self._type_names[name] = tid

    # -- construction --------------------------------------------------------

    def type_id(self, name: str) -> int:
        return self._type_names[name]

    def add_bucket(self, name: str, type_name: str, alg: str = "straw2") -> int:
        if name in self._bucket_names:
            raise ValueError(f"bucket {name} exists")
        bid = next(self._bucket_ids)
        self.map.buckets[bid] = Bucket(bid, self.type_id(type_name), alg)
        self._bucket_names[name] = bid
        return bid

    def bucket_id(self, name: str) -> int:
        return self._bucket_names[name]

    def add_item(self, bucket: int | str, item: int, weight: float = 1.0) -> None:
        """Insert a device or child bucket with a CRUSH weight."""
        if isinstance(bucket, str):
            bucket = self.bucket_id(bucket)
        b = self.map.buckets[bucket]
        b.items.append(item)
        b.weights.append(int(weight * WEIGHT_ONE))

    def build_flat(self, n_osds: int, osds_per_host: int = 1, root: str = "default") -> None:
        """Build root -> host -> osd tree, one weight each — what the
        standalone qa tests' `run_osd` loop effectively produces."""
        self.add_bucket(root, "root")
        for h in range((n_osds + osds_per_host - 1) // osds_per_host):
            hname = f"host{h}"
            hid = self.add_bucket(hname, "host")
            self.add_item(root, hid, 0.0)  # fixed up below
            for o in range(h * osds_per_host, min((h + 1) * osds_per_host, n_osds)):
                self.add_item(hname, o, 1.0)
        # parent weights = sum of children
        rid = self.bucket_id(root)
        rb = self.map.buckets[rid]
        rb.weights = [self.map.buckets[c].weight for c in rb.items]

    # -- rules ---------------------------------------------------------------

    def add_simple_rule(
        self,
        name: str,
        root: str = "default",
        failure_domain: str = "host",
        mode: str = "firstn",
    ) -> int:
        """CrushWrapper::add_simple_rule; EC profiles pass mode=indep."""
        assert mode in ("firstn", "indep")
        rid = next(self._rule_ids)
        steps = [
            Step("take", arg=self.bucket_id(root)),
            Step(f"chooseleaf_{mode}", num=0, arg=self.type_id(failure_domain)),
            Step("emit"),
        ]
        self.map.rules[rid] = Rule(rid, name, steps)
        return rid

    def rule_id(self, name: str) -> int | None:
        for rid, rule in self.map.rules.items():
            if rule.name == name:
                return rid
        return None

    # -- execution -----------------------------------------------------------

    def do_rule(
        self,
        rule_id: int,
        x: int,
        result_max: int,
        reweights: dict[int, int] | None = None,
    ) -> list[int]:
        return do_rule(self.map, rule_id, x, result_max, reweights)

    # -- encoding (owned here so wrapper internals stay private) -------------

    def encode(self, enc) -> None:
        cmap = self.map
        enc.u32(cmap.choose_total_tries)
        enc.map_(
            cmap.buckets,
            lambda e, k: e.i64(k),
            lambda e, b: (
                e.u32(b.type_id),
                e.string(b.alg),
                e.list_(b.items, lambda e2, i: e2.i64(i)),
                e.list_(b.weights, lambda e2, w: e2.i64(w)),
            ),
        )
        enc.map_(cmap.types, lambda e, k: e.u32(k), lambda e, v: e.string(v))
        enc.map_(
            cmap.rules,
            lambda e, k: e.u32(k),
            lambda e, r: (
                e.string(r.name),
                e.list_(
                    r.steps,
                    lambda e2, s: (e2.string(s.op), e2.i64(s.num), e2.i64(s.arg)),
                ),
            ),
        )
        enc.map_(
            self._bucket_names, lambda e, k: e.string(k), lambda e, v: e.i64(v)
        )

    @classmethod
    def decode(cls, dec) -> "CrushWrapper":
        cw = cls()
        cmap = CrushMap()
        cmap.choose_total_tries = dec.u32()
        cmap.buckets = dec.map_(
            lambda d: d.i64(),
            lambda d: Bucket(
                id=0,  # fixed below from the map key
                type_id=d.u32(),
                alg=d.string(),
                items=d.list_(lambda d2: d2.i64()),
                weights=d.list_(lambda d2: d2.i64()),
            ),
        )
        for bid, b in cmap.buckets.items():
            b.id = bid
        cmap.types = dec.map_(lambda d: d.u32(), lambda d: d.string())
        cmap.rules = dec.map_(
            lambda d: d.u32(),
            lambda d: Rule(
                id=0,
                name=d.string(),
                steps=d.list_(
                    lambda d2: Step(op=d2.string(), num=d2.i64(), arg=d2.i64())
                ),
            ),
        )
        for rid, r in cmap.rules.items():
            r.id = rid
        cw.map = cmap
        cw._bucket_names = dec.map_(lambda d: d.string(), lambda d: d.i64())
        cw._type_names = {v: k for k, v in cmap.types.items()}
        cw._bucket_ids = itertools.count(min(cmap.buckets, default=0) - 1, -1)
        cw._rule_ids = itertools.count(max(cmap.rules, default=-1) + 1)
        return cw
