"""crc32c (Castagnoli) — chunk integrity digests.

The reference tracks per-shard cumulative crc32c in the `hinfo` xattr
(/root/reference/src/osd/ECUtil.h:101-160) and verifies it on every sub-read
(ECBackend.cc:1023-1156).  Hot path is the native SSE4.2 implementation
(native/crc32c.cc via ctypes); the pure-Python table fallback keeps
correctness on toolchain-less hosts.
"""

from __future__ import annotations

import numpy as np

from .native import load as _load_native

_POLY = 0x82F63B78  # reflected Castagnoli


def _build_table() -> np.ndarray:
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table[i] = c
    return table


_TABLE = _build_table()


def _crc32c_py(crc: int, data: bytes) -> int:
    c = crc ^ 0xFFFFFFFF
    for b in data:
        c = int(_TABLE[(c ^ b) & 0xFF]) ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def crc32c(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Cumulative crc32c; pass the previous digest to chain appends."""
    if isinstance(data, np.ndarray):
        data = np.ascontiguousarray(data, dtype=np.uint8).tobytes()
    lib = _load_native()
    if lib is not None:
        return int(lib.ceph_tpu_crc32c(crc, data, len(data)))
    return _crc32c_py(crc, data)


def hw_available() -> bool:
    lib = _load_native()
    return bool(lib and lib.ceph_tpu_crc32c_hw_available())
