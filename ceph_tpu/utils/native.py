"""ctypes loader for the native runtime library (native/).

Builds libceph_tpu_native.so on first use if the toolchain is available and
the artifact is missing/stale; callers degrade gracefully to pure-Python
fallbacks when neither a binary nor a compiler exists.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
import threading

from ceph_tpu.common.lockdep import make_lock

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libceph_tpu_native.so"

_lock = make_lock("native_bindings")
_lib: ctypes.CDLL | None = None
_load_failed = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def load() -> ctypes.CDLL | None:
    """The native library, building it on demand; None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        sources_newer = False
        if _LIB_PATH.exists():
            lib_mtime = _LIB_PATH.stat().st_mtime
            sources_newer = any(
                src.stat().st_mtime > lib_mtime
                for src in _NATIVE_DIR.glob("*.cc")
            )
        if (not _LIB_PATH.exists() or sources_newer) and not _build():
            if not _LIB_PATH.exists():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
            lib.ceph_tpu_crc32c.restype = ctypes.c_uint32
            lib.ceph_tpu_crc32c.argtypes = [
                ctypes.c_uint32,
                ctypes.c_char_p,
                ctypes.c_size_t,
            ]
            lib.ceph_tpu_crc32c_hw_available.restype = ctypes.c_int
            lib.ceph_tpu_crush_hash32.restype = ctypes.c_uint32
            lib.ceph_tpu_crush_hash32.argtypes = [ctypes.c_uint32]
            lib.ceph_tpu_crush_hash32_2.restype = ctypes.c_uint32
            lib.ceph_tpu_crush_hash32_2.argtypes = [ctypes.c_uint32] * 2
            lib.ceph_tpu_crush_hash32_3.restype = ctypes.c_uint32
            lib.ceph_tpu_crush_hash32_3.argtypes = [ctypes.c_uint32] * 3
            lib.ceph_tpu_crush_set_ln_table.restype = None
            lib.ceph_tpu_crush_set_ln_table.argtypes = [
                ctypes.POINTER(ctypes.c_int32)
            ]
            lib.ceph_tpu_crush_ln_table_set.restype = ctypes.c_int
            lib.ceph_tpu_straw2_choose.restype = ctypes.c_int32
            lib.ceph_tpu_straw2_choose.argtypes = [
                ctypes.c_uint32,
                ctypes.c_uint32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
            ]
            _lib = lib
        except (OSError, AttributeError):
            # AttributeError: a stale prebuilt .so lacking newer symbols —
            # degrade to the pure-Python fallbacks like any other failure.
            _load_failed = True
        return _lib
