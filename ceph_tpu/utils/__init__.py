"""Shared utilities: native bindings, integrity digests."""

from .crc32c import crc32c, hw_available

__all__ = ["crc32c", "hw_available"]
