"""Leveled subsystem logging — mirror of src/log + dout.

Reference: /root/reference/src/log/Log.h:32 (async log thread draining a
queue, in-memory ring of recent entries for crash dump),
src/log/SubsystemMap.h (per-subsystem log/gather levels 0-30), and the
`dout(n)` macro family (src/common/dout.h): a statement is *gathered* when
level <= gather_level (kept in the ring) and *emitted* when
level <= log_level.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from dataclasses import dataclass

from .lockdep import make_lock


@dataclass
class LogEntry:
    stamp: float
    thread: int
    subsys: str
    level: int
    msg: str

    def format(self) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(self.stamp))
        frac = int((self.stamp % 1) * 1e6)
        return f"{ts}.{frac:06d} {self.thread:#x} {self.level:2d} {self.subsys}: {self.msg}"


class SubsystemMap:
    """Per-subsystem (log_level, gather_level) — SubsystemMap.h."""

    DEFAULT = (1, 5)

    def __init__(self) -> None:
        self._levels: dict[str, tuple[int, int]] = {}

    def set_log_level(self, subsys: str, log: int, gather: int | None = None) -> None:
        self._levels[subsys] = (log, gather if gather is not None else max(log, 5))

    def levels(self, subsys: str) -> tuple[int, int]:
        return self._levels.get(subsys, self.DEFAULT)

    def should_gather(self, subsys: str, level: int) -> bool:
        log, gather = self.levels(subsys)
        return level <= max(log, gather)


class Log:
    """Async log sink with a bounded recent-entry ring (Log.h:32).

    Entries are queued by producers and drained by a background thread;
    `dump_recent()` returns the ring (the crash-dump path the reference
    writes on assert failure).
    """

    def __init__(self, path: str = "", max_recent: int = 500):
        self._path = path
        self._queue: collections.deque[LogEntry] = collections.deque()
        self._recent: collections.deque[LogEntry] = collections.deque(maxlen=max_recent)
        self._cond = threading.Condition(make_lock("log_sink"))
        self._stop = False
        self._file = None
        if path:
            self._file = open(path, "a", buffering=1)
        self._thread = threading.Thread(target=self._drain, name="log", daemon=True)
        self._thread.start()

    def submit(self, entry: LogEntry, emit: bool) -> None:
        with self._cond:
            self._recent.append(entry)
            if emit:
                self._queue.append(entry)
                self._cond.notify()

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(timeout=0.5)
                if self._stop and not self._queue:
                    return
                batch = list(self._queue)
                self._queue.clear()
            out = self._file if self._file is not None else sys.stderr
            for e in batch:
                print(e.format(), file=out)

    def flush(self) -> None:
        with self._cond:
            batch = list(self._queue)
            self._queue.clear()
        out = self._file if self._file is not None else sys.stderr
        for e in batch:
            print(e.format(), file=out)

    def dump_recent(self) -> list[str]:
        with self._cond:
            return [e.format() for e in self._recent]

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout=2)
        if self._thread.is_alive():
            # Drain thread is wedged on a slow sink; leave the file open so
            # its in-progress writes don't hit a closed handle.
            return
        if self._file is not None:
            self._file.close()
            self._file = None


class LogClient:
    """The `dout` front end bound to a SubsystemMap + Log sink."""

    def __init__(self, log: Log | None = None, subsys_map: SubsystemMap | None = None):
        self.log = log or Log()
        self.subsys = subsys_map or SubsystemMap()

    @classmethod
    def from_config(cls, cfg) -> "LogClient":
        """Build from a Config: debug_* options + log_file."""
        sm = SubsystemMap()
        from .options import OPTIONS

        for name in OPTIONS:
            if name.startswith("debug_"):
                log_lvl, gather = cfg.debug_levels(name[len("debug_"):])
                sm.set_log_level(name[len("debug_"):], log_lvl, gather)
        return cls(
            Log(str(cfg.get("log_file")), int(cfg.get("log_max_recent"))), sm
        )

    def dout(self, subsys: str, level: int, msg: str) -> None:
        log_lvl, gather = self.subsys.levels(subsys)
        emit = level <= log_lvl
        if not emit and level > gather:
            return
        self.log.submit(
            LogEntry(time.time(), threading.get_ident(), subsys, level, msg),
            emit,
        )

    def derr(self, subsys: str, msg: str) -> None:
        self.dout(subsys, 0, msg)


# Process-wide default client (the reference's g_ceph_context->_log).
_default: LogClient | None = None
_default_lock = make_lock("log_default")


def default_client() -> LogClient:
    global _default
    with _default_lock:
        if _default is None:
            _default = LogClient()
            if os.environ.get("CEPH_TPU_DEBUG"):
                for sub in ("osd", "mon", "ms", "ec", "objecter", "paxos"):
                    _default.subsys.set_log_level(sub, 20, 20)
        return _default


def dout(subsys: str, level: int, msg: str) -> None:
    default_client().dout(subsys, level, msg)
