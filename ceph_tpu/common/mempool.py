"""HBM mempool ledger — unified device/host memory accounting (ISSUE 13).

Every lever built for the per-chip throughput push holds TPU HBM — the
donation pool's refcounted output buffers, the depth-N pipeline's
in-flight ring, the device-resident chunk cache, sharded placements —
yet until this module nothing answered "how many bytes are resident on
the device right now, held by whom, and are we about to OOM?".  The
reference treats this as a first-class subsystem
(src/include/mempool.h: per-pool byte/object accounting behind
``dump_mempools``, sharded by type in debug mode, plus
``osd_memory_target``/PriorityCache arbitrating cache sizes under one
budget); this is the HBM-native twin.

Design:

- A lock-cheap registry of named pools.  The EC data path's pools are
  predeclared (:data:`POOLS`); unknown names create pools on demand so
  new subsystems need no registry edit.
- RAII-style :class:`MempoolHandle` accounts allocate/resize/free.
  ``alloc(pool, nbytes, buf=...)`` optionally ties the handle to a
  device buffer with ``weakref.finalize`` — if the owning structure is
  dropped without an explicit ``free()``, the buffer's death still
  closes the books (``free`` is idempotent, so explicit + finalizer
  double-frees are safe).  :func:`track_buffer` is the fire-and-forget
  spelling for transient placements: account now, auto-free at GC.
- ``ec_tpu_mempool_debug`` shards counts by allocation call-site, like
  the reference's mempool debug mode — ``dump_mempools`` then shows
  which line of code holds the bytes.
- Reconciliation: pool counters are incremental, but every open handle
  is also registered, so :meth:`MempoolLedger.reconcile` can recompute
  live bytes from first principles and expose counter drift — the bug
  class the device-cache cap-shrink fix in this PR is about.

Pressure (``ec_tpu_hbm_target_bytes``, 0 = off): the ratio of total
resident bytes to the target drives a staged response — first trim the
device-resident chunk cache, then cap donation-pool retention, then
clamp the effective pipeline depth to 1 — and raises the
``TPU_HBM_PRESSURE`` HEALTH_WARN through the OSD status → mgr digest →
mon pipeline, clearing (and releasing the caps) on relief.  The lock is
never held across a trim call: pool/cache locks may nest INTO the
ledger lock, so the ledger lock stays a leaf.
"""

from __future__ import annotations

import sys
import time
import weakref
from collections import deque

from ceph_tpu.common.lockdep import make_rlock

# The EC data path's predeclared pools.  Holders:
#   ec_donation          codec/matrix_codec.DonationPool free buffers
#   ec_pipeline_inflight encode/decode launch outputs dispatched, unsettled
#   device_cache         ops/device_cache.DeviceChunkCache entries
#   sharded_placement    parallel/sharded.py NamedSharding device_puts
#   verify               VerifyAggregator in-flight mismatch bitmaps
#   scratch              plan-cache bit matrices + bench staging
POOLS = (
    "ec_donation",
    "ec_pipeline_inflight",
    "device_cache",
    "sharded_placement",
    "verify",
    "scratch",
)

# Pressure staging thresholds (ratio = total resident / target):
# at PRESSURE_RAISE the cache is trimmed back toward PRESSURE_RAISE of
# the target; still over PRESSURE_DONATION_CAP afterwards caps
# donation-pool retention; still over PRESSURE_DEPTH_CLAMP clamps the
# effective pipeline depth to 1.  The raised state clears (and the caps
# release) only under PRESSURE_CLEAR — hysteresis so the health check
# doesn't flap at the boundary.
PRESSURE_RAISE = 0.85
PRESSURE_DONATION_CAP = 0.95
PRESSURE_DEPTH_CLAMP = 1.0
PRESSURE_CLEAR = 0.70

# maybe_check_pressure() evaluates at most this often (hot-path guard)
_PRESSURE_CHECK_INTERVAL_S = 0.05

_STAGE_NAMES = {0: "none", 1: "cache-trim", 2: "donation-cap", 3: "depth-clamp"}


class _PoolStats:
    __slots__ = ("bytes", "buffers", "peak_bytes", "peak_buffers")

    def __init__(self) -> None:
        self.bytes = 0
        self.buffers = 0
        self.peak_bytes = 0
        self.peak_buffers = 0


class MempoolHandle:
    """One accounted allocation.  ``free()`` is idempotent — explicit
    release and the optional buffer finalizer may both fire."""

    __slots__ = ("_ledger", "pool", "nbytes", "site", "devices", "_open",
                 "_fin")

    def __init__(self, ledger: "MempoolLedger", pool: str, nbytes: int,
                 site: str, devices: tuple[str, ...]):
        self._ledger = ledger
        self.pool = pool
        self.nbytes = int(nbytes)
        self.site = site
        self.devices = devices
        self._open = True
        self._fin = None  # the buffer finalizer, detached on free

    def resize(self, nbytes: int) -> None:
        self._ledger._resize(self, int(nbytes))

    def free(self) -> None:
        self._ledger._free(self)


def _buf_devices(buf) -> tuple[str, ...]:
    """Stable per-device keys for a jax array's placement (the per-device
    breakdown); best-effort — accounting must never fail an allocation."""
    try:
        devs = getattr(buf, "sharding", None)
        devs = devs.device_set if devs is not None else buf.devices()
        return tuple(sorted(f"{d.platform}:{d.id}" for d in devs))
    except (AttributeError, TypeError):
        return ()  # not a placed jax array: lands on "unplaced"


def _call_site(skip: int = 2) -> str:
    """file:line of the nearest caller outside this module (the debug
    shard key)."""
    f = sys._getframe(skip)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno}"


class MempoolLedger:
    """Process-wide registry of named pools with pressure staging."""

    def __init__(self, debug: bool = False, target_bytes: int = 0):
        # REENTRANT: the buffer finalizers free handles through this
        # lock, and a cyclic-GC pass can fire a finalizer at any
        # allocation — including inside alloc/_resize while this thread
        # already holds the lock.  A plain lock would self-deadlock the
        # moment GC collects a tracked buffer under an accounting call.
        self._lock = make_rlock("mempool")
        # serializes whole pressure evaluations (read ratio → trim →
        # apply flags): two racing check_pressure calls interleaving
        # their flag writes could otherwise leave the caps armed with
        # the raised state cleared — retention silently disabled with
        # no health check to say so.  Ordering: this lock is OUTERMOST
        # (trims take aggregator/cache locks, which nest into the
        # counter lock above); nothing acquires it while holding any
        # other lock.
        self._pressure_lock = make_rlock("mempool_pressure")
        # handles whose buffers died in GC context, awaiting a free.
        # Buffer finalizers run INSIDE garbage collection — which can
        # strike while this thread is inside ANY lock's bookkeeping
        # (under lockdep every instrumented acquire shares one plain
        # registry mutex, and its critical sections allocate) — so a
        # finalizer must never acquire a lock.  It appends here
        # (deque.append is atomic, lock-free) and the next accounting
        # call drains in normal context.
        self._deferred: deque[MempoolHandle] = deque()
        self._pools: dict[str, _PoolStats] = {p: _PoolStats() for p in POOLS}
        self._handles: dict[int, MempoolHandle] = {}
        self._by_site: dict[tuple[str, str], list[int]] = {}
        self._total = 0
        self._total_peak = 0
        self.debug = bool(debug)
        self.target_bytes = int(target_bytes)
        # pressure state (hysteresis: sticky until ratio < PRESSURE_CLEAR)
        self._pressure_raised = False
        self._pressure_stage = 0
        self.donation_capped = False
        self.depth_clamped = False
        self._last_pressure_check = 0.0
        self._actions = {
            "cache_trimmed_bytes": 0,
            "donation_dropped_bytes": 0,
            "depth_clamps": 0,
            "raises": 0,
            "clears": 0,
        }

    # -- configuration -------------------------------------------------------

    def configure(self, debug: bool | None = None,
                  target_bytes: int | None = None) -> None:
        """Apply live config (`ec_tpu_mempool_debug` /
        `ec_tpu_hbm_target_bytes` observers)."""
        if debug is not None:
            self.debug = bool(debug)
        if target_bytes is not None:
            with self._lock:
                self.target_bytes = int(target_bytes)

    # -- accounting ----------------------------------------------------------

    def alloc(self, pool: str, nbytes: int, buf=None,
              site: str | None = None) -> MempoolHandle:
        """Account one allocation; returns its RAII handle.  When `buf`
        is given, a ``weakref.finalize`` ties the handle's free to the
        buffer's death, so an owner dropped without cleanup cannot leak
        ledger bytes (free is idempotent, double-release is safe)."""
        self._drain_deferred()  # close dead books before opening new ones
        if site is None:
            site = _call_site() if self.debug else ""
        devices = _buf_devices(buf) if buf is not None else ()
        h = MempoolHandle(self, pool, max(0, int(nbytes)), site, devices)
        with self._lock:
            st = self._pools.get(pool)
            if st is None:
                st = self._pools[pool] = _PoolStats()
            st.bytes += h.nbytes
            st.buffers += 1
            st.peak_bytes = max(st.peak_bytes, st.bytes)
            st.peak_buffers = max(st.peak_buffers, st.buffers)
            self._total += h.nbytes
            self._total_peak = max(self._total_peak, self._total)
            self._handles[id(h)] = h
            if h.site:
                self._by_site.setdefault((pool, h.site), [0, 0])
                self._by_site[(pool, h.site)][0] += h.nbytes
                self._by_site[(pool, h.site)][1] += 1
        if buf is not None:
            try:
                # defer, never free inline: the finalizer fires in GC
                # context, where taking any lock can self-deadlock the
                # interrupted thread (see _deferred).  Kept on the
                # handle so an explicit free can DETACH it — a recycled
                # buffer (the donation pool's whole point) must not
                # accumulate one dead registration per cycle.
                h._fin = weakref.finalize(buf, self._deferred.append, h)
            except TypeError:
                pass  # not weakref-able: explicit free only
        return h

    def _drain_deferred(self) -> None:
        """Close the books on buffers whose finalizers fired in GC
        context.  Called (cheap when empty) at the top of every
        accounting read; popleft hands each handle to exactly one
        drainer, and free is idempotent against a racing explicit
        free."""
        while self._deferred:
            try:
                h = self._deferred.popleft()
            except IndexError:
                return
            self._free(h)

    def _resize(self, h: MempoolHandle, nbytes: int) -> None:
        with self._lock:
            if not h._open:
                return
            delta = nbytes - h.nbytes
            st = self._pools[h.pool]
            st.bytes += delta
            st.peak_bytes = max(st.peak_bytes, st.bytes)
            self._total += delta
            self._total_peak = max(self._total_peak, self._total)
            if h.site:
                self._by_site[(h.pool, h.site)][0] += delta
            h.nbytes = nbytes

    def _free(self, h: MempoolHandle) -> None:
        fin, h._fin = h._fin, None
        if fin is not None:
            # unregister the buffer finalizer: a recycled buffer (the
            # donation pool recycles by design) must not pin one dead
            # handle + registration per accounting cycle for its whole
            # lifetime.  No-op when the finalizer already fired.
            fin.detach()
        with self._lock:
            if not h._open:
                return
            h._open = False
            st = self._pools[h.pool]
            st.bytes -= h.nbytes
            st.buffers -= 1
            self._total -= h.nbytes
            self._handles.pop(id(h), None)
            if h.site:
                rec = self._by_site.get((h.pool, h.site))
                if rec is not None:
                    rec[0] -= h.nbytes
                    rec[1] -= 1
                    if rec[1] <= 0 and rec[0] <= 0:
                        del self._by_site[(h.pool, h.site)]

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict[str, dict[str, int]]:
        """JSON-safe per-pool counters (the OSD status blob's
        ``hbm_mempools`` slice and the prometheus family source)."""
        self._drain_deferred()
        with self._lock:
            return {
                name: {
                    "bytes": st.bytes,
                    "buffers": st.buffers,
                    "peak_bytes": st.peak_bytes,
                    "peak_buffers": st.peak_buffers,
                }
                for name, st in sorted(self._pools.items())
            }

    def current_bytes(self, pool: str) -> int:
        self._drain_deferred()
        with self._lock:
            st = self._pools.get(pool)
            return st.bytes if st is not None else 0

    def total_device_bytes(self) -> int:
        self._drain_deferred()
        with self._lock:
            return self._total

    def peak_total_bytes(self) -> int:
        self._drain_deferred()
        with self._lock:
            return self._total_peak

    def per_device(self) -> dict[str, int]:
        """Resident bytes per device, from each handle's placement
        (buffers with unknown placement land on "unplaced")."""
        self._drain_deferred()
        out: dict[str, int] = {}
        with self._lock:
            # list(): a reentrant finalizer (GC during this loop's
            # allocations) may pop handles mid-iteration
            for h in list(self._handles.values()):
                devs = h.devices or ("unplaced",)
                share, rem = divmod(h.nbytes, len(devs))
                for i, d in enumerate(devs):
                    # the remainder lands on the first device so the
                    # breakdown still sums to total_bytes exactly
                    out[d] = out.get(d, 0) + share + (rem if i == 0 else 0)
        return out

    def reconcile(self) -> dict[str, dict[str, int]]:
        """Recompute per-pool live bytes/buffers from the open-handle
        registry and diff against the incremental counters.  Nonzero
        drift means counter arithmetic went wrong somewhere — exactly
        the bug shape the device-cache cap-shrink fix addresses."""
        self._drain_deferred()
        with self._lock:
            live_bytes: dict[str, int] = {}
            live_buffers: dict[str, int] = {}
            for h in list(self._handles.values()):
                live_bytes[h.pool] = live_bytes.get(h.pool, 0) + h.nbytes
                live_buffers[h.pool] = live_buffers.get(h.pool, 0) + 1
            out = {}
            for name, st in sorted(self._pools.items()):
                lb = live_bytes.get(name, 0)
                out[name] = {
                    "ledger_bytes": st.bytes,
                    "live_bytes": lb,
                    "drift": st.bytes - lb,
                    "ledger_buffers": st.buffers,
                    "live_buffers": live_buffers.get(name, 0),
                }
            return out

    def reset_peaks(self) -> None:
        """Rebase peaks to the current levels (asok ``dump_mempools
        reset_peaks``; bench stages measuring per-depth headroom)."""
        self._drain_deferred()
        with self._lock:
            for st in self._pools.values():
                st.peak_bytes = st.bytes
                st.peak_buffers = st.buffers
            self._total_peak = self._total

    def dump(self) -> dict:
        """The asok ``dump_mempools`` payload."""
        out = {
            "pools": self.snapshot(),
            "total_bytes": self.total_device_bytes(),
            "total_peak_bytes": self.peak_total_bytes(),
            "by_device": self.per_device(),
            "debug": self.debug,
            "pressure": self.pressure_status(),
        }
        if self.debug:
            with self._lock:
                out["by_site"] = {
                    f"{pool}@{site}": {"bytes": rec[0], "buffers": rec[1]}
                    for (pool, site), rec in sorted(self._by_site.items())
                }
        return out

    # -- pressure ------------------------------------------------------------

    def pressure_status(self) -> dict:
        """The current pressure verdict WITHOUT evaluating/trimming
        (dump paths; check_pressure is the mutating evaluation)."""
        with self._lock:
            target = self.target_bytes
            total = self._total
            ratio = (total / target) if target > 0 else 0.0
            return {
                "target_bytes": target,
                "total_bytes": total,
                "ratio": round(ratio, 4),
                "pressure": self._pressure_raised,
                "stage": self._pressure_stage,
                "stage_name": _STAGE_NAMES[self._pressure_stage],
                "donation_capped": self.donation_capped,
                "depth_clamped": self.depth_clamped,
                "actions": dict(self._actions),
                "pools": {
                    name: st.bytes
                    for name, st in sorted(self._pools.items())
                    if st.bytes
                },
            }

    def maybe_check_pressure(self) -> None:
        """Hot-path hook (aggregator submits): evaluate at most every
        _PRESSURE_CHECK_INTERVAL_S, and only when a target is set."""
        if self.target_bytes <= 0:
            return
        now = time.monotonic()
        if now - self._last_pressure_check < _PRESSURE_CHECK_INTERVAL_S:
            return
        self._last_pressure_check = now
        self.check_pressure()

    def check_pressure(self) -> dict:
        """Evaluate the pressure ratio and apply the staged response:
        trim the device cache back toward the raise threshold, then cap
        donation-pool retention, then clamp the effective pipeline
        depth.  Raised state (and the caps) persist until the ratio
        drops under PRESSURE_CLEAR.  The whole read-evaluate-apply
        sequence holds the (outermost) pressure lock so concurrent
        evaluations cannot interleave their flag writes; trims run with
        NO counter lock held (pool/cache locks nest into the counter
        lock, never the other way)."""
        with self._pressure_lock:
            return self._check_pressure_locked()

    def _check_pressure_locked(self) -> dict:
        self._drain_deferred()  # never raise/trim on already-dead bytes
        with self._lock:
            target = self.target_bytes
            total = self._total
        if target <= 0:
            self._clear_pressure(disabled=True)
            return self.pressure_status()
        ratio = total / target
        if ratio >= PRESSURE_RAISE:
            with self._lock:
                if not self._pressure_raised:
                    self._pressure_raised = True
                    self._actions["raises"] += 1
                stage = max(1, self._pressure_stage)
            # stage 1: trim the device-resident chunk cache back toward
            # the raise threshold — cached chunks are pure rebuildable
            # optimization, the cheapest bytes to give back
            excess = total - int(PRESSURE_RAISE * target)
            if excess > 0:
                freed = self._trim_device_cache(excess)
                if freed:
                    with self._lock:
                        self._actions["cache_trimmed_bytes"] += freed
            total = self.total_device_bytes()
            if total / target >= PRESSURE_DONATION_CAP:
                # stage 2: stop retaining dead output buffers — the
                # donation pool trades allocation churn for resident
                # bytes, the wrong trade under pressure
                stage = max(2, stage)
                self.donation_capped = True
                freed = self._drop_donation_retention()
                if freed:
                    with self._lock:
                        self._actions["donation_dropped_bytes"] += freed
                total = self.total_device_bytes()
            if total / target >= PRESSURE_DEPTH_CLAMP:
                # stage 3: clamp the effective pipeline depth to 1 — no
                # more than one launch's output in flight, trading the
                # H2D/kernel overlap for bounded residency
                stage = 3
                if not self.depth_clamped:
                    self.depth_clamped = True
                    with self._lock:
                        self._actions["depth_clamps"] += 1
            with self._lock:
                self._pressure_stage = max(self._pressure_stage, stage)
        elif ratio < PRESSURE_CLEAR:
            self._clear_pressure()
        # between CLEAR and RAISE: hysteresis — keep the current stage
        return self.pressure_status()

    def _clear_pressure(self, disabled: bool = False) -> None:
        with self._lock:
            was = self._pressure_raised
            self._pressure_raised = False
            self._pressure_stage = 0
            self.donation_capped = False
            self.depth_clamped = False
            if was and not disabled:
                self._actions["clears"] += 1

    @staticmethod
    def _trim_device_cache(excess: int) -> int:
        try:
            from ceph_tpu.ops.device_cache import device_chunk_cache

            return device_chunk_cache().trim_for_pressure(excess)
        except Exception as e:
            from ceph_tpu.common.log import dout

            dout("osd", 1, f"mempool: device-cache trim failed: {e!r}")
            return 0

    @staticmethod
    def _drop_donation_retention() -> int:
        try:
            from ceph_tpu.codec.matrix_codec import drop_donation_retention

            return drop_donation_retention()
        except Exception as e:
            from ceph_tpu.common.log import dout

            dout("osd", 1, f"mempool: donation-pool drop failed: {e!r}")
            return 0


_LEDGER: MempoolLedger | None = None


def ledger() -> MempoolLedger:
    """The process-wide ledger, built lazily from option defaults like
    the device guard and the default aggregators; daemons with a live
    Config re-bind the knobs through their runtime observers."""
    global _LEDGER
    if _LEDGER is None:
        from ceph_tpu.common.options import OPTIONS

        _LEDGER = MempoolLedger(
            debug=bool(OPTIONS["ec_tpu_mempool_debug"].default),
            target_bytes=int(OPTIONS["ec_tpu_hbm_target_bytes"].default),
        )
    return _LEDGER


def track_buffer(buf, pool: str = "scratch", site: str | None = None):
    """Fire-and-forget accounting for a transient device buffer: charge
    `pool` now, release automatically when the buffer is GC'd.  Host
    numpy arrays and zero-byte values pass through untracked — the
    ledger meters device residency, not host staging."""
    import numpy as np

    nbytes = int(getattr(buf, "nbytes", 0) or 0)
    if nbytes <= 0 or isinstance(buf, np.ndarray):
        return buf
    try:
        weakref.ref(buf)
    except TypeError:
        return buf  # not weakref-able (python scalars): nothing to meter
    ledger().alloc(pool, nbytes, buf=buf, site=site)
    return buf
