"""Fault injection — mirror of src/common/fault_injector.h.

Reference: /root/reference/src/common/fault_injector.h:57 (FaultInjector<T>:
named injection points that can be armed to fail with an errno or abort)
plus the messenger's probabilistic injections
(`ms_inject_socket_failures`, global.yaml.in:1240) and
`heartbeat_inject_failure` (:865).  Used by tests to drive the EIO /
corruption / connection-loss paths the qa suites exercise
(qa/standalone/erasure-code/test-erasure-eio.sh).
"""

from __future__ import annotations

import random
import threading


class InjectedFailure(Exception):
    def __init__(self, point: str, err: int):
        self.point = point
        self.errno = -abs(err)
        super().__init__(f"injected failure at {point} (errno {self.errno})")


class FaultInjector:
    """Named injection points, armed per-point with an errno and an
    optional remaining-hits budget."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._points: dict[str, tuple[int, int]] = {}  # name -> (errno, hits)
        self._probabilistic: dict[str, float] = {}  # name -> probability
        self._rng = random.Random(0xEC)

    def inject(self, point: str, err: int, hits: int = -1) -> None:
        """Arm: next `hits` checks at `point` raise (hits<0 = forever)."""
        with self._lock:
            self._points[point] = (err, hits)

    def inject_probabilistic(self, point: str, one_in: int) -> None:
        """1-in-N failure chance (ms_inject_socket_failures semantics)."""
        with self._lock:
            if one_in <= 0:
                self._probabilistic.pop(point, None)
            else:
                self._probabilistic[point] = 1.0 / one_in

    def clear(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._points.clear()
                self._probabilistic.clear()
            else:
                self._points.pop(point, None)
                self._probabilistic.pop(point, None)

    def check(self, point: str) -> None:
        """Call at the injection point; raises InjectedFailure if armed."""
        with self._lock:
            armed = self._points.get(point)
            if armed is not None:
                err, hits = armed
                if hits > 0:
                    hits -= 1
                    if hits == 0:
                        del self._points[point]
                    else:
                        self._points[point] = (err, hits)
                raise InjectedFailure(point, err)
            p = self._probabilistic.get(point)
            if p is not None and self._rng.random() < p:
                raise InjectedFailure(point, 5)  # EIO

    def armed(self, point: str) -> bool:
        with self._lock:
            return point in self._points or point in self._probabilistic


# Process-wide injector used by daemons when none is passed explicitly.
_global = FaultInjector()


def global_injector() -> FaultInjector:
    return _global
