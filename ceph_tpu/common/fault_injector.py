"""Fault injection — mirror of src/common/fault_injector.h.

Reference: /root/reference/src/common/fault_injector.h:57 (FaultInjector<T>:
named injection points that can be armed to fail with an errno or abort)
plus the messenger's probabilistic injections
(`ms_inject_socket_failures`, global.yaml.in:1240) and
`heartbeat_inject_failure` (:865).  Used by tests to drive the EIO /
corruption / connection-loss paths the qa suites exercise
(qa/standalone/erasure-code/test-erasure-eio.sh).
"""

from __future__ import annotations

import random
import threading

from .lockdep import make_lock


class InjectedFailure(Exception):
    def __init__(self, point: str, err: int):
        self.point = point
        self.errno = -abs(err)
        super().__init__(f"injected failure at {point} (errno {self.errno})")


class FaultInjector:
    """Named injection points, armed per-point with an errno and an
    optional remaining-hits budget."""

    def __init__(self) -> None:
        self._lock = make_lock("fault_injector")
        self._points: dict[str, tuple[int, int]] = {}  # name -> (errno, hits)
        self._probabilistic: dict[str, float] = {}  # name -> probability
        # delay_ms latency mode (ISSUE 17): name -> (delay_ms, hits, who).
        # A delayed point is slow, not failed — the gray-failure shape.
        # `who` scopes the delay to one caller identity ("osd.3"): the
        # injector is process-global, but a GRAY failure is one slow
        # daemon among healthy ones, so the harness must be able to
        # slow a single victim ("" = every caller, the legacy shape)
        self._delays: dict[str, tuple[float, int, str]] = {}
        self._rng = random.Random(0xEC)

    def inject(self, point: str, err: int, hits: int = -1) -> None:
        """Arm: next `hits` checks at `point` raise (hits<0 = forever)."""
        with self._lock:
            self._points[point] = (err, hits)

    def inject_probabilistic(self, point: str, one_in: int) -> None:
        """1-in-N failure chance (ms_inject_socket_failures semantics)."""
        with self._lock:
            if one_in <= 0:
                self._probabilistic.pop(point, None)
            else:
                self._probabilistic[point] = 1.0 / one_in

    def inject_delay(
        self, point: str, delay_ms: float, hits: int = -1, who: str = ""
    ) -> None:
        """Arm a LATENCY fault: the next `hits` checks at `point` report
        a pending delay of `delay_ms` (hits<0 = forever, <= 0 ms clears).
        Unlike `inject`, the seam stays functionally correct — callers
        apply the delay async-safely (sleep / call_later), never raise.
        `who` restricts the delay to one caller identity (e.g. "osd.3"):
        with daemons sharing one process-global injector, this is how a
        harness slows a single gray victim while its peers stay fast."""
        with self._lock:
            if delay_ms <= 0:
                self._delays.pop(point, None)
            else:
                self._delays[point] = (delay_ms, hits, who)

    def check_delay(self, point: str, who: str = "") -> float:
        """Pending injected delay in SECONDS for one pass through `point`
        (0.0 = none).  Decrements the hit budget like `check`.  A delay
        armed with a `who` scope only fires (and only spends hits) for
        the matching caller identity."""
        with self._lock:
            armed = self._delays.get(point)
            if armed is None:
                return 0.0
            delay_ms, hits, scope = armed
            if scope and scope != who:
                return 0.0
            if hits > 0:
                hits -= 1
                if hits == 0:
                    del self._delays[point]
                else:
                    self._delays[point] = (delay_ms, hits, scope)
            return delay_ms / 1000.0

    def clear(self, point: str | None = None) -> None:
        with self._lock:
            if point is None:
                self._points.clear()
                self._probabilistic.clear()
                self._delays.clear()
            else:
                self._points.pop(point, None)
                self._probabilistic.pop(point, None)
                self._delays.pop(point, None)

    def check(self, point: str) -> None:
        """Call at the injection point; raises InjectedFailure if armed."""
        with self._lock:
            armed = self._points.get(point)
            if armed is not None:
                err, hits = armed
                if hits > 0:
                    hits -= 1
                    if hits == 0:
                        del self._points[point]
                    else:
                        self._points[point] = (err, hits)
                raise InjectedFailure(point, err)
            p = self._probabilistic.get(point)
            if p is not None and self._rng.random() < p:
                raise InjectedFailure(point, 5)  # EIO

    def armed(self, point: str) -> bool:
        with self._lock:
            return (
                point in self._points
                or point in self._probabilistic
                or point in self._delays
            )


# The injection-point catalog: every name wired through `faultpoint()`
# anywhere in the tree MUST be registered here, and every entry must be
# documented in docs/ROBUSTNESS.md — tests/test_faultpoint_lint.py
# enforces both directions, so a hook can neither go stale in the docs
# nor be armed under a typo'd name that silently never fires.
FAULT_POINTS: dict[str, str] = {
    "msgr.send": (
        "messenger frame send, checked before any bytes reach the wire "
        "(ms_inject_socket_failures semantics: lossy connections reset, "
        "lossless ones transparently reconnect and resend).  In "
        "delay_ms mode the frame is held for the injected latency with "
        "an async-safe sleep before it is written — a slow NIC, not a "
        "dead one"
    ),
    "msgr.recv": (
        "messenger frame receive, checked after a frame is read; faults "
        "the connection like a peer reset (the already-read frame is "
        "lost, as a real mid-delivery connection death would lose it)"
    ),
    "os.read": (
        "objectstore read() data path (memstore + bluestore; stat/attr "
        "lookups stay clean): raises StoreError(EIO), the "
        "test-erasure-eio.sh disk-error analog"
    ),
    "os.write": (
        "objectstore queue_transaction (every backend, checked before "
        "any op is applied or staged): raises StoreError(EIO), failing "
        "the transaction whole — per-op injection would tear it, since "
        "apply does not roll back"
    ),
    "ec.sub_read": (
        "EC shard-side sub-read in ECBackend.handle_sub_read: the shard "
        "answers with a per-object EIO, driving redundant-read "
        "escalation and reconstruction on the primary.  In delay_ms "
        "mode the shard answers CORRECTLY but late (the reply is "
        "deferred on the event loop, never blocking it) — the gray "
        "failure that drives adaptive hedged reads"
    ),
    "codec.launch": (
        "device coding-launch submit in LaunchAggregator._launch: the "
        "device dispatch fails and the group re-runs on the byte-"
        "identical host oracle (gf/bitslice.py), marking the backend "
        "DEGRADED"
    ),
    "ec.recover_push": (
        "EC recovery push receive in ECBackend.handle_recovery_push: "
        "the target drops the PushOp on the floor, exactly as a dying "
        "target would — the primary's stalled-push retry "
        "(retry_stalled_pushes, osd_recovery_push_retry_sec) re-sends "
        "the pending shards so a wedged push cannot stall a "
        "recovery-storm wave forever"
    ),
    "peering.msg": (
        "peering message receive in PG.handle_peering_message: the "
        "query/notify/log message is dropped before the state machine "
        "sees it, wedging peering mid-storm; the tick-driven re-kick "
        "(PeeringState.tick restarts a primary stuck in GetInfo/GetLog) "
        "re-queries and self-heals"
    ),
}


# Process-wide injector used by daemons when none is passed explicitly.
_global = FaultInjector()


def global_injector() -> FaultInjector:
    return _global


def faultpoint(point: str) -> None:
    """Check a REGISTERED injection point on the process-global injector.

    The one spelling every wired seam uses (and the one the lint greps
    for): an unregistered name is a programming error, raised eagerly so
    a typo cannot create a hook that never fires."""
    if point not in FAULT_POINTS:
        raise ValueError(f"unregistered fault point {point!r}")
    _global.check(point)


def faultpoint_delay(point: str, who: str = "") -> float:
    """Pending injected delay (seconds) for a REGISTERED point on the
    process-global injector — the latency twin of `faultpoint()`.  The
    caller owns applying it async-safely (`await asyncio.sleep(d)` on
    the messenger path, `loop.call_later(d, ...)` around a synchronous
    reply) so an injected delay can never block the event loop.  `who`
    is the caller's daemon identity ("osd.3"); a delay armed with a
    scope only fires for the matching caller."""
    if point not in FAULT_POINTS:
        raise ValueError(f"unregistered fault point {point!r}")
    return _global.check_delay(point, who)
