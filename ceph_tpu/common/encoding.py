"""Versioned binary encoding — mirror of src/include/encoding.h.

Reference: /root/reference/src/include/encoding.h:188: every wire/disk
struct encodes as ENCODE_START(version, compat_version, bl) — a header of
(struct_v u8, struct_compat u8, length u32) — followed by little-endian
fields, closed by ENCODE_FINISH which backfills the length.  Decoders
check `struct_compat <= understood version` and can skip trailing bytes of
newer versions, which is how Ceph does rolling upgrades.  The
WRITE_CLASS_ENCODER macro family hangs encode/decode off each type; here
`Encodable` plays that role.

All integers little-endian, strings length-prefixed (u32), containers
count-prefixed (u32) — same conventions as the reference.
"""

from __future__ import annotations

import struct
from typing import Callable, TypeVar

T = TypeVar("T")


class DecodeError(Exception):
    pass


class Encoder:
    """Append-only byte builder (the bufferlist encode side)."""

    def __init__(self) -> None:
        self._parts: list[bytes] = []
        # stack of (index in _parts of the length placeholder) for nested
        # ENCODE_START frames
        self._frames: list[int] = []

    # -- primitives ----------------------------------------------------------

    def u8(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<B", v))
        return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<H", v))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v))
        return self

    def i64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "Encoder":
        self._parts.append(struct.pack("<d", v))
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    def bytes_(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self._parts.append(bytes(v))
        return self

    def string(self, v: str) -> "Encoder":
        return self.bytes_(v.encode("utf-8"))

    def raw(self, v: bytes) -> "Encoder":
        self._parts.append(bytes(v))
        return self

    # -- containers ----------------------------------------------------------

    def list_(self, items, item_fn: Callable[["Encoder", object], None]) -> "Encoder":
        items = list(items)
        self.u32(len(items))
        for it in items:
            item_fn(self, it)
        return self

    def map_(
        self,
        d: dict,
        key_fn: Callable[["Encoder", object], None],
        val_fn: Callable[["Encoder", object], None],
    ) -> "Encoder":
        self.u32(len(d))
        for k in sorted(d):
            key_fn(self, k)
            val_fn(self, d[k])
        return self

    # -- versioned frames (ENCODE_START / ENCODE_FINISH) ---------------------

    def start(self, version: int, compat: int) -> "Encoder":
        self.u8(version)
        self.u8(compat)
        self._parts.append(b"\x00\x00\x00\x00")  # length backfilled by finish
        self._frames.append(len(self._parts) - 1)
        return self

    def finish(self) -> "Encoder":
        idx = self._frames.pop()
        length = sum(len(p) for p in self._parts[idx + 1 :])
        self._parts[idx] = struct.pack("<I", length)
        return self

    def encodable(self, obj: "Encodable") -> "Encoder":
        obj.encode(self)
        return self

    def tobytes(self) -> bytes:
        assert not self._frames, "unbalanced start/finish"
        return b"".join(self._parts)


class Decoder:
    """Cursor over bytes (the bufferlist::const_iterator decode side)."""

    def __init__(self, data: bytes, offset: int = 0):
        self._data = data
        self._off = offset
        # stack of end-offsets for versioned frames, enabling skip of
        # unknown trailing fields (DECODE_FINISH)
        self._frames: list[int] = []

    def _take(self, n: int) -> bytes:
        if self._off + n > len(self._data):
            raise DecodeError(f"buffer underrun: need {n} at {self._off}")
        v = self._data[self._off : self._off + n]
        self._off += n
        return v

    @property
    def offset(self) -> int:
        return self._off

    def remaining(self) -> int:
        return len(self._data) - self._off

    # -- primitives ----------------------------------------------------------

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def bytes_(self) -> bytes:
        return self._take(self.u32())

    def string(self) -> str:
        return self.bytes_().decode("utf-8")

    def raw(self, n: int) -> bytes:
        return self._take(n)

    # -- containers ----------------------------------------------------------

    def list_(self, item_fn: Callable[["Decoder"], T]) -> list[T]:
        return [item_fn(self) for _ in range(self.u32())]

    def map_(self, key_fn, val_fn) -> dict:
        return {key_fn(self): val_fn(self) for _ in range(self.u32())}

    # -- versioned frames (DECODE_START / DECODE_FINISH) ---------------------

    def start(self, understood_version: int) -> int:
        """Returns struct_v; raises if struct_compat > understood."""
        struct_v = self.u8()
        struct_compat = self.u8()
        length = self.u32()
        if struct_compat > understood_version:
            raise DecodeError(
                f"struct_compat {struct_compat} > understood {understood_version}"
            )
        if self._off + length > len(self._data):
            raise DecodeError(
                f"versioned frame length {length} overruns buffer "
                f"({self.remaining()} bytes left)"
            )
        self._frames.append(self._off + length)
        return struct_v

    def finish(self) -> None:
        """Skip any trailing bytes of a newer encoding."""
        end = self._frames.pop()
        if self._off > end:
            raise DecodeError("overran versioned frame")
        self._off = end


def encode_kv_map(kv: dict[str, bytes]) -> bytes:
    """Wire blob for a str->bytes map (xattr dumps, omap key/value sets)."""
    e = Encoder()
    e.map_(kv, lambda enc, k: enc.string(k), lambda enc, v: enc.bytes_(v))
    return e.tobytes()


def decode_kv_map(blob: bytes) -> dict[str, bytes]:
    if not blob:
        return {}
    d = Decoder(blob)
    return d.map_(lambda dec: dec.string(), lambda dec: dec.bytes_())


def encode_str_list(items) -> bytes:
    e = Encoder()
    e.list_(items, lambda enc, s: enc.string(s))
    return e.tobytes()


def decode_str_list(blob: bytes) -> list[str]:
    if not blob:
        return []
    return Decoder(blob).list_(lambda dec: dec.string())


class Encodable:
    """Types with versioned encode/decode (WRITE_CLASS_ENCODER analog).

    Subclasses implement encode(Encoder) and classmethod decode(Decoder).
    """

    def encode(self, enc: Encoder) -> None:
        raise NotImplementedError

    @classmethod
    def decode(cls, dec: Decoder):
        raise NotImplementedError

    def tobytes(self) -> bytes:
        e = Encoder()
        self.encode(e)
        return e.tobytes()

    @classmethod
    def frombytes(cls, data: bytes):
        return cls.decode(Decoder(data))
