"""Admin socket — mirror of src/common/admin_socket.h.

Reference: /root/reference/src/common/admin_socket.h:106: every daemon
listens on a unix socket; hooks register commands (`perf dump`,
`config show`, `config set`, `dump_ops_in_flight`, ...) and the `ceph
daemon <sock> <cmd>` CLI sends a JSON request `{"prefix": ...}` and reads
a JSON reply.  Implemented on asyncio; a synchronous client helper is
provided for tools/tests.
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
from typing import Awaitable, Callable

from .log import dout

# A hook receives the parsed command dict and returns a JSON-serializable
# payload (AdminSocketHook::call).
Hook = Callable[[dict], object]


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._hooks: dict[str, tuple[Hook, str, bool]] = {}
        self._server: asyncio.AbstractServer | None = None
        # audit sink (ISSUE 16): the owning daemon wires this to its
        # cluster-log client so every MUTATING asok command lands on the
        # `audit` channel; called as audit_cb(prefix, cmd)
        self.audit_cb: Callable[[str, dict], None] | None = None
        self.register("help", lambda cmd: {
            prefix: desc for prefix, (_, desc, _m) in sorted(self._hooks.items())
        }, "list available commands")

    def register(
        self, prefix: str, hook: Hook, desc: str = "", mutating: bool = False
    ) -> None:
        """AdminSocket::register_command.  `mutating` marks commands
        that change daemon/cluster state (injectargs, fault arming,
        mark_unfound_lost, ...): they are audited through audit_cb, and
        the metrics lint's audit-discipline check enumerates them."""
        self._hooks[prefix] = (hook, desc, mutating)

    def mutating_prefixes(self) -> list[str]:
        """Commands registered as mutating (the audit-discipline lint's
        enumeration surface)."""
        return sorted(p for p, (_, _, m) in self._hooks.items() if m)

    async def start(self) -> None:
        if os.path.exists(self.path):
            os.unlink(self.path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._server = await asyncio.start_unix_server(self._handle, path=self.path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if os.path.exists(self.path):
            os.unlink(self.path)

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            raw = await reader.readline()
            if not raw:
                return
            try:
                cmd = json.loads(raw)
            except json.JSONDecodeError:
                cmd = {"prefix": raw.decode().strip()}
            prefix = cmd.get("prefix", "")
            entry = self._hooks.get(prefix)
            if entry is None:
                reply = {"error": f"unknown command {prefix!r}"}
            else:
                hook, _, mutating = entry
                if mutating and self.audit_cb is not None:
                    try:
                        self.audit_cb(prefix, cmd)
                    except Exception as e:
                        # auditing must never block the command itself
                        dout("asok", 1, f"audit hook failed for {prefix!r}: {e}")
                try:
                    result = hook(cmd)
                    if asyncio.iscoroutine(result):
                        result = await result
                    reply = {"result": result}
                except Exception as e:  # hook errors become error replies
                    reply = {"error": str(e)}
            writer.write(json.dumps(reply).encode() + b"\n")
            await writer.drain()
        finally:
            writer.close()


def admin_command(path: str, prefix: str, timeout: float = 5.0, **kwargs) -> object:
    """Synchronous client (the `ceph daemon <sock> <cmd>` analog)."""
    cmd = {"prefix": prefix, **kwargs}
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(path)
        s.sendall(json.dumps(cmd).encode() + b"\n")
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    reply = json.loads(buf)
    if "error" in reply:
        raise RuntimeError(reply["error"])
    return reply["result"]
