"""Runtime configuration — mirror of md_config_t / ConfigProxy.

Reference: /root/reference/src/common/config.h (md_config_t holds parsed
values layered defaults < conf file < env < cli < runtime-set) and
src/common/config_obs.h (md_config_obs_t observers notified when a
runtime-mutable key changes — e.g. mClockScheduler re-reads its QoS knobs,
src/osd/scheduler/mClockScheduler.h:72).  The mon-central config DB
(ConfigMonitor) pushes runtime `set`s through the same path.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterable

from .lockdep import make_rlock
from .options import OPTIONS, Option

ConfigObserver = Callable[[str, object], None]


class Config:
    """Layered typed config with change observers."""

    def __init__(
        self,
        overrides: dict[str, object] | None = None,
        conf_file: str | None = None,
        env: bool = True,
    ):
        self._lock = make_rlock("config")
        self._values: dict[str, object] = {
            name: opt.default for name, opt in OPTIONS.items()
        }
        self._observers: dict[str, list[ConfigObserver]] = {}
        if conf_file:
            self._apply_conf_file(conf_file)
        if env:
            # CEPH_TPU_<UPPER_NAME>=value overrides, like the CEPH_ARGS /
            # env override path in the reference.
            for name in OPTIONS:
                v = os.environ.get(f"CEPH_TPU_{name.upper()}")
                if v is not None:
                    self._set_locked(name, v)
        for k, v in (overrides or {}).items():
            self._set_locked(k, v)

    # -- reads ---------------------------------------------------------------

    def get(self, name: str):
        with self._lock:
            if name not in self._values:
                raise KeyError(f"unknown option {name}")
            return self._values[name]

    def __getitem__(self, name: str):
        return self.get(name)

    def get_option(self, name: str) -> Option:
        return OPTIONS[name]

    def show(self) -> dict[str, object]:
        """`config show` admin-socket command payload."""
        with self._lock:
            return dict(self._values)

    def diff(self) -> dict[str, object]:
        """`config diff`: only values that differ from defaults."""
        with self._lock:
            return {
                k: v
                for k, v in self._values.items()
                if v != OPTIONS[k].default
            }

    # -- writes --------------------------------------------------------------

    def set(self, name: str, value: object) -> None:
        """Runtime set; notifies observers (md_config_t::set_val +
        apply_changes)."""
        with self._lock:
            parsed = self._set_locked(name, value)
            observers = list(self._observers.get(name, ()))
        for obs in observers:
            obs(name, parsed)

    def _set_locked(self, name: str, value: object):
        opt = OPTIONS.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name}")
        parsed = opt.parse(value)
        self._values[name] = parsed
        return parsed

    def _apply_conf_file(self, path: str) -> None:
        """Minimal ini-ish `key = value` file, comments with #."""
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith(("#", ";", "[")):
                    continue
                key, _, val = line.partition("=")
                key = key.strip().replace(" ", "_")
                if key in OPTIONS:
                    self._set_locked(key, val.strip())

    # -- observers -----------------------------------------------------------

    def add_observer(self, names: Iterable[str], fn: ConfigObserver) -> None:
        """Register for change notifications on runtime-mutable keys
        (md_config_obs_t::get_tracked_conf_keys +
        handle_conf_change)."""
        with self._lock:
            for name in names:
                if name not in OPTIONS:
                    raise KeyError(f"unknown option {name}")
                self._observers.setdefault(name, []).append(fn)

    # -- subsystem debug levels ----------------------------------------------

    def debug_levels(self, subsys: str) -> tuple[int, int]:
        """Parse a debug_<subsys> "log/gather" pair (SubsystemMap levels)."""
        raw = str(self.get(f"debug_{subsys}"))
        log_s, _, gather_s = raw.partition("/")
        log = int(log_s)
        gather = int(gather_s) if gather_s else log
        return log, gather
