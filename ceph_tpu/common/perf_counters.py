"""Performance counters — mirror of src/common/perf_counters.h.

Reference: /root/reference/src/common/perf_counters.h:63 (PerfCounters: a
contiguous block of typed counters built by PerfCountersBuilder between a
lower/upper bound enum; types u64 counter, u64 gauge, time, and averages
(sum+count pairs)), and PerfCountersCollection aggregating every logger in
the process for `perf dump` on the admin socket.  The mgr scrapes these
(DaemonServer.cc) — here the prometheus-style text export lives on the
collection too.
"""

from __future__ import annotations

import threading

from .lockdep import make_lock
from dataclasses import dataclass, field


PERFCOUNTER_U64 = 1
PERFCOUNTER_TIME = 2
PERFCOUNTER_LONGRUNAVG = 4
PERFCOUNTER_COUNTER = 8  # monotonic (vs gauge)
PERFCOUNTER_HISTOGRAM = 16  # PerfHistogram axes (perf_histogram.h)


class PerfHistogramAxis:
    """One log2-scaled axis (perf_histogram.h axis_config_d with
    SCALE_LOG2): bucket i covers (bounds[i-1], bounds[i]], where
    bounds[i] = lowest * 2^i; the last bucket is the +Inf overflow."""

    def __init__(self, lowest: float, buckets: int):
        if buckets < 2:
            raise ValueError("histogram needs >= 2 buckets")
        self.lowest = lowest
        self.buckets = buckets
        # finite upper bounds; the final bucket is implicit +Inf
        self.bounds: list[float] = [
            lowest * (1 << i) for i in range(buckets - 1)
        ]

    def index(self, value: float) -> int:
        lo, hi = 0, len(self.bounds)
        while lo < hi:
            mid = (lo + hi) // 2
            if value <= self.bounds[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo  # == len(bounds) -> overflow bucket


class PerfHistogram:
    """1D log2-bucketed histogram (PerfHistogram<1>): per-bucket counts
    plus sum/count so the export satisfies the Prometheus histogram
    contract (_bucket/_sum/_count)."""

    def __init__(self, axis: PerfHistogramAxis):
        self.axis = axis
        self.counts = [0] * axis.buckets
        self.sum = 0.0
        self.count = 0

    def sample(self, value: float) -> None:
        self.counts[self.axis.index(value)] += 1
        self.sum += value
        self.count += 1

    def dump(self) -> dict:
        """JSON-safe cumulative bucket form: [[le, cumulative], ...] with
        the literal string "+Inf" as the final bound."""
        cum = 0
        buckets: list[list] = []
        for i, c in enumerate(self.counts):
            cum += c
            le = self.axis.bounds[i] if i < len(self.axis.bounds) else "+Inf"
            buckets.append([le, cum])
        return {
            "histogram": {
                "buckets": buckets,
                "sum": self.sum,
                "count": self.count,
            }
        }


class PerfHistogram2D:
    """2D histogram (PerfHistogram<2>, e.g. the reference's
    op_w_latency_in_bytes_histogram): counts over size x latency so tail
    latency can be attributed to op size, not just averaged away."""

    def __init__(self, x_axis: PerfHistogramAxis, y_axis: PerfHistogramAxis):
        self.x_axis = x_axis
        self.y_axis = y_axis
        self.counts = [[0] * y_axis.buckets for _ in range(x_axis.buckets)]
        self.count = 0

    def sample(self, x: float, y: float) -> None:
        self.counts[self.x_axis.index(x)][self.y_axis.index(y)] += 1
        self.count += 1

    def dump(self) -> dict:
        return {
            "histogram2d": {
                "x_le": list(self.x_axis.bounds) + ["+Inf"],
                "y_le": list(self.y_axis.bounds) + ["+Inf"],
                "counts": [list(row) for row in self.counts],
                "count": self.count,
            }
        }


def histogram_sample_lines(metric: str, h: dict, labels: str = "") -> list[str]:
    """Prometheus histogram samples for a PerfHistogram.dump() payload:
    cumulative `_bucket{le=...}` ending at +Inf, then `_sum`/`_count`.
    `labels` is a pre-rendered `k="v"` list WITHOUT braces ('' for none).
    Shared by every exporter so the exposition shape cannot diverge."""
    sep = "," if labels else ""
    lines = [
        f'{metric}_bucket{{{labels}{sep}le="{le}"}} {cum}'
        for le, cum in h["buckets"]
    ]
    suffix = f"{{{labels}}}" if labels else ""
    lines.append(f"{metric}_sum{suffix} {h['sum']}")
    lines.append(f"{metric}_count{suffix} {h['count']}")
    return lines


@dataclass
class _Counter:
    name: str
    type: int
    desc: str = ""
    value: float = 0.0
    avgcount: int = 0
    hist: object = None  # PerfHistogram | PerfHistogram2D


class PerfCounters:
    """One subsystem's counter block (perf_counters.h:63)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = make_lock("perf_counters")
        self._counters: dict[str, _Counter] = {}

    # -- updates (perf_counters.h inc/dec/set/tinc) --------------------------

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name].value += amount

    def dec(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name].value -= amount

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._counters[name].value = value

    def tinc(self, name: str, seconds: float) -> None:
        """Accumulate elapsed time; avg counters also count samples."""
        with self._lock:
            c = self._counters[name]
            c.value += seconds
            c.avgcount += 1

    def hinc(self, name: str, value: float) -> None:
        """Sample a 1D histogram counter (PerfCounters::hinc)."""
        with self._lock:
            self._counters[name].hist.sample(value)

    def hinc2(self, name: str, x: float, y: float) -> None:
        """Sample a 2D histogram counter."""
        with self._lock:
            self._counters[name].hist.sample(x, y)

    def ensure_histogram(
        self,
        name: str,
        desc: str = "",
        lowest: float = 1e-6,
        buckets: int = 25,
    ) -> None:
        """Lazily declare a 1D log2 histogram OUTSIDE the builder —
        for per-peer families whose membership is unknown at daemon
        construction (the osd_heartbeat_rtt_osd_<N> family, ISSUE 17).
        Idempotent; an existing counter of any type is left alone."""
        with self._lock:
            if name in self._counters:
                return
            self._counters[name] = _Counter(
                name,
                PERFCOUNTER_TIME | PERFCOUNTER_HISTOGRAM,
                desc,
                hist=PerfHistogram(PerfHistogramAxis(lowest, buckets)),
            )

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters[name].value

    def avgcount(self, name: str) -> int:
        with self._lock:
            return self._counters[name].avgcount

    # -- dump ----------------------------------------------------------------

    def dump(self) -> dict[str, object]:
        with self._lock:
            out: dict[str, object] = {}
            for c in self._counters.values():
                if c.type & PERFCOUNTER_HISTOGRAM:
                    out[c.name] = c.hist.dump()
                elif c.type & PERFCOUNTER_LONGRUNAVG:
                    out[c.name] = {"avgcount": c.avgcount, "sum": c.value}
                else:
                    out[c.name] = c.value
            return out

    def dump_histograms(self) -> dict[str, object]:
        """Only the histogram counters (`perf histogram dump` /
        `dump_histograms` admin-socket payload)."""
        with self._lock:
            return {
                c.name: c.hist.dump()
                for c in self._counters.values()
                if c.type & PERFCOUNTER_HISTOGRAM
            }


class PerfCountersBuilder:
    """Declarative construction (perf_counters.h PerfCountersBuilder)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64_counter(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, PERFCOUNTER_U64 | PERFCOUNTER_COUNTER, desc)
        return self

    def add_u64(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, PERFCOUNTER_U64, desc)
        return self

    def add_time_avg(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(
            name, PERFCOUNTER_TIME | PERFCOUNTER_LONGRUNAVG, desc
        )
        return self

    def add_histogram(
        self,
        name: str,
        desc: str = "",
        lowest: float = 1e-6,
        buckets: int = 25,
    ) -> "PerfCountersBuilder":
        """1D log2 histogram; the default axis covers 1 µs .. ~8.4 s of
        latency before the +Inf overflow bucket."""
        self._pc._counters[name] = _Counter(
            name,
            PERFCOUNTER_TIME | PERFCOUNTER_HISTOGRAM,
            desc,
            hist=PerfHistogram(PerfHistogramAxis(lowest, buckets)),
        )
        return self

    def add_histogram_2d(
        self,
        name: str,
        desc: str = "",
        x_lowest: float = 4096,
        x_buckets: int = 12,
        y_lowest: float = 1e-6,
        y_buckets: int = 25,
    ) -> "PerfCountersBuilder":
        """2D log2 histogram; defaults to size (4 KiB .. 8 MiB) x latency
        (1 µs .. ~8.4 s) — the op_w_latency_in_bytes_histogram shape."""
        self._pc._counters[name] = _Counter(
            name,
            PERFCOUNTER_U64 | PERFCOUNTER_HISTOGRAM,
            desc,
            hist=PerfHistogram2D(
                PerfHistogramAxis(x_lowest, x_buckets),
                PerfHistogramAxis(y_lowest, y_buckets),
            ),
        )
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """Process-wide registry behind `perf dump` (perf_counters.h
    PerfCountersCollection; surfaced via the admin socket)."""

    def __init__(self) -> None:
        self._lock = make_lock("perf_counters_collection")
        self._loggers: dict[str, PerfCounters] = {}

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def dump(self) -> dict[str, dict[str, object]]:
        with self._lock:
            return {name: pc.dump() for name, pc in self._loggers.items()}

    def prometheus_text(self) -> str:
        """Prometheus exposition format — the mgr prometheus-module /
        ceph-exporter analog (src/exporter/, src/pybind/mgr/prometheus)."""
        def sanitize(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        lines: list[str] = []
        for logger, counters in sorted(self.dump().items()):
            for cname, val in sorted(counters.items()):
                metric = f"ceph_tpu_{sanitize(logger)}_{sanitize(cname)}"
                if isinstance(val, dict) and "histogram" in val:
                    lines.append(f"# HELP {metric} perf histogram {cname}")
                    lines.append(f"# TYPE {metric} histogram")
                    lines.extend(
                        histogram_sample_lines(metric, val["histogram"])
                    )
                elif isinstance(val, dict) and "histogram2d" in val:
                    continue  # 2D grids have no prometheus family shape
                elif isinstance(val, dict):
                    lines.append(f"{metric}_sum {val['sum']}")
                    lines.append(f"{metric}_count {val['avgcount']}")
                else:
                    lines.append(f"{metric} {val}")
        return "\n".join(lines) + "\n"
