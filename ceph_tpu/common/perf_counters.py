"""Performance counters — mirror of src/common/perf_counters.h.

Reference: /root/reference/src/common/perf_counters.h:63 (PerfCounters: a
contiguous block of typed counters built by PerfCountersBuilder between a
lower/upper bound enum; types u64 counter, u64 gauge, time, and averages
(sum+count pairs)), and PerfCountersCollection aggregating every logger in
the process for `perf dump` on the admin socket.  The mgr scrapes these
(DaemonServer.cc) — here the prometheus-style text export lives on the
collection too.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


PERFCOUNTER_U64 = 1
PERFCOUNTER_TIME = 2
PERFCOUNTER_LONGRUNAVG = 4
PERFCOUNTER_COUNTER = 8  # monotonic (vs gauge)


@dataclass
class _Counter:
    name: str
    type: int
    desc: str = ""
    value: float = 0.0
    avgcount: int = 0


class PerfCounters:
    """One subsystem's counter block (perf_counters.h:63)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, _Counter] = {}

    # -- updates (perf_counters.h inc/dec/set/tinc) --------------------------

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name].value += amount

    def dec(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name].value -= amount

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._counters[name].value = value

    def tinc(self, name: str, seconds: float) -> None:
        """Accumulate elapsed time; avg counters also count samples."""
        with self._lock:
            c = self._counters[name]
            c.value += seconds
            c.avgcount += 1

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters[name].value

    def avgcount(self, name: str) -> int:
        with self._lock:
            return self._counters[name].avgcount

    # -- dump ----------------------------------------------------------------

    def dump(self) -> dict[str, object]:
        with self._lock:
            out: dict[str, object] = {}
            for c in self._counters.values():
                if c.type & PERFCOUNTER_LONGRUNAVG:
                    out[c.name] = {"avgcount": c.avgcount, "sum": c.value}
                else:
                    out[c.name] = c.value
            return out


class PerfCountersBuilder:
    """Declarative construction (perf_counters.h PerfCountersBuilder)."""

    def __init__(self, name: str):
        self._pc = PerfCounters(name)

    def add_u64_counter(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, PERFCOUNTER_U64 | PERFCOUNTER_COUNTER, desc)
        return self

    def add_u64(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(name, PERFCOUNTER_U64, desc)
        return self

    def add_time_avg(self, name: str, desc: str = "") -> "PerfCountersBuilder":
        self._pc._counters[name] = _Counter(
            name, PERFCOUNTER_TIME | PERFCOUNTER_LONGRUNAVG, desc
        )
        return self

    def create_perf_counters(self) -> PerfCounters:
        return self._pc


class PerfCountersCollection:
    """Process-wide registry behind `perf dump` (perf_counters.h
    PerfCountersCollection; surfaced via the admin socket)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._loggers: dict[str, PerfCounters] = {}

    def add(self, pc: PerfCounters) -> None:
        with self._lock:
            self._loggers[pc.name] = pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._loggers.pop(name, None)

    def dump(self) -> dict[str, dict[str, object]]:
        with self._lock:
            return {name: pc.dump() for name, pc in self._loggers.items()}

    def prometheus_text(self) -> str:
        """Prometheus exposition format — the mgr prometheus-module /
        ceph-exporter analog (src/exporter/, src/pybind/mgr/prometheus)."""
        def sanitize(name: str) -> str:
            return name.replace(".", "_").replace("-", "_")

        lines: list[str] = []
        for logger, counters in sorted(self.dump().items()):
            for cname, val in sorted(counters.items()):
                metric = f"ceph_tpu_{sanitize(logger)}_{sanitize(cname)}"
                if isinstance(val, dict):
                    lines.append(f"{metric}_sum {val['sum']}")
                    lines.append(f"{metric}_count {val['avgcount']}")
                else:
                    lines.append(f"{metric} {val}")
        return "\n".join(lines) + "\n"
