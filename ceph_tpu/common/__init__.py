"""Common substrate — mirror of /root/reference/src/common + src/log.

The layer-1 services everything else sits on (SURVEY.md §1 row 1): typed
config options with runtime observers, per-subsystem leveled logging,
performance counters, the admin socket, the versioned binary encoding
framework, throttles, fault injection, and span tracing.
"""

from .config import Config, ConfigObserver
from .encoding import Decoder, Encoder, Encodable
from .fault_injector import (
    FAULT_POINTS,
    FaultInjector,
    InjectedFailure,
    faultpoint,
    global_injector,
)
from .options import OPTIONS, Option, OptionLevel
from .perf_counters import PerfCounters, PerfCountersBuilder, PerfCountersCollection
from .throttle import Throttle
from .tracer import Span, Tracer

__all__ = [
    "Config",
    "ConfigObserver",
    "Decoder",
    "Encodable",
    "Encoder",
    "FAULT_POINTS",
    "FaultInjector",
    "InjectedFailure",
    "faultpoint",
    "global_injector",
    "OPTIONS",
    "Option",
    "OptionLevel",
    "PerfCounters",
    "PerfCountersBuilder",
    "PerfCountersCollection",
    "Span",
    "Throttle",
    "Tracer",
]
