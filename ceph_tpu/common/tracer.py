"""Span tracing — mirror of src/common/tracer.h + blkin ZTracer.

Reference: /root/reference/src/common/tracer.h:18 (`tracing::Tracer`
producing `jspan` opentelemetry spans) and the Zipkin/blkin traces
threaded through the EC data path (every ECBackend::handle_sub_* takes a
ZTracer::Trace, src/osd/ECBackend.h:64-87, with events like
`trace.event("start ec write")`, ECBackend.cc:2020).  Spans here are
in-process records with parent links, timed events, and keyvals,
exportable as JSON for offline analysis.

Cross-daemon propagation (the W3C traceparent / jspan-context analog):
every span carries a 63-bit `trace_id` shared by the whole operation and
a process-unique `span_id`.  `inject()` copies the pair into a message's
envelope fields and `extract()` recovers a `TraceContext` on the far
side, so one client write yields ONE trace spanning client → messenger →
OSD dispatch → EC encode → codec kernel → commit, with every hop
parent-linked across daemons.  `current_span()`/`span_scope()` expose
the active span through a contextvar so deep layers (codec plugins, the
stripe driver) can attach sub-spans without threading a parent through
every signature.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import random
import threading
import time
from dataclasses import dataclass, field

from .lockdep import make_lock


# envelope sampling-decision values (msg.trace_sampled): the head
# decision is made ONCE — at the first daemon with sampling CONFIGURED
# (the client when it has the knobs, else the OSD) — and carried on the
# message envelope so every downstream span honors it instead of
# re-rolling the dice
SAMPLED_KEEP = 1   # trace is head-sampled: retain spans immediately
SAMPLED_DROP = 2   # head-sampled OUT: spans stay provisional (tail-keep
                   # for slow/errored ops can still rescue them)
SAMPLED_NONE = 3   # sender traced but has NO sampling configured (e.g.
                   # a client without the OSD knobs): the receiver makes
                   # its own head decision rather than inheriting an
                   # implicit KEEP that would bypass the span budget


@dataclass(frozen=True)
class TraceContext:
    """The propagated (trace_id, span_id, sampled) triple — what rides a
    message envelope between daemons (jspan context / blkin trace info).
    `sampled` carries the head-sampling decision; envelopes from senders
    predating the flag default to KEEP (the pre-sampling behavior)."""

    trace_id: int
    span_id: int
    sampled: int = SAMPLED_KEEP


@dataclass
class Span:
    tracer: "Tracer"
    span_id: int
    parent_id: int | None
    name: str
    # True when this span is in the export buffer.  event()/keyval() key
    # off THIS, not the tracer's live flag: a runtime enable mid-op must
    # not grow events on spans the dump will never show, nor attach
    # exported children to unexported parents.
    recorded: bool = False
    # True while the span collects events but has NOT been committed to
    # the export ring: its trace was head-sampled out (or over budget)
    # and only a tail keep (slow/errored op) can still retain it.
    provisional: bool = False
    trace_id: int = 0
    start: float = field(default_factory=time.monotonic)
    end: float | None = None
    events: list[tuple[float, str]] = field(default_factory=list)
    tags: dict[str, str] = field(default_factory=dict)

    def event(self, name) -> None:
        """blkin Trace::event.  `name` may be a zero-arg callable so hot
        paths skip f-string construction when tracing is off."""
        if self.recorded:
            self.events.append(
                (time.monotonic(), name() if callable(name) else name)
            )

    def keyval(self, key: str, val: object) -> None:
        if self.recorded:
            self.tags[key] = str(val() if callable(val) else val)

    def child(self, name: str) -> "Span":
        return self.tracer.start_span(name, parent=self)

    def context(self) -> TraceContext:
        """The propagatable identity of this span."""
        return TraceContext(self.trace_id, self.span_id)

    def finish(self) -> None:
        self.end = time.monotonic()
        if self.provisional:
            self.tracer._provisional_finished(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "events": [{"t": t, "name": n} for t, n in self.events],
            "tags": self.tags,
        }


class Tracer:
    """Span factory + in-memory export buffer (tracer.h Tracer::init;
    disabled tracers hand out no-op spans just like the reference's
    null jspan).

    Budgeted sampling (ISSUE 10): `sample_rate` head-samples NEW roots
    (the client/messenger entry decision, carried on message envelopes
    via TraceContext.sampled so downstream spans honor one decision),
    and `budget_per_sec` is a token bucket charged once per head-sampled
    trace — always-on tracing cannot exceed the retention budget however
    hot the workload.  Head-rejected traces stay PROVISIONAL: their
    spans still collect events (bounded by in-flight work) but only
    reach the export ring if `mark_keep()` fires before they all finish
    — the tail-based always-keep for ops that exceed the OpTracker
    complaint age or error out."""

    # provisional-trace bound: traces whose spans never finish (leaked
    # by a fault path) must not accumulate — evict oldest past this
    MAX_PENDING = 1024

    # NONE-envelope head-decision memo bound (oldest evicted first; a
    # resend arriving after eviction re-rolls, which only risks the
    # decision splitting on traces older than thousands of newer ones)
    MAX_HEAD_MEMO = 4096

    def __init__(
        self,
        service: str = "",
        enabled: bool = True,
        max_spans: int = 10000,
        sample_rate: float = 1.0,
        budget_per_sec: float = 0.0,
    ):
        from collections import OrderedDict, deque

        self.service = service
        self.enabled = enabled
        self.sample_rate = float(sample_rate)
        self.budget_per_sec = float(budget_per_sec)
        self._ids = itertools.count(1)
        # span ids must not collide across the daemons contributing to one
        # trace: offset each tracer's counter by a random 63-bit base (the
        # reference gets uniqueness from otel's random 64-bit span ids)
        self._id_base = random.getrandbits(63) & ~0xFFFFF
        self._lock = make_lock("tracer")
        # ring buffer: the NEWEST max_spans survive — an operator dumping
        # traces to debug a current problem needs recent spans, not the
        # daemon's boot-time history
        self._spans: "deque[Span]" = deque(maxlen=max_spans)
        # token bucket (retention budget): capacity = one second of burst
        self._tokens = self._budget_cap()
        self._tokens_t = time.monotonic()
        # provisional traces: trace_id -> {"spans": [Span], "keep": bool}
        self._pending: dict[int, dict] = {}
        # memoized head decisions for NONE-stamped envelopes: ONE roll
        # per trace, not per message — the objecter re-injects the SAME
        # context on every resend, and re-rolling could split a trace
        # keep/drop and charge the budget once per delivery
        self._head_memo: "OrderedDict[int, bool]" = OrderedDict()
        # sampling counters (exported via sampling_stats -> the scrape)
        self._stats = {
            "sampled": 0,          # head-sampled traces (budget-charged)
            "unsampled": 0,        # head-rejected by sample_rate
            "dropped_budget": 0,   # rate-accepted, bucket empty
            "dropped_tail": 0,     # provisional traces discarded at finish
            "kept_tail": 0,        # provisional traces rescued by mark_keep
            "retained_spans": 0,   # spans committed to the export ring
        }

    # -- sampling --------------------------------------------------------------

    def configure_sampling(
        self,
        sample_rate: float | None = None,
        budget_per_sec: float | None = None,
    ) -> None:
        """Runtime knob application (the OSD config-observer pattern:
        op_trace_sample_rate / op_trace_budget_per_sec)."""
        with self._lock:
            if sample_rate is not None:
                self.sample_rate = float(sample_rate)
            if budget_per_sec is not None:
                prev = self.budget_per_sec
                self.budget_per_sec = float(budget_per_sec)
                if prev <= 0.0:
                    # enabling (or re-enabling) the budget starts with
                    # the documented one-second burst — an empty bucket
                    # would count the first traces dropped_budget
                    self._tokens = self._budget_cap()
                else:
                    # lowering clamps to the new capacity; raising keeps
                    # the current tokens (refill reaches the new cap
                    # within a second anyway)
                    self._tokens = min(self._tokens, self._budget_cap())
                self._tokens_t = time.monotonic()

    def _sampling_active(self) -> bool:
        return self.sample_rate < 1.0 or self.budget_per_sec > 0.0

    def _budget_cap(self) -> float:
        """Bucket capacity: one second of burst, but never less than one
        whole token — a fractional budget (0 < budget < 1/s) must mean
        "one trace every 1/budget seconds", not "no traces ever"."""
        return max(self.budget_per_sec, 1.0)

    def _budget_take(self) -> bool:
        """One token per head-sampled trace; callers hold _lock."""
        if self.budget_per_sec <= 0.0:
            return True
        now = time.monotonic()
        self._tokens = min(
            self._budget_cap(),
            self._tokens + (now - self._tokens_t) * self.budget_per_sec,
        )
        self._tokens_t = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def _head_decision(self) -> bool:
        """The once-per-trace head decision (callers hold _lock)."""
        if self.sample_rate < 1.0 and random.random() >= self.sample_rate:
            self._stats["unsampled"] += 1
            return False
        if not self._budget_take():
            self._stats["dropped_budget"] += 1
            return False
        self._stats["sampled"] += 1
        return True

    def mark_keep(self, span: Span | None) -> None:
        """Tail-based always-keep: flag `span`'s trace for retention —
        called when an op exceeds the OpTracker complaint age or errors,
        so slow/broken ops NEVER lose their trace to sampling.  No-op
        for already-retained or unrecorded spans."""
        if span is None or not span.recorded or not span.provisional:
            return
        with self._lock:
            pending = self._pending.get(span.trace_id)
            if pending is not None:
                pending["keep"] = True

    def _provisional_finished(self, span: Span) -> None:
        """A provisional span finished: once EVERY span of its trace has
        finished, commit (keep flagged) or discard the whole set.
        Resolution waits for all spans — an OSD's op span outlives the
        messenger hop span that opened the trace locally."""
        retained: list[Span] = []
        with self._lock:
            pending = self._pending.get(span.trace_id)
            if pending is None:
                return
            if any(s.end is None for s in pending["spans"]):
                return
            del self._pending[span.trace_id]
            if pending["keep"]:
                self._stats["kept_tail"] += 1
                retained = pending["spans"]
                self._stats["retained_spans"] += len(retained)
                for s in retained:
                    s.provisional = False
                    self._spans.append(s)
            else:
                self._stats["dropped_tail"] += 1

    def sampling_stats(self) -> dict:
        """Sampled/kept/dropped counters + live config — the OSD ships
        these in its status blob and (flattened) on MMgrReport so the
        scrape carries ceph_tpu_trace_* families."""
        with self._lock:
            return {
                **self._stats,
                "sample_rate": self.sample_rate,
                "budget_per_sec": self.budget_per_sec,
                "pending_traces": len(self._pending),
            }

    def start_span(
        self,
        name: str,
        parent: Span | None = None,
        remote: TraceContext | None = None,
    ) -> Span:
        """Start a span.  `parent` links within this process; `remote` is
        an extracted cross-daemon context (takes effect only when no local
        parent is given)."""
        # children of unrecorded parents stay unrecorded (no dangling
        # parent_id in the export after a mid-op enable flip)
        record = self.enabled and (parent is None or parent.recorded)
        provisional = False
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
            provisional = parent.provisional
        elif remote is not None and remote.trace_id:
            trace_id = remote.trace_id
            parent_id = remote.span_id
            # honor the envelope-carried decision: a head-rejected trace
            # stays provisional here too (local tail-keep may rescue
            # it).  NONE means the sender traced without sampling
            # configured — the head decision falls to THIS daemon
            if (
                record
                and remote.sampled == SAMPLED_NONE
                and self._sampling_active()
            ):
                with self._lock:
                    keep = self._head_memo.get(trace_id)
                    if keep is None:
                        keep = self._head_decision()
                        self._head_memo[trace_id] = keep
                        if len(self._head_memo) > self.MAX_HEAD_MEMO:
                            self._head_memo.popitem(last=False)
                    provisional = not keep
            else:
                provisional = record and remote.sampled == SAMPLED_DROP
        else:
            # new root: allocate a trace id only when it can be exported;
            # the head-sampling decision is made HERE, exactly once
            parent_id = None
            trace_id = 0
            if record:
                trace_id = random.getrandbits(63) | 1
                if self._sampling_active():
                    with self._lock:
                        provisional = not self._head_decision()
        span = Span(
            tracer=self,
            span_id=self._id_base + next(self._ids),
            parent_id=parent_id,
            name=name,
            recorded=record,
            provisional=provisional,
            trace_id=trace_id,
        )
        if record:
            with self._lock:
                if provisional:
                    pending = self._pending.get(span.trace_id)
                    if pending is None:
                        if len(self._pending) >= self.MAX_PENDING:
                            # evict the oldest NON-keep trace: under
                            # sustained load the oldest pending traces
                            # are exactly the slowest ops, and a trace
                            # mark_keep already rescued must not be
                            # silently dropped by the memory bound —
                            # when every pending trace is keep-flagged,
                            # commit the evictee instead of dropping it
                            victim_id = next(
                                (
                                    tid
                                    for tid, p in self._pending.items()
                                    if not p["keep"]
                                ),
                                next(iter(self._pending)),
                            )
                            victim = self._pending.pop(victim_id)
                            if victim["keep"]:
                                self._stats["kept_tail"] += 1
                                self._stats["retained_spans"] += len(
                                    victim["spans"]
                                )
                                for s in victim["spans"]:
                                    s.provisional = False
                                    self._spans.append(s)
                            else:
                                self._stats["dropped_tail"] += 1
                        pending = self._pending[span.trace_id] = {
                            "spans": [], "keep": False,
                        }
                    pending["spans"].append(span)
                else:
                    self._spans.append(span)
                    self._stats["retained_spans"] += 1
        return span

    def export(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def export_traces(self) -> dict[str, list[dict]]:
        """Spans grouped by trace id, each trace ordered by start time —
        the `dump_tracing` admin-socket payload."""
        traces: dict[str, list[dict]] = {}
        for s in self.export():
            traces.setdefault(str(s["trace_id"]), []).append(s)
        for spans in traces.values():
            spans.sort(key=lambda s: s["start"])
        return traces

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


NULL_TRACER = Tracer(enabled=False)


def null_span(name: str = "") -> Span:
    return NULL_TRACER.start_span(name)


# -- context propagation helpers ----------------------------------------------

_CURRENT: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "ceph_tpu_current_span", default=None
)


def current_span() -> Span | None:
    """The active span in this execution context (if any)."""
    return _CURRENT.get()


@contextlib.contextmanager
def span_scope(span: Span | None):
    """Make `span` the current span for the duration of the block (the
    otel Scope analog).  Does NOT finish the span.  Unrecorded spans are
    fine here: consumers filter on `.recorded` (codec/tracing.active_span)
    or inherit unrecordedness through start_span, so callers need no
    `if span.recorded` guard."""
    token = _CURRENT.set(span)
    try:
        yield span
    finally:
        _CURRENT.reset(token)


def inject(span: Span | None, msg) -> None:
    """Copy a span's context into a message's envelope fields (the
    traceparent header write).  No-op for unrecorded spans, so disabled
    tracers cost two attribute reads.  The head-sampling decision rides
    along (`trace_sampled`): provisional spans mark the envelope DROP so
    downstream daemons buffer instead of retaining."""
    if span is not None and span.recorded:
        msg.trace_id = span.trace_id
        msg.span_id = span.span_id
        if span.provisional:
            msg.trace_sampled = SAMPLED_DROP
        elif span.tracer is not None and span.tracer._sampling_active():
            msg.trace_sampled = SAMPLED_KEEP
        else:
            # no sampling configured here: don't stamp an implicit KEEP
            # (it would bypass the receiver's budget) — let the first
            # sampling-configured daemon downstream decide
            msg.trace_sampled = SAMPLED_NONE


def extract(msg) -> TraceContext | None:
    """Recover the propagated context from a received message (the
    traceparent header read); None when the sender wasn't tracing.
    Envelopes without an explicit sampling decision (pre-sampling
    senders) default to KEEP."""
    trace_id = getattr(msg, "trace_id", 0)
    if not trace_id:
        return None
    return TraceContext(
        trace_id,
        getattr(msg, "span_id", 0),
        getattr(msg, "trace_sampled", 0) or SAMPLED_KEEP,
    )
