"""Span tracing — mirror of src/common/tracer.h + blkin ZTracer.

Reference: /root/reference/src/common/tracer.h:18 (`tracing::Tracer`
producing `jspan` opentelemetry spans) and the Zipkin/blkin traces
threaded through the EC data path (every ECBackend::handle_sub_* takes a
ZTracer::Trace, src/osd/ECBackend.h:64-87, with events like
`trace.event("start ec write")`, ECBackend.cc:2020).  Spans here are
in-process records with parent links, timed events, and keyvals,
exportable as JSON for offline analysis.

Cross-daemon propagation (the W3C traceparent / jspan-context analog):
every span carries a 63-bit `trace_id` shared by the whole operation and
a process-unique `span_id`.  `inject()` copies the pair into a message's
envelope fields and `extract()` recovers a `TraceContext` on the far
side, so one client write yields ONE trace spanning client → messenger →
OSD dispatch → EC encode → codec kernel → commit, with every hop
parent-linked across daemons.  `current_span()`/`span_scope()` expose
the active span through a contextvar so deep layers (codec plugins, the
stripe driver) can attach sub-spans without threading a parent through
every signature.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import random
import threading
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class TraceContext:
    """The propagated (trace_id, span_id) pair — what rides a message
    envelope between daemons (jspan context / blkin trace info)."""

    trace_id: int
    span_id: int


@dataclass
class Span:
    tracer: "Tracer"
    span_id: int
    parent_id: int | None
    name: str
    # True when this span is in the export buffer.  event()/keyval() key
    # off THIS, not the tracer's live flag: a runtime enable mid-op must
    # not grow events on spans the dump will never show, nor attach
    # exported children to unexported parents.
    recorded: bool = False
    trace_id: int = 0
    start: float = field(default_factory=time.monotonic)
    end: float | None = None
    events: list[tuple[float, str]] = field(default_factory=list)
    tags: dict[str, str] = field(default_factory=dict)

    def event(self, name) -> None:
        """blkin Trace::event.  `name` may be a zero-arg callable so hot
        paths skip f-string construction when tracing is off."""
        if self.recorded:
            self.events.append(
                (time.monotonic(), name() if callable(name) else name)
            )

    def keyval(self, key: str, val: object) -> None:
        if self.recorded:
            self.tags[key] = str(val() if callable(val) else val)

    def child(self, name: str) -> "Span":
        return self.tracer.start_span(name, parent=self)

    def context(self) -> TraceContext:
        """The propagatable identity of this span."""
        return TraceContext(self.trace_id, self.span_id)

    def finish(self) -> None:
        self.end = time.monotonic()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "events": [{"t": t, "name": n} for t, n in self.events],
            "tags": self.tags,
        }


class Tracer:
    """Span factory + in-memory export buffer (tracer.h Tracer::init;
    disabled tracers hand out no-op spans just like the reference's
    null jspan)."""

    def __init__(self, service: str = "", enabled: bool = True, max_spans: int = 10000):
        from collections import deque

        self.service = service
        self.enabled = enabled
        self._ids = itertools.count(1)
        # span ids must not collide across the daemons contributing to one
        # trace: offset each tracer's counter by a random 63-bit base (the
        # reference gets uniqueness from otel's random 64-bit span ids)
        self._id_base = random.getrandbits(63) & ~0xFFFFF
        self._lock = threading.Lock()
        # ring buffer: the NEWEST max_spans survive — an operator dumping
        # traces to debug a current problem needs recent spans, not the
        # daemon's boot-time history
        self._spans: "deque[Span]" = deque(maxlen=max_spans)

    def start_span(
        self,
        name: str,
        parent: Span | None = None,
        remote: TraceContext | None = None,
    ) -> Span:
        """Start a span.  `parent` links within this process; `remote` is
        an extracted cross-daemon context (takes effect only when no local
        parent is given)."""
        # children of unrecorded parents stay unrecorded (no dangling
        # parent_id in the export after a mid-op enable flip)
        record = self.enabled and (parent is None or parent.recorded)
        if parent is not None:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        elif remote is not None and remote.trace_id:
            trace_id = remote.trace_id
            parent_id = remote.span_id
        else:
            # new root: allocate a trace id only when it can be exported
            trace_id = (random.getrandbits(63) | 1) if record else 0
            parent_id = None
        span = Span(
            tracer=self,
            span_id=self._id_base + next(self._ids),
            parent_id=parent_id,
            name=name,
            recorded=record,
            trace_id=trace_id,
        )
        if record:
            with self._lock:
                self._spans.append(span)
        return span

    def export(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def export_traces(self) -> dict[str, list[dict]]:
        """Spans grouped by trace id, each trace ordered by start time —
        the `dump_tracing` admin-socket payload."""
        traces: dict[str, list[dict]] = {}
        for s in self.export():
            traces.setdefault(str(s["trace_id"]), []).append(s)
        for spans in traces.values():
            spans.sort(key=lambda s: s["start"])
        return traces

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


NULL_TRACER = Tracer(enabled=False)


def null_span(name: str = "") -> Span:
    return NULL_TRACER.start_span(name)


# -- context propagation helpers ----------------------------------------------

_CURRENT: contextvars.ContextVar[Span | None] = contextvars.ContextVar(
    "ceph_tpu_current_span", default=None
)


def current_span() -> Span | None:
    """The active span in this execution context (if any)."""
    return _CURRENT.get()


@contextlib.contextmanager
def span_scope(span: Span | None):
    """Make `span` the current span for the duration of the block (the
    otel Scope analog).  Does NOT finish the span.  Unrecorded spans are
    fine here: consumers filter on `.recorded` (codec/tracing.active_span)
    or inherit unrecordedness through start_span, so callers need no
    `if span.recorded` guard."""
    token = _CURRENT.set(span)
    try:
        yield span
    finally:
        _CURRENT.reset(token)


def inject(span: Span | None, msg) -> None:
    """Copy a span's context into a message's envelope fields (the
    traceparent header write).  No-op for unrecorded spans, so disabled
    tracers cost two attribute reads."""
    if span is not None and span.recorded:
        msg.trace_id = span.trace_id
        msg.span_id = span.span_id


def extract(msg) -> TraceContext | None:
    """Recover the propagated context from a received message (the
    traceparent header read); None when the sender wasn't tracing."""
    trace_id = getattr(msg, "trace_id", 0)
    if not trace_id:
        return None
    return TraceContext(trace_id, getattr(msg, "span_id", 0))
