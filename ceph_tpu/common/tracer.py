"""Span tracing — mirror of src/common/tracer.h + blkin ZTracer.

Reference: /root/reference/src/common/tracer.h:18 (`tracing::Tracer`
producing `jspan` opentelemetry spans) and the Zipkin/blkin traces
threaded through the EC data path (every ECBackend::handle_sub_* takes a
ZTracer::Trace, src/osd/ECBackend.h:64-87, with events like
`trace.event("start ec write")`, ECBackend.cc:2020).  Spans here are
in-process records with parent links, timed events, and keyvals,
exportable as JSON for offline analysis.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Span:
    tracer: "Tracer"
    span_id: int
    parent_id: int | None
    name: str
    # True when this span is in the export buffer.  event()/keyval() key
    # off THIS, not the tracer's live flag: a runtime enable mid-op must
    # not grow events on spans the dump will never show, nor attach
    # exported children to unexported parents.
    recorded: bool = False
    start: float = field(default_factory=time.monotonic)
    end: float | None = None
    events: list[tuple[float, str]] = field(default_factory=list)
    tags: dict[str, str] = field(default_factory=dict)

    def event(self, name) -> None:
        """blkin Trace::event.  `name` may be a zero-arg callable so hot
        paths skip f-string construction when tracing is off."""
        if self.recorded:
            self.events.append(
                (time.monotonic(), name() if callable(name) else name)
            )

    def keyval(self, key: str, val: object) -> None:
        if self.recorded:
            self.tags[key] = str(val() if callable(val) else val)

    def child(self, name: str) -> "Span":
        return self.tracer.start_span(name, parent=self)

    def finish(self) -> None:
        self.end = time.monotonic()

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "events": [{"t": t, "name": n} for t, n in self.events],
            "tags": self.tags,
        }


class Tracer:
    """Span factory + in-memory export buffer (tracer.h Tracer::init;
    disabled tracers hand out no-op spans just like the reference's
    null jspan)."""

    def __init__(self, service: str = "", enabled: bool = True, max_spans: int = 10000):
        from collections import deque

        self.service = service
        self.enabled = enabled
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        # ring buffer: the NEWEST max_spans survive — an operator dumping
        # traces to debug a current problem needs recent spans, not the
        # daemon's boot-time history
        self._spans: "deque[Span]" = deque(maxlen=max_spans)

    def start_span(self, name: str, parent: Span | None = None) -> Span:
        # children of unrecorded parents stay unrecorded (no dangling
        # parent_id in the export after a mid-op enable flip)
        record = self.enabled and (parent is None or parent.recorded)
        span = Span(
            tracer=self,
            span_id=next(self._ids),
            parent_id=parent.span_id if parent else None,
            name=name,
            recorded=record,
        )
        if record:
            with self._lock:
                self._spans.append(span)
        return span

    def export(self) -> list[dict]:
        with self._lock:
            return [s.to_dict() for s in self._spans]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


NULL_TRACER = Tracer(enabled=False)


def null_span(name: str = "") -> Span:
    return NULL_TRACER.start_span(name)
