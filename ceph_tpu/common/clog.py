"""Cluster-log client — mirror of src/common/LogClient.{h,cc}.

Every daemon owns a `ClusterLogClient`: structured entries (channel,
severity, entity, per-client seq, optional health code) are batched and
shipped to the monitors' LogMonitor, which commits them through Paxos so
the whole quorum holds one bounded, ordered cluster timeline.

Client-side behaviors mirrored from the reference:

- **Batching** (LogClient::get_mon_log_message): entries accumulate in a
  pending queue and flush as one MLog either when the batch fills or
  after a short linger, so a burst costs one message, not N.
- **Repeat dedup** (LogChannel's "last message repeated N times"):
  consecutive identical (channel, prio, msg) entries collapse into the
  original plus one summary entry when the run breaks or flushes.
- **Rate limiting**: a token bucket caps sustained entries/sec per
  client; drops are counted (`dropped`) and surfaced as a final
  "N cluster log entries dropped (rate limited)" marker so the log
  never silently loses mass without saying so.

The `send` callable is async (MonClient.send_log); daemons pass their
monc's bound method.  Everything is best-effort — a lost entry is
re-emitted by the next transition, so there is no retry queue.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable

# severity names, least to most severe (LogEntry's clog_type)
SEVERITIES = ("debug", "info", "warn", "error")
CHANNELS = ("cluster", "audit")

# batching: flush when this many entries are pending, or after the
# linger elapses — whichever comes first (mon_client_log_interval's
# spirit, scaled to this port's sub-second test clusters)
BATCH_MAX = 32
BATCH_LINGER_SEC = 0.05

# token-bucket rate limiter: sustained entries/sec + burst headroom.
# Generous — the limiter exists to survive a looping daemon, not to
# shave healthy traffic.
RATE_PER_SEC = 50.0
RATE_BURST = 100.0


def severity_rank(prio: str) -> int:
    """Index into SEVERITIES; unknown strings rank as info."""
    try:
        return SEVERITIES.index(prio)
    except ValueError:
        return 1


class ClusterLogClient:
    def __init__(
        self,
        name: str,
        send: Callable[[list[dict]], Awaitable[None]] | None = None,
    ):
        self.name = name
        self._send = send
        self._pending: list[dict] = []
        self._flush_handle: asyncio.TimerHandle | None = None
        self._seq = 0
        # repeat-dedup state: the last entry key and how many times it
        # repeated beyond the first emission
        self._last_key: tuple[str, str, str] | None = None
        self._repeats = 0
        # token bucket
        self._tokens = RATE_BURST
        self._tokens_at = time.monotonic()
        self.dropped = 0
        self._dropped_noted = 0
        # (channel, severity) -> emitted count, for perf/scrape surfaces
        self.counts: dict[tuple[str, str], int] = {}

    # -- public API ------------------------------------------------------------

    def log(
        self,
        prio: str,
        message: str,
        channel: str = "cluster",
        code: str | None = None,
    ) -> None:
        """Queue one structured entry (LogChannel::do_log)."""
        if prio not in SEVERITIES:
            prio = "info"
        if channel not in CHANNELS:
            channel = "cluster"
        key = (channel, prio, message)
        if key == self._last_key:
            # consecutive identical entry: collapse into a repeat count
            self._repeats += 1
            return
        self._break_repeat_run()
        self._last_key = key
        if not self._take_token():
            self.dropped += 1
            return
        self._queue_entry(prio, channel, message, code)

    def debug(self, message: str, **kw) -> None:
        self.log("debug", message, **kw)

    def info(self, message: str, **kw) -> None:
        self.log("info", message, **kw)

    def warn(self, message: str, **kw) -> None:
        self.log("warn", message, **kw)

    def error(self, message: str, **kw) -> None:
        self.log("error", message, **kw)

    def audit(self, message: str, code: str | None = None) -> None:
        """Audit-channel entry: every mutating admin command lands here
        (the reference's `audit` LogChannel fed by the mon's forward of
        each command — here each daemon audits its own admin surface)."""
        self.log("info", message, channel="audit", code=code)

    async def flush(self) -> None:
        """Force-ship everything pending (LogClient::queue drain); used
        by tests and shutdown paths."""
        self._break_repeat_run()
        await self._flush_now()

    # -- internals -------------------------------------------------------------

    def _queue_entry(
        self, prio: str, channel: str, message: str, code: str | None
    ) -> None:
        self._seq += 1
        entry = {
            "prio": prio,
            "channel": channel,
            "who": self.name,
            "seq": self._seq,
            "stamp": time.time(),
            "msg": message,
        }
        if code is not None:
            entry["code"] = code
        self._pending.append(entry)
        k = (channel, prio)
        self.counts[k] = self.counts.get(k, 0) + 1
        self._schedule_flush()

    def _break_repeat_run(self) -> None:
        """Emit the 'last message repeated N times' summary closing a
        run of consecutive identical entries."""
        if self._repeats and self._last_key is not None:
            channel, prio, _msg = self._last_key
            n = self._repeats
            self._repeats = 0
            if self._take_token():
                self._queue_entry(
                    prio, channel, f"last message repeated {n} times", None
                )
            else:
                self.dropped += 1
        else:
            self._repeats = 0

    def _take_token(self) -> bool:
        now = time.monotonic()
        self._tokens = min(
            RATE_BURST, self._tokens + (now - self._tokens_at) * RATE_PER_SEC
        )
        self._tokens_at = now
        if self._tokens < 1.0:
            return False
        self._tokens -= 1.0
        return True

    def _schedule_flush(self) -> None:
        if len(self._pending) >= BATCH_MAX:
            self._kick_flush()
            return
        if self._flush_handle is None:
            try:
                loop = asyncio.get_event_loop()
            except RuntimeError:
                return  # no loop (sync tool context): flush() ships later
            self._flush_handle = loop.call_later(
                BATCH_LINGER_SEC, self._kick_flush
            )

    def _kick_flush(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        try:
            asyncio.get_event_loop().create_task(self._flush_now())
        except RuntimeError:
            pass

    async def _flush_now(self) -> None:
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        if self.dropped > self._dropped_noted:
            n = self.dropped - self._dropped_noted
            self._dropped_noted = self.dropped
            self._seq += 1
            self._pending.append(
                {
                    "prio": "warn",
                    "channel": "cluster",
                    "who": self.name,
                    "seq": self._seq,
                    "stamp": time.time(),
                    "msg": f"{n} cluster log entries dropped (rate limited)",
                }
            )
        if not self._pending or self._send is None:
            return
        batch, self._pending = self._pending, []
        await self._send(batch)

    def perf_dump(self) -> dict:
        """Counters for the daemon perf/scrape surface."""
        return {
            "clog_messages": {
                f"{ch}.{prio}": n for (ch, prio), n in sorted(self.counts.items())
            },
            "clog_dropped": self.dropped,
        }
