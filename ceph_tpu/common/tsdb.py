"""In-memory time-series store — the mgr-resident history substrate
(ISSUE 14; the prometheus-module + healthcheck-history role the
reference keeps in the mgr).

Every observability layer so far answers "what is happening now"; this
store answers "what changed, and when" with three design constraints:

- **Fixed memory.**  Each series holds one bounded ring per resolution
  level: raw samples land in the finest ring and are simultaneously
  folded into coarser min/max/avg/last buckets (classic RRD/whisper
  downsampling), so retention scales with bucket width while footprint
  stays `levels x slots` buckets per series, forever.
- **Bounded cardinality.**  Series are keyed by family + labels with an
  LRU cap: when a new series would exceed `max_series`, the
  least-recently-written series is evicted (counted) — churned daemons
  and departed clients age out the way the iostat module expires idle
  clients, instead of growing the mgr without bound.
- **Lock-cheap.**  One lockdep-named mutex; appends touch O(levels)
  bucket tails, queries copy only the requested window.

The store itself is clock-agnostic: callers pass timestamps (the
metrics-history module feeds `time.monotonic()`), which also keeps the
downsample math deterministic under test.
"""

from __future__ import annotations

from collections import OrderedDict, deque

from .lockdep import make_lock

# accounting estimate per retained bucket: 5 floats + tuple/deque
# overhead.  An estimate (not sys.getsizeof truth) so the bytes gauge is
# deterministic and cheap; the BOUND it witnesses is exact — buckets per
# series are structurally capped.
BYTES_PER_BUCKET = 120
BYTES_PER_SERIES = 256  # key + rings + bookkeeping overhead

AGGREGATES = ("avg", "min", "max", "last", "sum")


class _Bucket:
    """One downsample bucket: [start, start + width) of one series."""

    __slots__ = ("start", "vmin", "vmax", "vsum", "count", "last")

    def __init__(self, start: float, value: float):
        self.start = start
        self.vmin = value
        self.vmax = value
        self.vsum = value
        self.count = 1
        self.last = value

    def fold(self, value: float) -> None:
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        self.vsum += value
        self.count += 1
        self.last = value

    def value(self, aggregate: str) -> float:
        if aggregate == "min":
            return self.vmin
        if aggregate == "max":
            return self.vmax
        if aggregate == "last":
            return self.last
        if aggregate == "sum":
            return self.vsum
        return self.vsum / self.count  # avg

    def dump(self) -> dict:
        return {
            "t": self.start,
            "min": self.vmin,
            "max": self.vmax,
            "avg": self.vsum / self.count,
            "last": self.last,
            "count": self.count,
        }


class _Series:
    """One (family, labels) series: a bounded ring per resolution."""

    __slots__ = ("rings", "last_t", "appends")

    def __init__(self, levels: int, slots: int):
        self.rings: list[deque] = [
            deque(maxlen=slots) for _ in range(levels)
        ]
        self.last_t = 0.0
        self.appends = 0

    def append(self, t: float, value: float, widths: tuple) -> None:
        # a clock-skewed out-of-order sample must not REWIND the
        # series' newest-sample anchor: default-anchored queries
        # (now=None) would shift into the past and drop genuinely
        # newer buckets from the view
        self.last_t = max(self.last_t, t)
        self.appends += 1
        for ring, width in zip(self.rings, widths):
            start = (t // width) * width
            tail = ring[-1] if ring else None
            if tail is not None and tail.start == start:
                tail.fold(value)
            elif tail is not None and start < tail.start:
                # out-of-order sample (a clock-skewed report): fold into
                # the tail rather than corrupting ring ordering
                tail.fold(value)
            else:
                ring.append(_Bucket(start, value))

    def buckets(self) -> int:
        return sum(len(r) for r in self.rings)


def _labels_key(labels: dict | None) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class TimeSeriesStore:
    """Cardinality-bounded multi-resolution store (see module doc)."""

    def __init__(
        self,
        max_series: int = 256,
        slots: int = 360,
        resolutions: tuple[float, ...] = (1.0, 10.0, 60.0),
    ):
        self._lock = make_lock("tsdb")
        self._series: OrderedDict[tuple, _Series] = OrderedDict()
        self._max_series = max(1, int(max_series))
        self._slots = max(2, int(slots))
        self._resolutions = self._parse_resolutions(resolutions)
        self.evictions = 0
        self.appends = 0

    @staticmethod
    def _parse_resolutions(resolutions) -> tuple[float, ...]:
        if isinstance(resolutions, str):
            parts = [p.strip() for p in resolutions.split(",") if p.strip()]
            resolutions = tuple(float(p) for p in parts)
        widths = tuple(sorted(float(w) for w in resolutions if float(w) > 0))
        return widths or (1.0,)

    # -- configuration (runtime-mutable knobs) --------------------------------

    def configure(
        self,
        max_series: int | None = None,
        slots: int | None = None,
        resolutions=None,
    ) -> None:
        """Apply runtime knob changes.  Shrinking `max_series` evicts
        LRU immediately; changing slot count / resolutions rebuilds the
        rings empty (history restarts at the new geometry — the same
        newest-kept contract the flight recorder uses, but a geometry
        change invalidates the downsample alignment entirely)."""
        with self._lock:
            if max_series is not None and int(max_series) > 0:
                self._max_series = int(max_series)
                while len(self._series) > self._max_series:
                    self._series.popitem(last=False)
                    self.evictions += 1
            rebuild = False
            if slots is not None and int(slots) >= 2 and \
                    int(slots) != self._slots:
                self._slots = int(slots)
                rebuild = True
            if resolutions is not None:
                widths = self._parse_resolutions(resolutions)
                if widths != self._resolutions:
                    self._resolutions = widths
                    rebuild = True
            if rebuild:
                self._series.clear()

    @property
    def resolutions(self) -> tuple[float, ...]:
        return self._resolutions

    # -- writes ---------------------------------------------------------------

    def append(
        self, family: str, labels: dict | None, t: float, value: float
    ) -> None:
        key = (family, _labels_key(labels))
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _Series(
                    len(self._resolutions), self._slots
                )
            # LRU order = write recency: churned daemons/clients stop
            # writing and drift to the evictable end
            self._series.move_to_end(key)
            series.append(t, float(value), self._resolutions)
            self.appends += 1
            while len(self._series) > self._max_series:
                self._series.popitem(last=False)
                self.evictions += 1

    # -- queries --------------------------------------------------------------

    def series_ls(self) -> list[dict]:
        """One row per live series: identity + retention shape (the
        `perf history ls` payload)."""
        with self._lock:
            out = []
            for (family, lkey), series in self._series.items():
                # retention is the COARSEST ring's reach: once the fine
                # ring wraps, hours of downsampled history remain
                # queryable — the inventory must not understate it
                oldest = [r[0].start for r in series.rings if r]
                out.append({
                    "family": family,
                    "labels": dict(lkey),
                    "appends": series.appends,
                    "buckets": series.buckets(),
                    "newest_t": series.last_t,
                    "oldest_t": min(oldest) if oldest else None,
                })
            return out

    def _find(self, family: str, labels: dict | None) -> _Series | None:
        return self._series.get((family, _labels_key(labels)))

    def _choose_level(self, series: _Series, start: float) -> int:
        """Finest resolution whose OLDEST retained bucket reaches back
        to `start`.  When no level covers (the window outruns even the
        coarsest retention — OR the series is younger than the window,
        in which case every level holds the same since-birth span), the
        finest ring that retains the series' full observed history
        wins: maximum detail, never an artificially coarse view of a
        young series."""
        fine = series.rings[0]
        birth_covered = bool(fine) and len(fine) < (fine.maxlen or 1)
        for i, ring in enumerate(series.rings):
            if ring and (ring[0].start <= start or (birth_covered and i == 0)):
                return i
        return len(self._resolutions) - 1

    def query(
        self,
        family: str,
        labels: dict | None = None,
        window: float = 300.0,
        step: float = 0.0,
        aggregate: str = "avg",
        now: float | None = None,
    ) -> dict:
        """Re-bucketed view of one series over the trailing `window`
        seconds: picks the finest resolution whose retention covers the
        window, then folds those buckets into `step`-wide output points
        with the requested aggregate (`avg`/`min`/`max`/`last`/`sum`).
        `step` <= 0 returns the chosen resolution's buckets as-is."""
        if aggregate not in AGGREGATES:
            raise ValueError(
                f"aggregate must be one of {AGGREGATES}, got {aggregate!r}"
            )
        with self._lock:
            series = self._find(family, labels)
            if series is None:
                return {
                    "family": family,
                    "labels": dict(labels or {}),
                    "resolution": None,
                    "points": [],
                }
            end = series.last_t if now is None else now
            start = end - max(window, 0.0)
            chosen = self._choose_level(series, start)
            width = self._resolutions[chosen]
            buckets = [
                b for b in series.rings[chosen]
                if b.start + width > start and b.start <= end
            ]
            points: list[list[float]]
            if step and step > 0:
                # structural merge of the source buckets (min/max/
                # sum/count/last compose exactly), so a re-bucketed avg
                # is the true sample-weighted average — never an
                # avg-of-avgs skewed by uneven bucket fill
                folded: OrderedDict[float, _Bucket] = OrderedDict()
                for b in buckets:
                    s = (b.start // step) * step
                    f = folded.get(s)
                    if f is None:
                        f = folded[s] = _Bucket(s, b.last)
                        f.vmin, f.vmax = b.vmin, b.vmax
                        f.vsum, f.count = b.vsum, b.count
                    else:
                        f.vmin = min(f.vmin, b.vmin)
                        f.vmax = max(f.vmax, b.vmax)
                        f.vsum += b.vsum
                        f.count += b.count
                        f.last = b.last
                points = [
                    [s, f.value(aggregate)] for s, f in folded.items()
                ]
            else:
                points = [[b.start, b.value(aggregate)] for b in buckets]
            return {
                "family": family,
                "labels": dict(labels or {}),
                "resolution": width,
                "step": step or width,
                "aggregate": aggregate,
                "points": points,
            }

    def window_value(
        self,
        family: str,
        labels: dict | None,
        start_ago: float,
        end_ago: float,
        aggregate: str = "avg",
        now: float | None = None,
    ) -> float | None:
        """One aggregate over [now - start_ago, now - end_ago) — what
        the trend sentinels compare (recent window vs trailing
        baseline).  None when the series has no bucket in the span."""
        with self._lock:
            series = self._find(family, labels)
            if series is None:
                return None
            end_t = series.last_t if now is None else now
            lo = end_t - start_ago
            hi = end_t - end_ago
            chosen = self._choose_level(series, lo)
            width = self._resolutions[chosen]
            hit = [
                b for b in series.rings[chosen]
                if b.start + width > lo and b.start < hi
            ]
            if not hit:
                return None
            if aggregate == "min":
                return min(b.vmin for b in hit)
            if aggregate == "max":
                return max(b.vmax for b in hit)
            if aggregate == "last":
                return hit[-1].last
            if aggregate == "sum":
                return sum(b.vsum for b in hit)
            return sum(b.vsum for b in hit) / sum(b.count for b in hit)

    # -- accounting -----------------------------------------------------------

    def stats(self) -> dict:
        """The meta-gauges (`ceph_tpu_history_*`): series count, total
        retained buckets, the byte estimate of the bound, eviction and
        append totals."""
        with self._lock:
            buckets = sum(s.buckets() for s in self._series.values())
            return {
                "series": len(self._series),
                "max_series": self._max_series,
                "points": buckets,
                "bytes": (
                    len(self._series) * BYTES_PER_SERIES
                    + buckets * BYTES_PER_BUCKET
                ),
                "evictions": self.evictions,
                "appends": self.appends,
            }
