"""Typed option schema — mirror of the reference's options framework.

Reference: /root/reference/src/common/options/global.yaml.in (~800 typed
options code-generated into md_config_t) and src/common/options.h (Option
struct: name, type, level, default, description, see_also, flags).  This
framework keeps the same shape — a declarative table of typed, leveled,
documented options — scoped to the subsystems this framework implements.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class OptionLevel(enum.Enum):
    """Audience levels (options.h LEVEL_BASIC/ADVANCED/DEV)."""

    BASIC = "basic"
    ADVANCED = "advanced"
    DEV = "dev"


@dataclass(frozen=True)
class Option:
    """One typed option (src/common/options.h Option)."""

    name: str
    type: type  # int | float | bool | str
    default: object
    level: OptionLevel = OptionLevel.ADVANCED
    desc: str = ""
    see_also: tuple[str, ...] = ()
    # Runtime-mutable options notify registered observers on change
    # (md_config_obs_t; e.g. mClockScheduler, src/osd/scheduler/
    # mClockScheduler.h:72).
    runtime: bool = False

    def parse(self, value: object):
        """Coerce a raw (usually string) value to the option's type."""
        if isinstance(value, self.type):
            return value
        s = str(value)
        if self.type is bool:
            if s.lower() in ("true", "1", "yes", "on"):
                return True
            if s.lower() in ("false", "0", "no", "off"):
                return False
            raise ValueError(f"invalid bool for {self.name}: {s!r}")
        return self.type(s)


def _opts(*options: Option) -> dict[str, Option]:
    table: dict[str, Option] = {}
    for o in options:
        if o.name in table:
            raise ValueError(f"duplicate option {o.name}")
        table[o.name] = o
    return table


B = OptionLevel.BASIC
A = OptionLevel.ADVANCED
D = OptionLevel.DEV

# The option table.  Names and defaults follow the reference's
# global.yaml.in / osd.yaml.in where an equivalent exists (cited inline).
OPTIONS: dict[str, Option] = _opts(
    # --- identity / cluster -------------------------------------------------
    Option("name", str, "", B, "entity name, e.g. osd.0 / mon.a / client"),
    Option("fsid", str, "", B, "cluster fsid"),
    Option("mon_host", str, "", B, "comma-separated mon addresses"),
    # --- erasure coding (global.yaml.in:431, :2541; osd.yaml.in) ------------
    Option("erasure_code_dir", str, "", A, "directory for native codec plugins"),
    Option(
        "osd_erasure_code_plugins",
        str,
        "tpu native jerasure lrc shec clay",
        A,
        "space-separated plugins preloaded at OSD boot (global.yaml.in:2541)",
    ),
    Option(
        "osd_pool_erasure_code_stripe_unit",
        int,
        4096,
        A,
        "default stripe unit (bytes) for EC pools (osd.yaml.in)",
    ),
    Option(
        "osd_op_class_load_list",
        str,
        "lock version numops refcount",
        A,
        "object classes preloaded at OSD boot (osd_class_load_list; "
        "others load lazily on first CALL)",
    ),
    Option(
        "osd_pool_default_erasure_code_profile",
        str,
        "plugin=tpu technique=reed_sol_van k=2 m=1",
        A,
        "default EC profile (global.yaml.in)",
    ),
    Option(
        "ec_tpu_aggregate_window",
        int,
        0,
        A,
        "EC encode launch aggregation window: submissions of one "
        "(matrix, chunk-size) geometry held before a coalesced device "
        "launch (codec/matrix_codec.py EncodeAggregator).  <= 1 launches "
        "every submission immediately.  Commit barriers always drain the "
        "window, so a value up to the encode queue depth trades no "
        "durability, only launch count",
        see_also=("ec_tpu_aggregate_max_bytes",),
        runtime=True,
    ),
    Option(
        "ec_tpu_aggregate_max_bytes",
        int,
        64 << 20,
        A,
        "input-byte budget per aggregation group: a group launches as "
        "soon as its queued stripe bytes reach this, whatever the window "
        "(bounds device memory held by deferred encodes)",
        see_also=("ec_tpu_aggregate_window",),
        runtime=True,
    ),
    Option(
        "ec_tpu_decode_aggregate_window",
        int,
        0,
        A,
        "EC decode launch aggregation window: recovery/degraded-read "
        "decodes of one (decode-matrix, chunk-size) signature held before "
        "a coalesced device launch (codec/matrix_codec.py "
        "DecodeAggregator).  <= 1 launches every submission immediately.  "
        "Recovery drains its decode pipeline at every barrier, so a value "
        "up to the decode queue depth trades no correctness, only launch "
        "count during backfill/recovery",
        see_also=("ec_tpu_decode_aggregate_max_bytes",
                  "ec_tpu_aggregate_window"),
        runtime=True,
    ),
    Option(
        "ec_tpu_decode_aggregate_max_bytes",
        int,
        64 << 20,
        A,
        "survivor-byte budget per decode aggregation group: a group "
        "launches as soon as its queued survivor bytes reach this, "
        "whatever the window (bounds device memory held by deferred "
        "recovery decodes)",
        see_also=("ec_tpu_decode_aggregate_window",),
        runtime=True,
    ),
    Option(
        "ec_tpu_verify_aggregate_window",
        int,
        64,
        A,
        "EC verify launch aggregation window: deep-scrub parity "
        "recompute submissions of one (matrix, chunk-size) geometry held "
        "before a coalesced compare-only device launch "
        "(codec/matrix_codec.py VerifyAggregator).  <= 1 launches every "
        "submission immediately.  Scrub has no commit barrier, so the "
        "window is open by default — the scrubber's per-chunk bitmap "
        "reap is the flush",
        see_also=("ec_tpu_verify_aggregate_max_bytes",
                  "ec_tpu_aggregate_window"),
        runtime=True,
    ),
    Option(
        "ec_tpu_verify_aggregate_max_bytes",
        int,
        64 << 20,
        A,
        "codeword-byte budget per verify aggregation group: a group "
        "launches as soon as its queued scrub bytes reach this, whatever "
        "the window (bounds device memory held by deferred verifies)",
        see_also=("ec_tpu_verify_aggregate_window",),
        runtime=True,
    ),
    # --- EC launch scheduler QoS (ISSUE 9; ops/launch_scheduler.py) ---------
    # dmClock (reservation, weight, limit) per launch lane, in nominal
    # 4 KiB items/sec (a launch of N bytes costs N/4096 items).  The
    # scheduler is work-conserving: limits deprioritize, never idle the
    # device.  0 = unset (no reservation / unlimited).
    Option("ec_tpu_sched_client_res", float, 25600.0, A,
           "launch-scheduler reservation for the client lane (encode "
           "launches), in nominal 4 KiB items/sec: matured reservations "
           "dequeue before any weight-phase launch.  A launch of N bytes "
           "consumes N/4096 items, so the rate must be launch-scaled to "
           "matter — the default 25600 guarantees ~100 MiB/s of client "
           "launch bandwidth (a 64 MiB launch advances the reservation "
           "tag 0.64 s); a per-op-scale value like 1.0 would push the "
           "tag hours into the future on the first aggregated launch "
           "and never mature again", runtime=True),
    Option("ec_tpu_sched_client_wgt", float, 2.0, A,
           "launch-scheduler weight for the client lane", runtime=True),
    Option("ec_tpu_sched_client_lim", float, 0.0, A,
           "launch-scheduler limit for the client lane (0 = unlimited)",
           runtime=True),
    Option("ec_tpu_sched_recovery_res", float, 0.0, A,
           "launch-scheduler reservation for the recovery lane (decode "
           "launches), in nominal 4 KiB items/sec (launch-scaled, see "
           "ec_tpu_sched_client_res); 0 = no reservation", runtime=True),
    Option("ec_tpu_sched_recovery_wgt", float, 1.0, A,
           "launch-scheduler weight for the recovery lane", runtime=True),
    Option("ec_tpu_sched_recovery_lim", float, 0.0, A,
           "launch-scheduler limit for the recovery lane (0 = unlimited)",
           runtime=True),
    Option("ec_tpu_sched_background_res", float, 0.0, A,
           "launch-scheduler reservation for the background lane "
           "(deep-scrub verify, best-effort work), in nominal 4 KiB "
           "items/sec (launch-scaled, see ec_tpu_sched_client_res); "
           "0 = no reservation", runtime=True),
    Option("ec_tpu_sched_background_wgt", float, 0.5, A,
           "launch-scheduler weight for the background lane: under "
           "contention a queued client encode dequeues ahead of a "
           "queued scrub verify; when the queue is otherwise idle the "
           "background lane drains at full device speed "
           "(work-conserving)", runtime=True),
    Option("ec_tpu_sched_background_lim", float, 0.0, A,
           "launch-scheduler limit for the background lane (0 = "
           "unlimited; a nonzero value deprioritizes scrub launches "
           "past the rate without ever idling the device)",
           runtime=True),
    Option(
        "ec_tpu_launch_timeout_ms",
        int,
        20000,
        A,
        "per-launch deadline (ms) for EC device dispatches and their "
        "blocking materialization, enforced by a watchdog thread "
        "(ops/guard.py DeviceGuard).  A launch that exceeds it marks the "
        "backend DEGRADED and re-runs on the byte-identical host oracle "
        "(gf/bitslice.py) so in-flight writes/recoveries complete instead "
        "of chain-aborting behind a wedged TPU.  <= 0 disables the "
        "watchdog (launches may block forever, the pre-ISSUE-7 behavior)",
        see_also=("ec_tpu_probe_interval_ms",),
        runtime=True,
    ),
    Option(
        "ec_tpu_probe_interval_ms",
        int,
        2000,
        A,
        "while DEGRADED, re-probe the device backend with a tiny compile "
        "probe at most this often (ms); a probe that completes under the "
        "launch deadline self-heals dispatch back to the TPU path and "
        "clears the TPU_BACKEND_DEGRADED health check.  <= 0 disables "
        "re-probing (degraded mode is then sticky until restart)",
        see_also=("ec_tpu_launch_timeout_ms",),
        runtime=True,
    ),
    Option(
        "ec_tpu_inflight_max_bytes",
        int,
        256 << 20,
        A,
        "end-to-end backpressure bound: input bytes admitted into the EC "
        "launch aggregators (windowed + launched-but-unreaped) before a "
        "new submission must first settle older launches.  Bounds the "
        "memory a degraded/slow backend can queue behind itself and "
        "pushes back on submitters instead of growing the window "
        "unboundedly.  <= 0 disables admission control",
        see_also=("ec_tpu_aggregate_max_bytes",
                  "ec_tpu_decode_aggregate_max_bytes"),
        runtime=True,
    ),
    Option(
        "ec_tpu_pipeline_depth",
        int,
        2,
        A,
        "depth of the asynchronous device-launch pipeline (ISSUE 11): "
        "how many aggregated launches may be in flight (dispatched, not "
        "yet settled) before a new launch first settles the oldest.  At "
        "depth >= 2 window N+1's H2D staging overlaps window N's kernel "
        "— the overlap the flight recorder's idle gaps pointed at.  The "
        "settle order is oldest-first, and the donation pool's per-slot "
        "refcounts guarantee an in-flight launch's output buffer is "
        "never recycled early.  <= 0 disables the ring (in-flight "
        "launches bounded only by ec_tpu_inflight_max_bytes, the "
        "pre-ISSUE-11 behavior)",
        see_also=("ec_tpu_inflight_max_bytes", "ec_tpu_aggregate_window"),
        runtime=True,
    ),
    Option(
        "ec_tpu_fuse_max_windows",
        int,
        4,
        A,
        "super-launch fusion bound (ISSUE 18): when the in-flight launch "
        "ring (ec_tpu_pipeline_depth) is full as an aggregation window "
        "trips, the group keeps accumulating up to this many whole "
        "windows and launches them as ONE fused multi-window dispatch — "
        "amortizing the fixed dispatch overhead exactly when the backlog "
        "proves demand.  Per-ticket settle slices, QoS arbitration and "
        "the host-oracle fallback are unchanged; fused launches count on "
        "fused_launches/fused_windows and flag `fused` on their flight "
        "records.  <= 1 disables fusion (every window trip launches "
        "immediately)",
        see_also=("ec_tpu_pipeline_depth", "ec_tpu_aggregate_window"),
        runtime=True,
    ),
    Option(
        "ec_tpu_pad_buckets",
        int,
        4,
        A,
        "learned pad-bucket slots per aggregation group key (ISSUE 18): "
        "a batch size the key's workload produces repeatedly is promoted "
        "to an exact-fit launch target instead of rounding up to the "
        "static pow2/64-multiple bucket, cutting zero-pad stripes on "
        "recurring sizes while the bounded, LRU-evicted slot set keeps "
        "the jit-cache geometry count capped (evicted targets drop "
        "their pooled output buffers so bucket churn cannot pin HBM).  "
        "Waste is exported as padding_waste_ratio / pad_waste.<label>.  "
        "<= 0 keeps the static buckets only",
        see_also=("ec_tpu_aggregate_window",),
        runtime=True,
    ),
    Option(
        "ec_tpu_rmw_delta",
        bool,
        True,
        A,
        "on-device RMW delta-encode path (ISSUE 18): when every operand "
        "of a read-modify-write — the k pre-write data chunks AND the m "
        "parity chunks — is resident in the device chunk cache at the "
        "op's pre-write generation, parity is updated IN HBM via the "
        "GF(2)-linear delta program (parity_new = parity_old xor "
        "Encode(data_old xor data_new), the same chosen XOR schedule as "
        "a full encode) — one launch, zero H2D and zero D2H on the "
        "flight record, byte-identical to the host-oracle RMW.  Any "
        "cache miss or a DEGRADED backend falls back to the existing "
        "materialize path",
        see_also=("ec_tpu_device_cache_bytes",),
        runtime=True,
    ),
    Option(
        "ec_tpu_device_cache_bytes",
        int,
        32 << 20,
        A,
        "device-resident chunk cache bound (ISSUE 11): recently "
        "encoded/decoded chunk buffers kept in HBM keyed by (object, "
        "shard, generation), consulted by the RMW read-modify path and "
        "degraded reads BEFORE issuing H2D — a repeated degraded read "
        "of a hot object serves its missing chunks with one D2H copy "
        "and no launch.  Invalidated on overwrite and cleared on a "
        "DEGRADED backend transition; hit/miss/evict counters ride the "
        "ec_dispatch perf dump (ceph_tpu_ec_dispatch_cache_*).  <= 0 "
        "disables the cache",
        see_also=("ec_tpu_pipeline_depth",),
        runtime=True,
    ),
    Option(
        "ec_tpu_mempool_debug",
        bool,
        False,
        A,
        "shard HBM mempool ledger counts by allocation call-site "
        "(common/mempool.py, ISSUE 13), like the reference's mempool "
        "debug mode: asok dump_mempools then breaks each pool down by "
        "the file:line that allocated the bytes.  Costs one stack walk "
        "per tracked allocation; off by default",
        see_also=("ec_tpu_hbm_target_bytes",),
        runtime=True,
    ),
    Option(
        "ec_tpu_hbm_target_bytes",
        int,
        0,
        A,
        "HBM residency target for the mempool pressure layer (ISSUE 13; "
        "the osd_memory_target analog for device memory).  When total "
        "ledger-tracked bytes exceed 85% of the target the staged "
        "response engages — trim the device-resident chunk cache, then "
        "cap donation-pool retention, then clamp the effective pipeline "
        "depth to 1 — and TPU_HBM_PRESSURE raises through the OSD "
        "status -> mgr digest -> mon health pipeline, clearing (and "
        "releasing the caps) once residency falls back under 70%.  "
        "0 disables pressure evaluation entirely",
        see_also=("ec_tpu_mempool_debug", "ec_tpu_device_cache_bytes",
                  "ec_tpu_pipeline_depth"),
        runtime=True,
    ),
    Option(
        "ec_tpu_flight_records",
        int,
        512,
        A,
        "launch flight-recorder ring capacity (ops/flight_recorder.py): "
        "completed per-launch records retained for the asok dump_flight "
        "command and tools/trace_export.py timelines.  Resizing at "
        "runtime keeps the newest records; the ring is the memory bound "
        "— each record is a small flat dict",
        see_also=("ec_tpu_aggregate_window",),
        runtime=True,
    ),
    # --- workload attribution + SLOs (ISSUE 10; mgr/iostat.py) --------------
    Option("mgr_iostat_window_sec", float, 10.0, A,
           "iostat rate window: per-pool/per-client IOPS, bytes/sec and "
           "windowed p99 are computed over the last this-many seconds "
           "of merged OSD reports (EMA-smoothed like the progress "
           "module's rates)", runtime=True),
    Option("mgr_iostat_top_clients", int, 10, A,
           "how many clients the iostat module ranks in its "
           "top-by-IOPS/bytes/p99 views (mgr asok `iostat top`, mon "
           "`status`, and the ceph_tpu_top_client_* scrape families — "
           "the scrape cardinality bound)", runtime=True),
    Option("mgr_slo_latency_target_ms", float, 0.0, A,
           "default per-pool op latency SLO target in milliseconds: the "
           "objective is `mgr_slo_objective` of ops under this latency. "
           "0 disables SLO evaluation.  Per-pool overrides via "
           "mgr_slo_pool_latency_targets",
           see_also=("mgr_slo_pool_latency_targets", "mgr_slo_objective"),
           runtime=True),
    Option("mgr_slo_pool_latency_targets", str, "", A,
           "per-pool latency-target overrides as comma-separated "
           "`<pool id or name>:<ms>` entries, e.g. `rbd:50,7:10`; pools "
           "not listed use mgr_slo_latency_target_ms",
           see_also=("mgr_slo_latency_target_ms",), runtime=True),
    Option("mgr_slo_objective", float, 0.99, A,
           "latency SLO objective: the target fraction of ops under the "
           "pool's latency target; the error budget is 1 - objective "
           "and burn rate = observed bad fraction / error budget",
           runtime=True),
    Option("mgr_slo_burn_threshold", float, 1.0, A,
           "burn-rate threshold: SLO_LATENCY_BREACH raises when BOTH "
           "the fast and slow windows burn above this (the multi-window "
           "burn-rate alert shape: the fast window confirms it is "
           "happening now, the slow window that it is not a blip); "
           "clears when either window drops back under", runtime=True),
    Option("mgr_slo_fast_window_sec", float, 10.0, A,
           "fast burn-rate window (seconds)",
           see_also=("mgr_slo_slow_window_sec",), runtime=True),
    Option("mgr_slo_slow_window_sec", float, 60.0, A,
           "slow burn-rate window (seconds)",
           see_also=("mgr_slo_fast_window_sec",), runtime=True),
    # --- metrics history + trend sentinels (ISSUE 14; common/tsdb.py,
    # --- mgr/metrics_history.py) --------------------------------------------
    Option("mgr_history_max_series", int, 256, A,
           "cardinality cap of the mgr-resident time-series store "
           "(common/tsdb.py): when a new series would exceed it, the "
           "least-recently-written series is evicted — churned daemons "
           "and departed clients age out instead of growing the mgr "
           "without bound.  Evictions are counted on the "
           "ceph_tpu_history_evictions counter", runtime=True),
    Option("mgr_history_ring_slots", int, 360, A,
           "downsample buckets retained per resolution level per "
           "series: with the default 1s/10s/60s resolutions, 360 slots "
           "keep ~6 minutes of raw samples, an hour at 10 s, and six "
           "hours at 1 min — in fixed memory per series",
           see_also=("mgr_history_resolutions",), runtime=True),
    Option("mgr_history_resolutions", str, "1,10,60", A,
           "comma-separated downsample bucket widths in seconds, "
           "finest first; raw samples land in the finest ring and fold "
           "into min/max/avg/last buckets at each coarser width.  "
           "Changing this at runtime restarts the history at the new "
           "geometry", see_also=("mgr_history_ring_slots",), runtime=True),
    Option("mgr_trend_window_sec", float, 15.0, A,
           "recent window the trend sentinels average over; compared "
           "against the trailing mgr_trend_baseline_sec window that "
           "precedes it.  Sentinels hold fire until a full "
           "window + baseline of genuinely observed history exists "
           "(mgr failover never alarms on imported totals)",
           see_also=("mgr_trend_baseline_sec",), runtime=True),
    Option("mgr_trend_baseline_sec", float, 60.0, A,
           "trailing baseline window the trend sentinels compare the "
           "recent window against", see_also=("mgr_trend_window_sec",),
           runtime=True),
    Option("mgr_trend_regression_ratio", float, 0.5, A,
           "TPU_THROUGHPUT_REGRESSION threshold: the check raises when "
           "recent encode/decode GB/s falls below this fraction of the "
           "trailing baseline while launch volume persists (>= "
           "mgr_trend_min_launch_rate and >= half the baseline launch "
           "cadence — a load DROP is not a regression).  <= 0 disables "
           "the sentinel", see_also=("mgr_trend_min_launch_rate",),
           runtime=True),
    Option("mgr_trend_occupancy_ratio", float, 0.5, A,
           "TPU_OCCUPANCY_COLLAPSE threshold: raises when recent device "
           "occupancy falls below this fraction of the trailing "
           "baseline under sustained launch volume.  <= 0 disables the "
           "sentinel", runtime=True),
    Option("mgr_trend_queue_wait_factor", float, 3.0, A,
           "TPU_QUEUE_WAIT_INFLATION threshold: raises when the recent "
           "mean launch queue-wait exceeds this multiple of the "
           "trailing baseline (baseline floored at 1 ms, so a "
           "near-zero-wait baseline requires factor x 1 ms) under "
           "sustained launch volume.  <= 0 disables the sentinel",
           runtime=True),
    Option("mgr_trend_min_launch_rate", float, 0.1, A,
           "launch-volume floor (launches/sec over BOTH trend windows) "
           "below which NO trend sentinel evaluates — an idle or "
           "draining cluster has trends worth graphing, not alarming "
           "on, and an idle baseline is nothing to regress from",
           see_also=("mgr_trend_regression_ratio",), runtime=True),
    Option(
        "mgr_progress_stall_sec",
        float,
        60.0,
        A,
        "PG_RECOVERY_STALLED window (mgr/progress.py): a PG whose "
        "recovery/backfill event reports no objects/bytes advance for "
        "this many seconds raises the health warning; it clears on the "
        "next observed advance (or event completion).  <= 0 disables "
        "the check",
        runtime=True,
    ),
    Option(
        "ec_tpu_shard_min_batch",
        int,
        32,
        A,
        "minimum stripe count before a coding launch (aggregated or bulk) "
        "shards data-parallel over the device mesh (parallel/dispatch.py); "
        "smaller launches stay single-device — a sharded dispatch pays a "
        "sharded H2D placement and a per-mesh compile, pure overhead for "
        "the few-stripe writes the aggregation window already coalesces",
        see_also=("ec_tpu_shard_devices", "ec_tpu_aggregate_window"),
        runtime=True,
    ),
    Option(
        "ec_tpu_shard_devices",
        int,
        0,
        A,
        "device-mesh width for sharded coding launches: 0 = every visible "
        "device, 1 disables sharding entirely, N caps the mesh at the "
        "first N devices (a pod slice reserved for serving can be kept "
        "out of bulk recovery launches)",
        see_also=("ec_tpu_shard_min_batch",),
        runtime=True,
    ),
    # --- OSD ----------------------------------------------------------------
    Option("osd_recovery_max_chunk", int, 8 << 20, A,
           "max recovery push size; rounded to stripe (ECBackend.h:206)"),
    Option("osd_recovery_max_active", int, 3, A,
           "max concurrent recovery ops per OSD"),
    Option("osd_recovery_push_retry_sec", float, 5.0, A,
           "re-send pending recovery PushOps whose target has not "
           "acked for this many seconds (ECBackend.retry_stalled_pushes, "
           "tick-driven): a push a dying target dropped cannot park its "
           "RecoveryOp in WRITING forever.  Re-applying a landed push is "
           "idempotent.  <= 0 disables the retry (the pre-ISSUE-15 "
           "behavior)", runtime=True),
    # --- recovery-storm controller (ISSUE 15; osd/recovery_controller.py) ---
    Option("osd_recovery_storm_min_objects", int, 8, A,
           "outstanding missing objects across this OSD's primaried PGs "
           "before the recovery-storm controller engages: below it the "
           "per-PG osd_recovery_max_active trickle is the right tool; at "
           "or above it the controller batches cross-PG reconstruction "
           "into mesh-wide decode waves",
           see_also=("osd_recovery_storm_wave_objects",), runtime=True),
    Option("osd_recovery_storm_wave_objects", int, 16, A,
           "max objects admitted per recovery-storm wave (the adaptive "
           "wave size's ceiling): one wave's decodes coalesce through "
           "the DecodeAggregator into few padded launches on the "
           "recovery QoS lane.  Admission adapts between "
           "osd_recovery_storm_min_wave_objects and this ceiling on the "
           "live client burn rate", runtime=True,
           see_also=("osd_recovery_storm_min_wave_objects",
                     "osd_recovery_storm_burn_threshold")),
    Option("osd_recovery_storm_min_wave_objects", int, 2, A,
           "adaptive wave-size floor under SLO shedding: even a pool "
           "burning its latency budget keeps rebuilding at this trickle "
           "(availability beats a perfectly idle rebuild)",
           see_also=("osd_recovery_storm_wave_objects",), runtime=True),
    Option("osd_recovery_storm_max_inflight", int, 32, A,
           "bounded wave depth: objects mid-recovery across ALL "
           "primaried PGs before the controller stops admitting new "
           "waves (the cross-PG analog of osd_recovery_max_active)",
           runtime=True),
    Option("osd_recovery_storm_slo_target_ms", float, 0.0, A,
           "client-op latency target (ms) the storm admission loop "
           "evaluates the LOCAL burn rate against, from this OSD's own "
           "io-accounting histograms (the iostat/SLO layer's per-OSD "
           "input): ops slower than this eat the error budget.  0 "
           "disables admission feedback — waves always ramp to the "
           "ceiling", see_also=("osd_recovery_storm_burn_threshold",
                                "mgr_slo_latency_target_ms"),
           runtime=True),
    Option("osd_recovery_storm_slo_objective", float, 0.99, A,
           "fraction of client ops that must land under the storm SLO "
           "target; the error budget is 1 - objective and burn rate = "
           "observed bad fraction / error budget (the "
           "mgr_slo_objective shape, evaluated OSD-locally per tick)",
           see_also=("osd_recovery_storm_slo_target_ms",), runtime=True),
    Option("osd_recovery_storm_burn_threshold", float, 1.0, A,
           "local burn rate above which the storm SHEDS (halves the "
           "wave toward the floor) and at/below which it RAMPS (doubles "
           "toward the ceiling) — the SLO_LATENCY_BREACH-risk feedback "
           "that keeps a whole-OSD rebuild from eating client p99",
           see_also=("osd_recovery_storm_slo_target_ms",), runtime=True),
    Option("osd_max_backfills", int, 1, A, "max concurrent backfills",
           runtime=True),
    Option("osd_min_pg_log_entries", int, 250, A,
           "entries kept after a trim (PGLog floor)"),
    Option("osd_max_pg_log_entries", int, 500, A,
           "trim threshold (PGLog ceiling)"),
    Option("osd_backfill_scan_max", int, 64, A,
           "objects per backfill scan chunk", runtime=True),
    Option("osd_op_num_shards", int, 4, A,
           "op queue shards (OSD.h sharded op queue)"),
    Option("osd_op_history_size", int, 20, A,
           "completed ops kept for dump_historic_ops (TrackedOp.h)",
           runtime=True),
    Option("osd_op_complaint_time", float, 30.0, A,
           "in-flight ops older than this count as slow requests "
           "(osd.yaml.in osd_op_complaint_time; feeds SLOW_OPS health)",
           runtime=True),
    Option("osd_op_num_threads_per_shard", int, 2, A, ""),
    Option("osd_heartbeat_interval", float, 1.0, A,
           "seconds between OSD->OSD pings (osd.yaml.in, scaled down)"),
    Option("osd_heartbeat_grace", float, 6.0, A,
           "seconds without reply before reporting failure "
           "(OSDMonitor.cc:3240)", runtime=True),
    # --- gray-failure tolerance (ISSUE 17; osd/ec_backend.py hedging,
    # --- osd laggy detection) -----------------------------------------------
    Option("osd_ec_hedge_quantile", float, 3.0, A,
           "hedge trigger as a multiple of the shard source's EWMA "
           "sub-read latency: an outstanding EC sub-read older than "
           "quantile x the peer's smoothed round-trip (floored at "
           "osd_ec_hedge_min_ms) triggers one speculative read to an "
           "unused shard source; first k replies win through the "
           "redundant-read escalation path, the loser is reaped when "
           "its tid completes.  <= 0 disables hedging",
           see_also=("osd_ec_hedge_min_ms",
                     "osd_ec_hedge_budget_percent"), runtime=True),
    Option("osd_ec_hedge_min_ms", float, 10.0, A,
           "floor (ms) under the EWMA-scaled hedge threshold: sub-reads "
           "younger than this never hedge, so microsecond-fast healthy "
           "clusters do not hedge on scheduling noise",
           see_also=("osd_ec_hedge_quantile",), runtime=True),
    Option("osd_ec_hedge_budget_percent", float, 5.0, A,
           "token-bucket hedge budget as a percentage of issued "
           "sub-reads (burst = 10 tokens): each sub-read earns "
           "percent/100 of a token, each hedge spends one, and an empty "
           "bucket falls back to plain waiting — a cluster-wide "
           "slowdown cannot melt itself with speculative load.  "
           "<= 0 removes the cap",
           see_also=("osd_ec_hedge_quantile",), runtime=True),
    Option("osd_heartbeat_slow_factor", float, 8.0, A,
           "laggy-peer threshold: a peer whose EWMA ping RTT (or EC "
           "sub-read service time) inflates past this multiple of the "
           "cluster-median peer RTT (floored at 10 ms absolute) is "
           "reported to the mon as LAGGY — a non-fatal OSD_SLOW_PEER "
           "health warn, never an auto-down/out; primaries deprioritize "
           "the peer as an EC read source and hedge it preemptively.  "
           "The report clears when the peer's RTT recovers.  <= 0 "
           "disables laggy detection",
           see_also=("osd_heartbeat_grace",
                     "osd_ec_hedge_quantile"), runtime=True),
    Option("osd_scrub_interval", float, 0.0, A,
           "periodic scrub interval; 0 disables the timer"),
    Option("osd_pool_default_pg_num", int, 8, B, ""),
    Option("osd_client_op_priority", int, 63, A, "", runtime=True),
    Option("osd_recovery_op_priority", int, 3, A, "", runtime=True),
    Option("osd_op_queue", str, "mclock_scheduler", A,
           "op scheduler: mclock_scheduler | wpq "
           "(osd/scheduler/OpScheduler)"),
    Option("osd_fast_read", bool, False, A,
           "issue k+m reads, first k win (pool fast_read default)"),
    # --- mClock QoS (osd/scheduler/mClockScheduler.h:72) --------------------
    Option("osd_mclock_client_res", float, 1.0, A, "", runtime=True),
    Option("osd_mclock_client_wgt", float, 2.0, A, "", runtime=True),
    Option("osd_mclock_client_lim", float, 0.0, A, "", runtime=True),
    Option("osd_mclock_recovery_res", float, 0.0, A, "", runtime=True),
    Option("osd_mclock_recovery_wgt", float, 1.0, A, "", runtime=True),
    Option("osd_mclock_recovery_lim", float, 3.0, A, "", runtime=True),
    # --- monitor ------------------------------------------------------------
    Option("mon_lease", float, 5.0, A, "paxos lease seconds (Paxos.h)"),
    Option("mon_tick_interval", float, 1.0, A, ""),
    Option("mon_osd_min_down_reporters", int, 2, A,
           "distinct reporters needed to mark an osd down "
           "(OSDMonitor.cc can_mark_down quorum; reference default 2)"),
    Option("mon_osd_reporter_subtree_level", str, "host", A, ""),
    Option("mon_osd_down_out_interval", float, 30.0, A,
           "seconds down before an osd is marked out"),
    # --- mon flap dampening (ISSUE 15; mon/osd_monitor.py) ------------------
    Option("mon_osd_flap_window", float, 300.0, A,
           "seconds a markdown stays in an OSD's recent-flap history: "
           "the down->out grace for an OSD with N markdowns inside the "
           "window is mon_osd_down_out_interval * "
           "mon_osd_flap_backoff^(N-1), so a flapping OSD earns an "
           "exponentially longer grace instead of re-triggering "
           "peering storms on every bounce.  <= 0 disables dampening "
           "(every markdown uses the base interval)",
           see_also=("mon_osd_flap_backoff",
                     "mon_osd_down_out_interval"), runtime=True),
    Option("mon_osd_flap_backoff", float, 2.0, A,
           "grace multiplier per recent markdown beyond the first "
           "(exponent capped at 8); 1.0 disables the growth",
           see_also=("mon_osd_flap_window",), runtime=True),
    Option("mon_osd_flap_max_auto_out_per_tick", int, 4, A,
           "auto-out churn cap: at most this many OSDs are marked out "
           "per down-out sweep tick — a rack-wide blip cannot remap "
           "the whole map in one epoch; the remainder keep their "
           "down-clock and go out on later ticks.  <= 0 removes the "
           "cap", see_also=("mon_osd_down_out_interval",), runtime=True),
    Option("mon_log_max", int, 500, A,
           "committed cluster-log entries each mon retains (the `log "
           "last` tail; mon/log_monitor.py).  Entries past the bound "
           "age out oldest-first on the next commit; lowering it at "
           "runtime trims immediately, raising it lets the tail grow. "
           "History beyond the bound lives only in daemon logs",
           runtime=True),
    # --- messenger (global.yaml.in:1240-1271 fault injection) ---------------
    Option("ms_type", str, "async+posix", A,
           "messenger stack: async+posix (TCP) or async+inproc "
           "(in-process pipes, kernel-bypass for one-host topologies)"),
    Option("ms_crc_data", bool, True, A, "crc32c-protect frame payloads"),
    Option("ms_inject_socket_failures", int, 0, D,
           "1-in-N chance of injected connection failure "
           "(global.yaml.in:1240)", runtime=True),
    Option("ms_inject_internal_delays", float, 0.0, D,
           "injected delay seconds in delivery (global.yaml.in:1271)",
           runtime=True),
    Option("ms_dispatch_throttle_bytes", int, 100 << 20, A, ""),
    Option("ms_secure", bool, False, A,
           "require AES-GCM-encrypted sessions (ms_*_mode=secure analog); "
           "needs a keyring for the cephx-derived session key"),
    Option("ms_compress", bool, False, A,
           "compress on-wire frames when the peer supports it"),
    Option("keyring", str, "", A,
           "keyring file for cephx (daemon identity + peer verification)"),
    # --- objectstore --------------------------------------------------------
    Option("osd_objectstore", str, "memstore", A,
           "objectstore backend: memstore | filestore | bluestore"),
    Option("osd_data", str, "", A,
           "data directory for persistent stores (empty = in-memory)"),
    Option("bluestore_compression_algorithm", str, "none", A,
           "blob compression: none | zlib | zstd | device "
           "(src/compressor plugin family; bluestore_compression_algorithm; "
           "`device` is the batched byte-plane transpose + zero-run "
           "elision plugin riding the offload runtime, compressor/device.py)"),
    Option("bluestore_compression_required_ratio", float, 0.875, A,
           "store compressed only when compressed/raw <= this ratio"),
    Option(
        "bluestore_csum_offload",
        bool,
        False,
        A,
        "compute BlueStore per-block crc32c on the device through the "
        "offload runtime (ops/checksum_offload.py ChecksumAggregator, "
        "background lane): large-write stored-form checksums and batched "
        "read-verify ride coalesced bit-matrix launches, with the "
        "byte-identical utils/crc32c host oracle under faults/DEGRADED.  "
        "Off = every checksum on the host table loop",
        see_also=("bluestore_csum_offload_window",
                  "bluestore_csum_offload_max_bytes"),
        runtime=True,
    ),
    Option(
        "bluestore_csum_offload_window",
        int,
        64,
        A,
        "checksum/compressor offload aggregation window: same-length "
        "block batches held before a coalesced device launch "
        "(ChecksumAggregator / CompressAggregator).  <= 1 launches every "
        "submission immediately.  Store reaps drain the window, so the "
        "value trades no durability, only launch count",
        see_also=("bluestore_csum_offload",
                  "bluestore_csum_offload_max_bytes"),
        runtime=True,
    ),
    Option(
        "bluestore_csum_offload_max_bytes",
        int,
        64 << 20,
        A,
        "input-byte budget per checksum/compressor aggregation group: a "
        "group launches as soon as its queued block bytes reach this, "
        "whatever the window (bounds device memory held by deferred "
        "csum/compress launches)",
        see_also=("bluestore_csum_offload_window",),
        runtime=True,
    ),
    Option("memstore_device_bytes", int, 1 << 30, A, ""),
    # --- logging (src/log) --------------------------------------------------
    Option("log_file", str, "", B, "empty = stderr"),
    Option("log_max_recent", int, 500, A,
           "in-memory ring entries kept for crash dump (Log.h)"),
    Option("debug_osd", str, "1/5", A, "log/gather levels for subsystem osd"),
    Option("debug_mon", str, "1/5", A, ""),
    Option("debug_ms", str, "0/5", A, ""),
    Option("debug_ec", str, "1/5", A, ""),
    Option("debug_objecter", str, "0/5", A, ""),
    Option("debug_crush", str, "0/5", A, ""),
    Option("debug_paxos", str, "1/5", A, ""),
    Option("debug_objectstore", str, "0/5", A, ""),
    # --- admin socket (src/common/admin_socket.h:106) -----------------------
    Option("admin_socket", str, "", A,
           "unix socket path; empty disables the admin socket"),
    # --- tracing (src/common/tracer.h) --------------------------------------
    Option("jaeger_tracing_enable", bool, False, A,
           "record spans through the EC data path in the in-process tracer "
           "(default off, matching the reference)", runtime=True),
    Option("op_trace_sample_rate", float, 1.0, A,
           "head-sampling probability for op traces (ISSUE 10): the "
           "retention decision is made once at the client/messenger "
           "entry and carried on the message envelope so every "
           "downstream span honors it.  Sampled-out ops still register "
           "in the OpTracker (SLOW_OPS accounting is never sampled) and "
           "still keep their FULL trace if they exceed the complaint "
           "age or error (tail-based always-keep).  1.0 = record "
           "everything (pre-sampling behavior)",
           see_also=("op_trace_budget_per_sec", "jaeger_tracing_enable"),
           runtime=True),
    Option("op_trace_budget_per_sec", float, 0.0, A,
           "token-bucket retention budget: head-sampled traces retained "
           "per second (burst = one second's worth).  Rate-accepted "
           "traces that find the bucket empty fall back to provisional "
           "(tail-keep still rescues slow/errored ops), so always-on "
           "tracing under the traffic harness cannot exceed this span "
           "budget.  <= 0 = unlimited",
           see_also=("op_trace_sample_rate",), runtime=True),
    # --- mgr modules --------------------------------------------------------
    Option("telemetry_salt", str, "", A,
           "cluster-persistent salt for the telemetry report's anonymized "
           "cluster id; set once (e.g. via the central config DB) so reports "
           "stay correlated across mgr failovers.  Empty -> a per-mgr random "
           "salt (ids change on failover).  Mirrors the reference telemetry "
           "module's persisted report id.", runtime=True),
    # --- fault injection ----------------------------------------------------
    Option("heartbeat_inject_failure", float, 0.0, D,
           "seconds to pretend heartbeats fail (global.yaml.in:865)",
           runtime=True),
)
