"""Lock-order validation — mirror of src/common/lockdep.{h,cc}.

The reference's lockdep (enabled in debug builds, CMakeLists.txt's
-DCEPH_DEBUG_MUTEX tier backing its tsan/helgrind strategy) records the
ORDER in which named mutexes are acquired and fails loudly when two
locks are ever taken in both orders — the invariant whose violation is a
latent deadlock, caught even if the interleaving that would actually
deadlock never runs.

This module keeps that design for BOTH concurrency models the framework
uses: `threading.Lock` (codec plan caches, native bindings) and
`asyncio.Lock` (daemon big locks).  Ownership context is the current
thread for the former and the current asyncio task for the latter —
coroutines interleave at awaits exactly like threads at preemption
points, so holding lock A across an await and then taking B builds the
same A→B ordering edge.

Enable with CEPH_TPU_LOCKDEP=1 (or lockdep.enable()); disabled, the
factory hands out plain locks with zero overhead — the reference gates
identically on its debug flag.  Self-deadlock (re-acquiring a held
non-reentrant lock) is also reported, like lockdep.cc's recursive check.
"""

from __future__ import annotations

import asyncio
import os
import threading
import weakref


class LockOrderError(AssertionError):
    """Two locks were acquired in both orders (latent deadlock)."""


class _Registry:
    def __init__(self) -> None:
        self._graph: dict[str, set[str]] = {}  # edge a -> b: b taken under a
        self._mutex = threading.Lock()
        self._violations = 0  # LockOrderErrors raised (lifetime)

    def clear(self) -> None:
        with self._mutex:
            self._graph.clear()

    def edges(self) -> dict[str, set[str]]:
        with self._mutex:
            return {k: set(v) for k, v in self._graph.items()}

    def violations(self) -> int:
        with self._mutex:
            return self._violations

    def _violation(self, msg: str) -> LockOrderError:
        # counted so harnesses (tools/chaos.py) can assert ZERO even when
        # a daemon task swallowed the raise with the rest of its failure
        self._violations += 1
        return LockOrderError(msg)

    def check_acquire(self, held: list[str], name: str) -> None:
        """Pre-acquire validation: raises on self-deadlock or an ordering
        cycle.  Records NOTHING — edges are committed by record_acquire
        only once the lock is actually taken, so a failed or abandoned
        acquire cannot pollute the graph."""
        if not held:
            return
        if name in held:
            with self._mutex:
                raise self._violation(
                    f"lockdep: re-acquiring held lock {name!r} "
                    "(self-deadlock)"
                )
        with self._mutex:
            for h in held:
                # would edge h -> name close a cycle? (name ~> h exists)
                if self._reaches(name, h):
                    raise self._violation(
                        f"lockdep: acquiring {name!r} while holding {h!r}, "
                        f"but {h!r} has been taken under {name!r} before — "
                        f"lock-order cycle (latent deadlock)"
                    )

    def record_acquire(self, held: list[str], name: str) -> None:
        if not held:
            return
        with self._mutex:
            for h in held:
                self._graph.setdefault(h, set()).add(name)

    def _reaches(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            node = stack.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self._graph.get(node, ()))
        return False


_REGISTRY = _Registry()
_enabled = os.environ.get("CEPH_TPU_LOCKDEP", "") not in ("", "0")

# held-lock stacks per ownership context
_thread_held = threading.local()


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def clear() -> None:
    _REGISTRY.clear()


def edges() -> dict[str, set[str]]:
    """Observed ordering graph (lockdep's dependency dump)."""
    return _REGISTRY.edges()


def violations() -> int:
    """LockOrderErrors raised so far (process lifetime).  Harnesses
    snapshot this at run start and assert a zero delta — a violation
    that a daemon task swallowed with the rest of its failure still
    counts."""
    return _REGISTRY.violations()


def graph_dump() -> dict[str, list[str]]:
    """JSON-ready ordering graph: lock name -> sorted locks ever taken
    under it (the chaos report's `lockdep_graph` payload)."""
    return {k: sorted(v) for k, v in sorted(_REGISTRY.edges().items())}


def _thread_stack() -> list[str]:
    if not hasattr(_thread_held, "stack"):
        _thread_held.stack = []
    return _thread_held.stack


# task object -> held-lock names; weak keys mean a task that dies while
# holding a lock cannot leak its stack or bequeath it to an unrelated
# task at a recycled address (id() reuse)
_task_held: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _task_stack() -> list[str]:
    task = asyncio.current_task()
    stack = _task_held.get(task)
    if stack is None:
        stack = _task_held[task] = []
    return stack


class DebugLock:
    """threading.Lock with ordering validation (ceph::mutex in debug).
    Validation keys off the GLOBAL enabled flag at acquire time, so a
    lock created before lockdep.enable() still instruments afterward
    (module-level singletons included)."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._owner_stack: list[str] | None = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            return self._lock.acquire(blocking, timeout)
        stack = _thread_stack()
        if blocking:
            # validate BEFORE blocking: catch the latent deadlock instead
            # of entering it
            _REGISTRY.check_acquire(stack, self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            # a successful TRYLOCK records ordering but must not raise —
            # trylocks cannot deadlock (lockdep.cc's try variant)
            _REGISTRY.record_acquire(stack, self.name)
            stack.append(self.name)
            self._owner_stack = stack
        return got

    def release(self) -> None:
        stack = self._owner_stack
        if stack is not None and self.name in stack:
            stack.remove(self.name)
        self._owner_stack = None
        self._lock.release()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class DebugRLock:
    """threading.RLock with ordering validation.  Reentrancy is
    per-INSTANCE (like RLock itself): a nested acquire of the same
    object neither re-validates nor re-pushes the held-stack entry, so
    the reap-inside-reap patterns the aggregators rely on stay legal
    while cross-lock ordering is still checked on the outermost
    acquire."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.RLock()
        self._local = threading.local()  # per-thread depth on THIS object

    def _depth(self) -> int:
        return getattr(self._local, "depth", 0)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            got = self._lock.acquire(blocking, timeout)
            if got:
                self._local.depth = self._depth() + 1
            return got
        depth = self._depth()
        if depth:  # reentrant: already validated at the outermost acquire
            got = self._lock.acquire(blocking, timeout)
            if got:
                self._local.depth = depth + 1
            return got
        stack = _thread_stack()
        if blocking:
            _REGISTRY.check_acquire(stack, self.name)
        got = self._lock.acquire(blocking, timeout)
        if got:
            _REGISTRY.record_acquire(stack, self.name)
            stack.append(self.name)
            self._local.depth = 1
        return got

    def release(self) -> None:
        depth = self._depth()
        if depth:
            self._local.depth = depth - 1
            if depth == 1:
                stack = _thread_stack()
                if self.name in stack:
                    stack.remove(self.name)
        self._lock.release()

    def __enter__(self) -> "DebugRLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class DebugAsyncLock:
    """asyncio.Lock with ordering validation; held-set is per-task.
    Cross-task release (the asyncio.Lock handoff pattern) is supported:
    release edits the ACQUIRER's stack, not the releasing task's."""

    def __init__(self, name: str):
        self.name = name
        self._lock = asyncio.Lock()
        self._owner_stack: list[str] | None = None

    async def acquire(self) -> bool:
        if not _enabled:
            await self._lock.acquire()
            return True
        stack = _task_stack()
        _REGISTRY.check_acquire(stack, self.name)
        await self._lock.acquire()
        _REGISTRY.record_acquire(stack, self.name)
        stack.append(self.name)
        self._owner_stack = stack
        return True

    def release(self) -> None:
        stack = self._owner_stack
        if stack is not None and self.name in stack:
            stack.remove(self.name)
        self._owner_stack = None
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    async def __aenter__(self) -> "DebugAsyncLock":
        await self.acquire()
        return self

    async def __aexit__(self, *exc) -> None:
        self.release()


def make_lock(name: str) -> DebugLock:
    """Factory the framework's subsystems use.  Always returns the
    instrumentable wrapper: enablement is checked per-acquire (one global
    read when off), so module-level singleton locks created at import
    time still participate when lockdep.enable() runs later."""
    return DebugLock(name)


def make_rlock(name: str) -> DebugRLock:
    """Reentrant variant for subsystems whose hold patterns re-enter
    (aggregator reap-forced launches, the config proxy)."""
    return DebugRLock(name)


def make_async_lock(name: str) -> DebugAsyncLock:
    return DebugAsyncLock(name)
