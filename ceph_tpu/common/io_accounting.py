"""Per-pool / per-client IO accounting — the OSD half of workload
attribution (ISSUE 10).

The reference attributes load through pg_stat_t / osd_stat_t and the mgr
`iostat` module; here one `IOAccountant` per OSD accumulates, for every
completed op, per-pool ops/bytes counters and log2 latency
`PerfHistogram`s split by op class (``read`` / ``write`` /
``recovery``), plus a bounded per-(pool, client) slice for
top-N-client views.  Everything is CUMULATIVE — the mgr's iostat module
(mgr/iostat.py) diffs successive status blobs into windowed rates, so a
restart (counters rebase to zero) is detected as a negative delta and
re-anchored rather than reported as negative IOPS.

The accountant ships in the OSD status blob (``pool_io`` /
``client_io``), which keeps the wire shape JSON-safe: histograms ride as
their standard cumulative ``PerfHistogram.dump()`` payload, which merges
across OSDs (and diffs across time) by plain per-bucket arithmetic.
"""

from __future__ import annotations

import threading
import time

from .lockdep import make_lock
from .perf_counters import PerfHistogram, PerfHistogramAxis

OP_CLASSES = ("read", "write", "recovery")

# latency axis shared by every accounting histogram: 1 µs .. ~8.4 s
# before +Inf, the op_latency shape (perf_counters.py defaults)
_LAT_LOWEST = 1e-6
_LAT_BUCKETS = 25

# per-pool client-slice bound: clients beyond this fold into a single
# overflow entry so one OSD tracking a million-client fleet stays O(1)
# per pool in memory (the mgr ranks top-N anyway — the tail is noise)
OTHER_CLIENT = "_other"


def _new_hist() -> PerfHistogram:
    return PerfHistogram(PerfHistogramAxis(_LAT_LOWEST, _LAT_BUCKETS))


class _ClassIO:
    __slots__ = ("ops", "bytes", "lat", "last")

    def __init__(self) -> None:
        self.ops = 0
        self.bytes = 0
        self.lat = _new_hist()
        self.last = 0.0  # monotonic time of the last account()

    def account(
        self, nbytes: int, latency: float | None, now: float = 0.0
    ) -> None:
        self.ops += 1
        self.bytes += int(nbytes)
        self.last = now
        if latency is not None:
            self.lat.sample(latency)

    def fold(self, other: "_ClassIO") -> None:
        """Absorb another record (same axis) — the overflow-bucket merge
        when an idle client is evicted from the tracked slice."""
        self.ops += other.ops
        self.bytes += other.bytes
        for i, c in enumerate(other.lat.counts):
            self.lat.counts[i] += c
        self.lat.sum += other.lat.sum
        self.lat.count += other.lat.count
        self.last = max(self.last, other.last)

    def dump(self) -> dict:
        return {"ops": self.ops, "bytes": self.bytes, "lat": self.lat.dump()}


class IOAccountant:
    """Cumulative per-pool (by op class) + per-(pool, client) IO
    counters for one OSD (thread-safe; sampled from the op reply path
    and the recovery push path)."""

    # a tracked client idle this long may be evicted (folded into
    # _other) to admit a new one — without this, 64 short-lived clients
    # (reqid names embed a per-process nonce, so every client restart is
    # a new key) would permanently saturate the slice and attribute ALL
    # subsequent load to _other
    IDLE_EVICT_SEC = 60.0

    def __init__(self, max_clients_per_pool: int = 64):
        self._lock = make_lock("io_accountant")
        self.max_clients_per_pool = int(max_clients_per_pool)
        # pool id -> op class -> _ClassIO
        self._pools: dict[int, dict[str, _ClassIO]] = {}
        # pool id -> client -> _ClassIO (class-agnostic: the per-client
        # question is "who", the per-class split already answers "what")
        self._clients: dict[int, dict[str, _ClassIO]] = {}

    def account(
        self,
        pool_id: int,
        client: str,
        op_class: str,
        nbytes: int,
        latency: float | None = None,
    ) -> None:
        if op_class not in OP_CLASSES:
            op_class = "read"
        now = time.monotonic()
        with self._lock:
            pool = self._pools.setdefault(int(pool_id), {})
            cls = pool.get(op_class)
            if cls is None:
                cls = pool[op_class] = _ClassIO()
            cls.account(nbytes, latency, now)
            if not client:
                return
            clients = self._clients.setdefault(int(pool_id), {})
            rec = clients.get(client)
            if rec is None:
                if len(clients) >= self.max_clients_per_pool:
                    # full slice: evict the least-recently-active
                    # tracked client into _other IF it has gone idle —
                    # active clients are never displaced, so a burst of
                    # new keys can't churn the slice, but departed
                    # clients don't pin it forever either
                    victim = min(
                        (k for k in clients if k != OTHER_CLIENT),
                        key=lambda k: clients[k].last,
                        default=None,
                    )
                    if (
                        victim is not None
                        and now - clients[victim].last >= self.IDLE_EVICT_SEC
                    ):
                        other = clients.get(OTHER_CLIENT)
                        if other is None:
                            other = clients[OTHER_CLIENT] = _ClassIO()
                        other.fold(clients.pop(victim))
                    else:
                        client = OTHER_CLIENT
                        rec = clients.get(client)
                if rec is None:
                    rec = clients[client] = _ClassIO()
            rec.account(nbytes, latency, now)

    # -- dumps (the OSD status blob slices) ----------------------------------

    def dump_pools(self) -> dict[str, dict]:
        """{"<pool id>": {"read"|"write"|"recovery": {ops, bytes, lat}}}
        — JSON-string pool keys so the blob survives json round-trips
        the same way the pool_stored/pool_bytes slices do."""
        with self._lock:
            return {
                str(pid): {cls: io.dump() for cls, io in classes.items()}
                for pid, classes in self._pools.items()
            }

    def dump_clients(self) -> dict[str, dict]:
        """{"<pool id>": {"<client>": {ops, bytes, lat}}}."""
        with self._lock:
            return {
                str(pid): {c: io.dump() for c, io in clients.items()}
                for pid, clients in self._clients.items()
            }

    def totals(self) -> dict[str, int]:
        """Cluster-reconciliation totals: overall ops/bytes across every
        pool and class (what an OSD's op counters must agree with)."""
        with self._lock:
            ops = sum(
                io.ops for p in self._pools.values() for io in p.values()
            )
            nbytes = sum(
                io.bytes for p in self._pools.values() for io in p.values()
            )
        return {"ops": ops, "bytes": nbytes}
