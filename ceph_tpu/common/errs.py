"""Shared errno constants — the reference returns negative errnos across
every subsystem boundary (codec, objectstore, mon commands); naming them in
one place keeps errno audits greppable."""

ENOENT = 2
EIO = 5
EAGAIN = 11
EBUSY = 16
EINVAL = 22
EPERM = 1
EEXIST = 17
EXDEV = 18
ETIMEDOUT = 110
ENODATA = 61
ENXIO = 6
ENOTDIR = 20
ENOTEMPTY = 39
EOPNOTSUPP = 95
ECANCELED = 125
EDQUOT = 122
ESHUTDOWN = 108
