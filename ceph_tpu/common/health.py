"""Shared health-check construction.

The mon (`ceph health [detail]`) and the mgr (prometheus
`ceph_tpu_healthcheck` gauge) both derive SLOW_OPS and OSD_DOWN from the
same digest slices; building the wording in one place keeps the two
surfaces in lockstep (the reference gets this from a single
HealthMonitor check registry)."""

from __future__ import annotations


def slow_ops_summary(slow: dict[str, dict]) -> str | None:
    """The SLOW_OPS check summary for a per-daemon slow-ops slice
    ({daemon: {count, oldest_sec}}), or None when nothing is slow.
    Wording matches the reference's `N slow ops, oldest one blocked for
    S sec, daemons [...] have slow ops.`"""
    total = sum(v.get("count", 0) for v in slow.values())
    if not total:
        return None
    oldest = max(v.get("oldest_sec", 0.0) for v in slow.values())
    return (
        f"{total} slow ops, oldest one blocked for {oldest:.0f} sec, "
        f"daemons [{','.join(sorted(slow))}] have slow ops."
    )


def slow_ops_detail(slow: dict[str, dict]) -> list[str]:
    """Per-daemon breakdown lines (`health detail`)."""
    return [
        f"{d}: {v.get('count', 0)} slow ops, oldest "
        f"{v.get('oldest_sec', 0.0):.0f} sec"
        for d, v in sorted(slow.items())
    ]


def slow_peer_summary(laggy: dict[int, dict]) -> str | None:
    """The OSD_SLOW_PEER check summary for a laggy-OSD slice
    ({osd_id: {reporters, rtt_ms, since_sec}}), or None when no peer is
    laggy (ISSUE 17).  Non-fatal by construction: these OSDs answer
    heartbeats — slowly — so the check is a WARN and never feeds a
    markdown."""
    if not laggy:
        return None
    worst = max(v.get("rtt_ms", 0.0) for v in laggy.values())
    return (
        f"{len(laggy)} osd(s) laggy — heartbeats answer but service is "
        f"slow (worst rtt ewma {worst:.0f} ms): "
        f"[{','.join(f'osd.{o}' for o in sorted(laggy))}]"
    )


def slow_peer_detail(laggy: dict[int, dict]) -> list[str]:
    """Per-OSD breakdown lines (`health detail`)."""
    return [
        f"osd.{o}: laggy for {v.get('since_sec', 0.0):.0f} sec, rtt ewma "
        f"{v.get('rtt_ms', 0.0):.0f} ms, reported by "
        f"[{','.join(str(r) for r in v.get('reporters', []))}]"
        for o, v in sorted(laggy.items())
    ]


def tpu_degraded_summary(degraded: dict[str, dict]) -> str | None:
    """The TPU_BACKEND_DEGRADED check summary for a per-daemon degraded
    slice ({daemon: {degraded_for_sec, reason, fallback_launches}}), or
    None when every backend is healthy.  Shared by the mon health check
    and the mgr's healthcheck gauge so the two surfaces agree."""
    if not degraded:
        return None
    longest = max(v.get("degraded_for_sec", 0.0) for v in degraded.values())
    return (
        f"{len(degraded)} daemon(s) dispatching EC on the host fallback "
        f"(device backend degraded, longest for {longest:.0f} sec): "
        f"[{','.join(sorted(degraded))}]"
    )


def tpu_degraded_detail(degraded: dict[str, dict]) -> list[str]:
    """Per-daemon breakdown lines (`health detail`)."""
    return [
        f"{d}: degraded {v.get('degraded_for_sec', 0.0):.0f} sec "
        f"({v.get('fallback_launches', 0)} host-fallback launches): "
        f"{v.get('reason', '') or 'unknown'}"
        for d, v in sorted(degraded.items())
    ]


def hbm_pressure_summary(pressured: dict[str, dict]) -> str | None:
    """The TPU_HBM_PRESSURE check summary for a per-daemon pressure
    slice ({daemon: {ratio, target_bytes, total_bytes, stage_name,
    pools}}), or None when no daemon is under HBM pressure.  Shared by
    the mon health check and the mgr's healthcheck gauge so the two
    surfaces agree."""
    if not pressured:
        return None
    worst = max(v.get("ratio", 0.0) for v in pressured.values())
    return (
        f"{len(pressured)} daemon(s) under device HBM memory pressure "
        f"(worst at {worst:.2f}x of target): "
        f"[{','.join(sorted(pressured))}]"
    )


def hbm_pressure_detail(pressured: dict[str, dict]) -> list[str]:
    """Per-daemon breakdown lines (`health detail`): residency vs
    target, the trim stage reached, and the top pools holding bytes."""
    lines = []
    for d, v in sorted(pressured.items()):
        pools = v.get("pools") or {}
        top = ", ".join(
            f"{name}={nbytes}"
            for name, nbytes in sorted(
                pools.items(), key=lambda kv: -kv[1]
            )[:3]
        )
        lines.append(
            f"{d}: {v.get('total_bytes', 0)} bytes resident vs "
            f"{v.get('target_bytes', 0)} target "
            f"(ratio {v.get('ratio', 0.0):.2f}, "
            f"stage {v.get('stage_name', 'none')})"
            + (f"; top pools: {top}" if top else "")
        )
    return lines


def recovery_stalled_summary(stalled: dict[str, dict]) -> str | None:
    """The PG_RECOVERY_STALLED check summary for a stalled-event slice
    ({"<pgid>:<kind>": {pgid, kind, stalled_for_sec, objects_done,
    objects_total}}), or None when every event is advancing.  Shared by
    the mgr progress module and the mon health check so the two
    surfaces agree."""
    if not stalled:
        return None
    longest = max(v.get("stalled_for_sec", 0.0) for v in stalled.values())
    return (
        f"{len(stalled)} pg event(s) have recovery/backfill making no "
        f"progress (longest stalled for {longest:.0f} sec): "
        f"[{','.join(sorted(stalled))}]"
    )


def recovery_stalled_detail(stalled: dict[str, dict]) -> list[str]:
    """Per-event breakdown lines (`health detail`)."""
    return [
        f"pg {v.get('pgid', key)}: {v.get('kind', 'recovery')} stalled "
        f"{v.get('stalled_for_sec', 0.0):.0f} sec at "
        f"{v.get('objects_done', 0)}/{v.get('objects_total', 0)} objects"
        for key, v in sorted(stalled.items())
    ]


def slo_breach_summary(breaches: dict[str, dict]) -> str | None:
    """The SLO_LATENCY_BREACH check summary for a per-pool breach slice
    ({pid: {pool, target_ms, burn_fast, burn_slow, p99_ms}}), or None
    when every pool is inside its latency objective.  Shared by the mgr
    iostat module and the mon health check so the two surfaces agree."""
    if not breaches:
        return None
    worst = max(v.get("burn_slow", 0.0) for v in breaches.values())
    pools = ",".join(
        sorted(str(v.get("pool", pid)) for pid, v in breaches.items())
    )
    return (
        f"{len(breaches)} pool(s) burning their latency SLO error "
        f"budget (worst burn rate {worst:.1f}x): [{pools}]"
    )


def slo_breach_detail(breaches: dict[str, dict]) -> list[str]:
    """Per-pool breakdown lines (`health detail`)."""
    lines = []
    for pid, v in sorted(breaches.items()):
        p99 = v.get("p99_ms")
        p99_s = f"{p99:.1f} ms" if p99 is not None else "overflow"
        lines.append(
            f"pool {v.get('pool', pid)} (id {pid}): p99 {p99_s} vs "
            f"{v.get('target_ms', 0.0):.1f} ms target, burn rate "
            f"fast {v.get('burn_fast', 0.0):.1f}x / "
            f"slow {v.get('burn_slow', 0.0):.1f}x"
        )
    return lines


def throughput_regression_summary(regressions: dict[str, dict]) -> str | None:
    """The TPU_THROUGHPUT_REGRESSION check summary for a per-kind trend
    slice ({kind: {current_gbps, baseline_gbps, ratio,
    launches_per_sec}}), or None when throughput tracks its baseline.
    Shared by the mgr metrics-history module and the mon health check
    so the two surfaces agree."""
    if not regressions:
        return None
    worst = min(v.get("ratio", 1.0) for v in regressions.values())
    kinds = ",".join(sorted(regressions))
    return (
        f"EC {kinds} throughput regressed to {worst:.0%} of its "
        f"trailing baseline while launch volume persists"
    )


def throughput_regression_detail(regressions: dict[str, dict]) -> list[str]:
    """Per-kind breakdown lines (`health detail`)."""
    return [
        f"{kind}: {v.get('current_gbps', 0.0):.3f} GB/s vs "
        f"{v.get('baseline_gbps', 0.0):.3f} GB/s baseline "
        f"({v.get('ratio', 0.0):.0%}) at "
        f"{v.get('launches_per_sec', 0.0):.2f} launches/s"
        for kind, v in sorted(regressions.items())
    ]


def occupancy_collapse_summary(data: dict) -> str | None:
    """The TPU_OCCUPANCY_COLLAPSE check summary ({current, baseline,
    ratio, launches_per_sec}), or None on an empty slice."""
    if not data:
        return None
    return (
        f"device occupancy collapsed to {data.get('ratio', 0.0):.0%} of "
        f"its trailing baseline "
        f"({data.get('current', 0.0):.3f} vs "
        f"{data.get('baseline', 0.0):.3f}) while launch volume persists"
    )


def occupancy_collapse_detail(data: dict) -> list[str]:
    return [
        f"occupancy {data.get('current', 0.0):.4f} vs baseline "
        f"{data.get('baseline', 0.0):.4f} at "
        f"{data.get('launches_per_sec', 0.0):.2f} launches/s"
    ]


def queue_wait_inflation_summary(data: dict) -> str | None:
    """The TPU_QUEUE_WAIT_INFLATION check summary ({current_ms,
    baseline_ms, factor}), or None on an empty slice."""
    if not data:
        return None
    return (
        f"launch queue wait inflated {data.get('factor', 0.0):.1f}x over "
        f"its trailing baseline ({data.get('current_ms', 0.0):.2f} ms vs "
        f"{data.get('baseline_ms', 0.0):.2f} ms)"
    )


def queue_wait_inflation_detail(data: dict) -> list[str]:
    return [
        f"mean queue wait {data.get('current_ms', 0.0):.3f} ms vs "
        f"baseline {data.get('baseline_ms', 0.0):.3f} ms "
        f"({data.get('factor', 0.0):.1f}x)"
    ]


def scrub_errors_total(scrub: dict[str, dict]) -> int:
    """Total scrub errors across a per-PG slice ({pgid: {errors,
    inconsistent, ...}})."""
    return sum(int(v.get("errors", 0)) for v in scrub.values())


def osd_scrub_errors_summary(scrub: dict[str, dict]) -> str | None:
    """The OSD_SCRUB_ERRORS check summary for a per-PG scrub-error
    slice, or None when every last scrub was clean.  Wording follows
    the reference's `N scrub errors`."""
    total = scrub_errors_total(scrub)
    if not total:
        return None
    return f"{total} scrub errors"


def pg_damaged_summary(scrub: dict[str, dict]) -> str | None:
    """The PG_DAMAGED check summary (`Possible data damage: N pgs
    inconsistent`), or None when no PG holds inconsistencies."""
    if not scrub:
        return None
    return (
        f"Possible data damage: {len(scrub)} pg(s) inconsistent: "
        f"[{','.join(sorted(scrub))}]"
    )


def pg_damaged_detail(scrub: dict[str, dict]) -> list[str]:
    """Per-PG breakdown lines (`health detail`): which objects, which
    shards, why — the slice `osd/scrubber.py` recorded at compare time."""
    lines: list[str] = []
    for pgid, v in sorted(scrub.items()):
        kind = "deep-scrub" if v.get("deep") else "scrub"
        lines.append(
            f"pg {pgid} is inconsistent: {v.get('errors', 0)} {kind} errors"
        )
        for oid, bad in sorted((v.get("inconsistent") or {}).items()):
            for osd, why in sorted(bad.items()):
                lines.append(f"pg {pgid} {oid}: osd.{osd} {why}")
    return lines


# Checks whose presence escalates overall cluster health to HEALTH_ERR
# (possible data damage): everything else raised is a HEALTH_WARN.
# This set is the SINGLE severity source — the mon's overall status and
# the mgr's per-check severity field both derive from it (plus any
# explicit severity a mgr module attaches), so the two surfaces cannot
# drift.
ERR_CHECKS = frozenset({"OSD_SCRUB_ERRORS", "PG_DAMAGED"})


def check_severity(code: str) -> str:
    """Severity for a check code: the mgr's health_checks() entries and
    overall_status() both call this, keeping the escalation rule in one
    place."""
    return "HEALTH_ERR" if code in ERR_CHECKS else "HEALTH_WARN"


def overall_status(checks) -> str:
    """Overall health string from the raised checks: HEALTH_ERR when
    any damage-class check is up, HEALTH_WARN for anything else,
    HEALTH_OK when clear.  Accepts either the mon shape (code ->
    summary string) or the mgr shape (code -> {severity, summary});
    an explicit severity field wins over the code-derived default, so
    a module-raised HEALTH_ERR check escalates on both surfaces."""
    worst = "HEALTH_OK"
    for code, info in (
        checks.items() if hasattr(checks, "items")
        else ((c, None) for c in checks)
    ):
        sev = (
            info.get("severity") if isinstance(info, dict) else None
        ) or check_severity(code)
        if sev == "HEALTH_ERR":
            return "HEALTH_ERR"
        worst = "HEALTH_WARN"
    return worst


def down_in_osds(osdmap) -> list:
    """OSDs that are IN but not up — the OSD_DOWN population.  A
    decommissioned (out) osd being down is healthy by design, as in the
    reference's OSD_DOWN check."""
    return sorted(o for o, i in osdmap.osds.items() if i.in_ and not i.up)
