"""Shared health-check construction.

The mon (`ceph health [detail]`) and the mgr (prometheus
`ceph_tpu_healthcheck` gauge) both derive SLOW_OPS and OSD_DOWN from the
same digest slices; building the wording in one place keeps the two
surfaces in lockstep (the reference gets this from a single
HealthMonitor check registry)."""

from __future__ import annotations


def slow_ops_summary(slow: dict[str, dict]) -> str | None:
    """The SLOW_OPS check summary for a per-daemon slow-ops slice
    ({daemon: {count, oldest_sec}}), or None when nothing is slow.
    Wording matches the reference's `N slow ops, oldest one blocked for
    S sec, daemons [...] have slow ops.`"""
    total = sum(v.get("count", 0) for v in slow.values())
    if not total:
        return None
    oldest = max(v.get("oldest_sec", 0.0) for v in slow.values())
    return (
        f"{total} slow ops, oldest one blocked for {oldest:.0f} sec, "
        f"daemons [{','.join(sorted(slow))}] have slow ops."
    )


def slow_ops_detail(slow: dict[str, dict]) -> list[str]:
    """Per-daemon breakdown lines (`health detail`)."""
    return [
        f"{d}: {v.get('count', 0)} slow ops, oldest "
        f"{v.get('oldest_sec', 0.0):.0f} sec"
        for d, v in sorted(slow.items())
    ]


def down_in_osds(osdmap) -> list:
    """OSDs that are IN but not up — the OSD_DOWN population.  A
    decommissioned (out) osd being down is healthy by design, as in the
    reference's OSD_DOWN check."""
    return sorted(o for o, i in osdmap.osds.items() if i.in_ and not i.up)
