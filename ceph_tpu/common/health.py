"""Shared health-check construction.

The mon (`ceph health [detail]`) and the mgr (prometheus
`ceph_tpu_healthcheck` gauge) both derive SLOW_OPS and OSD_DOWN from the
same digest slices; building the wording in one place keeps the two
surfaces in lockstep (the reference gets this from a single
HealthMonitor check registry)."""

from __future__ import annotations


def slow_ops_summary(slow: dict[str, dict]) -> str | None:
    """The SLOW_OPS check summary for a per-daemon slow-ops slice
    ({daemon: {count, oldest_sec}}), or None when nothing is slow.
    Wording matches the reference's `N slow ops, oldest one blocked for
    S sec, daemons [...] have slow ops.`"""
    total = sum(v.get("count", 0) for v in slow.values())
    if not total:
        return None
    oldest = max(v.get("oldest_sec", 0.0) for v in slow.values())
    return (
        f"{total} slow ops, oldest one blocked for {oldest:.0f} sec, "
        f"daemons [{','.join(sorted(slow))}] have slow ops."
    )


def slow_ops_detail(slow: dict[str, dict]) -> list[str]:
    """Per-daemon breakdown lines (`health detail`)."""
    return [
        f"{d}: {v.get('count', 0)} slow ops, oldest "
        f"{v.get('oldest_sec', 0.0):.0f} sec"
        for d, v in sorted(slow.items())
    ]


def tpu_degraded_summary(degraded: dict[str, dict]) -> str | None:
    """The TPU_BACKEND_DEGRADED check summary for a per-daemon degraded
    slice ({daemon: {degraded_for_sec, reason, fallback_launches}}), or
    None when every backend is healthy.  Shared by the mon health check
    and the mgr's healthcheck gauge so the two surfaces agree."""
    if not degraded:
        return None
    longest = max(v.get("degraded_for_sec", 0.0) for v in degraded.values())
    return (
        f"{len(degraded)} daemon(s) dispatching EC on the host fallback "
        f"(device backend degraded, longest for {longest:.0f} sec): "
        f"[{','.join(sorted(degraded))}]"
    )


def tpu_degraded_detail(degraded: dict[str, dict]) -> list[str]:
    """Per-daemon breakdown lines (`health detail`)."""
    return [
        f"{d}: degraded {v.get('degraded_for_sec', 0.0):.0f} sec "
        f"({v.get('fallback_launches', 0)} host-fallback launches): "
        f"{v.get('reason', '') or 'unknown'}"
        for d, v in sorted(degraded.items())
    ]


def recovery_stalled_summary(stalled: dict[str, dict]) -> str | None:
    """The PG_RECOVERY_STALLED check summary for a stalled-event slice
    ({"<pgid>:<kind>": {pgid, kind, stalled_for_sec, objects_done,
    objects_total}}), or None when every event is advancing.  Shared by
    the mgr progress module and the mon health check so the two
    surfaces agree."""
    if not stalled:
        return None
    longest = max(v.get("stalled_for_sec", 0.0) for v in stalled.values())
    return (
        f"{len(stalled)} pg event(s) have recovery/backfill making no "
        f"progress (longest stalled for {longest:.0f} sec): "
        f"[{','.join(sorted(stalled))}]"
    )


def recovery_stalled_detail(stalled: dict[str, dict]) -> list[str]:
    """Per-event breakdown lines (`health detail`)."""
    return [
        f"pg {v.get('pgid', key)}: {v.get('kind', 'recovery')} stalled "
        f"{v.get('stalled_for_sec', 0.0):.0f} sec at "
        f"{v.get('objects_done', 0)}/{v.get('objects_total', 0)} objects"
        for key, v in sorted(stalled.items())
    ]


def down_in_osds(osdmap) -> list:
    """OSDs that are IN but not up — the OSD_DOWN population.  A
    decommissioned (out) osd being down is healthy by design, as in the
    reference's OSD_DOWN check."""
    return sorted(o for o, i in osdmap.osds.items() if i.in_ and not i.up)
