"""OpTracker — in-flight + historic op introspection
(src/common/TrackedOp.{h,cc}: OpTracker / OpRequest; surfaced via the
admin socket's `dump_ops_in_flight` / `dump_historic_ops`, the operator's
first stop for "why is this op slow").

Each tracked op records its description, arrival time, and event marks
("queued", "reached_pg", "done" — TrackedOp::mark_event); completed ops
move into a bounded history ring ordered by recency, with the
longest-duration ops kept in a second ring (dump_historic_slow_ops).
"""

from __future__ import annotations

import time
from collections import deque


class TrackedOp:
    __slots__ = (
        "desc", "start", "events", "duration",
        # workload-attribution tags (ISSUE 10): which pool/client this op
        # belongs to and its class (read/write/recovery) — what the OSD's
        # IOAccountant and the mgr iostat module aggregate by
        "pool_id", "client", "op_class",
    )

    def __init__(
        self,
        desc: str,
        pool_id: int = -1,
        client: str = "",
        op_class: str = "",
    ):
        self.desc = desc
        self.start = time.monotonic()
        self.events: list[tuple[float, str]] = [(self.start, "initiated")]
        self.duration: float | None = None
        self.pool_id = pool_id
        self.client = client
        self.op_class = op_class

    def mark_event(self, what: str) -> None:
        self.events.append((time.monotonic(), what))

    def dump(self) -> dict:
        now = time.monotonic()
        return {
            "description": self.desc,
            "pool": self.pool_id,
            "client": self.client,
            "op_class": self.op_class,
            "age": round(now - self.start, 6),
            "duration": None if self.duration is None else round(self.duration, 6),
            "type_data": {
                "events": [
                    {"time": round(t - self.start, 6), "event": e}
                    for t, e in self.events
                ],
                # per-stage durations (ISSUE 8 satellite): the gap
                # between consecutive event marks, named after the stage
                # they END ("queued" -> "reached_pg" renders as
                # reached_pg's duration) — where a historic op's time
                # went, without the reader diffing timestamps by hand
                "stages": [
                    {
                        "stage": self.events[i][1],
                        "duration": round(
                            self.events[i][0] - self.events[i - 1][0], 6
                        ),
                    }
                    for i in range(1, len(self.events))
                ],
            },
        }


class OpTracker:
    """Bounded in-flight registry + completion history."""

    # in-flight entries older than this are swept to history as aborted:
    # an op whose reply closure was lost to a fault path must stay visible
    # for a while (that IS dump_ops_in_flight's job) but not accumulate
    # forever under repeated faults
    ABORT_SWEEP_AGE = 600.0

    def __init__(self, history_size: int = 20, slow_size: int = 20):
        self._inflight: dict[int, TrackedOp] = {}
        self._seq = 0
        self.history: deque[TrackedOp] = deque(maxlen=history_size)
        self.slow: deque[TrackedOp] = deque(maxlen=slow_size)
        # in-flight ops older than this are "slow requests"
        # (osd_op_complaint_time; OpTracker::check_ops_in_flight's
        # complaint threshold) — counted into the SLOW_OPS health check
        self.complaint_time = 30.0

    def resize_history(self, history_size: int) -> None:
        """Runtime osd_op_history_size change (config observer)."""
        self.history = deque(self.history, maxlen=max(1, int(history_size)))

    def create(
        self,
        desc: str,
        pool_id: int = -1,
        client: str = "",
        op_class: str = "",
    ) -> int:
        """Register an op; returns the token finish() takes.  The
        attribution tags (pool, client, op class) ride the tracked op so
        `dump_ops_in_flight` answers "whose op is stuck", and the OSD's
        reply path feeds them into the IOAccountant at finish.

        Registration is UNCONDITIONAL — trace sampling (ISSUE 10 layer 3)
        gates span *retention*, never this registry, so a sampled-out op
        still ages into the SLOW_OPS complaint accounting."""
        self._seq += 1
        self._inflight[self._seq] = TrackedOp(
            desc, pool_id=pool_id, client=client, op_class=op_class
        )
        if self._seq % 256 == 0:
            self._sweep_aborted()
        return self._seq

    def _sweep_aborted(self) -> None:
        cutoff = time.monotonic() - self.ABORT_SWEEP_AGE
        for tok in [t for t, o in self._inflight.items() if o.start < cutoff]:
            op = self._inflight.pop(tok)
            op.mark_event("aborted (tracker sweep)")
            op.duration = time.monotonic() - op.start
            self.history.append(op)

    def mark_event(self, token: int, what: str) -> None:
        op = self._inflight.get(token)
        if op is not None:
            op.mark_event(what)

    def finish(self, token: int) -> None:
        op = self._inflight.pop(token, None)
        if op is None:
            return
        op.mark_event("done")
        op.duration = time.monotonic() - op.start
        self.history.append(op)
        # keep the slowest ops separately (dump_historic_slow_ops): evict
        # the fastest once full
        if len(self.slow) < self.slow.maxlen:
            self.slow.append(op)
        else:
            fastest = min(self.slow, key=lambda o: o.duration or 0.0)
            if (op.duration or 0.0) > (fastest.duration or 0.0):
                self.slow.remove(fastest)
                self.slow.append(op)

    def slow_ops(self) -> tuple[int, float]:
        """(count, oldest age in seconds) of in-flight ops older than the
        complaint time (OpTracker::check_ops_in_flight; feeds the OSD's
        mgr report and, through the mgr digest, the SLOW_OPS health
        check)."""
        now = time.monotonic()
        ages = [
            now - op.start
            for op in self._inflight.values()
            if now - op.start >= self.complaint_time
        ]
        return len(ages), max(ages, default=0.0)

    # -- dumps (OpTracker::dump_ops_in_flight / dump_historic_ops) -----------

    def dump_in_flight(self) -> dict:
        ops = sorted(self._inflight.values(), key=lambda o: o.start)
        return {"num_ops": len(ops), "ops": [o.dump() for o in ops]}

    def dump_historic(self) -> dict:
        ops = list(self.history)
        return {"num_ops": len(ops), "ops": [o.dump() for o in reversed(ops)]}

    def dump_slow(self) -> dict:
        ops = sorted(self.slow, key=lambda o: -(o.duration or 0.0))
        return {"num_ops": len(ops), "ops": [o.dump() for o in ops]}
