"""Byte/count throttles — mirror of src/common/Throttle.{h,cc}.

Reference: the messenger's per-connection dispatch throttles
(`ms_dispatch_throttle_bytes`, policy throttles at
/root/reference/src/ceph_osd.cc:590-594) block producers once in-flight
bytes/messages exceed a limit and wake them as credit is returned.
Both a threading variant (for the sharded op path) and an asyncio variant
(for the messenger) are provided.
"""

from __future__ import annotations

import asyncio
import threading

from .lockdep import make_async_lock, make_lock


class Throttle:
    """Blocking counting throttle (Throttle.h)."""

    def __init__(self, name: str, limit: int):
        self.name = name
        self._limit = limit
        self._count = 0
        self._cond = threading.Condition(make_lock(f"throttle.{name}"))

    @property
    def current(self) -> int:
        with self._cond:
            return self._count

    @property
    def limit(self) -> int:
        with self._cond:
            return self._limit

    @limit.setter
    def limit(self, value: int) -> None:
        """Runtime-mutable bound (Throttle::reset_max): raising it wakes
        blocked producers; 0 disables the throttle."""
        with self._cond:
            self._limit = int(value)
            self._cond.notify_all()

    def take(self, amount: int = 1) -> None:
        """Unconditionally take credit, even past the limit — the
        reference's Throttle::take for work that must be admitted
        (oversized requests once nothing older remains)."""
        with self._cond:
            self._count += amount

    def get(self, amount: int = 1) -> None:
        """Take credit, blocking while over limit (Throttle::get).

        An amount larger than the limit is admitted once current usage
        drains to zero (the reference's _should_wait lets oversized
        requests through rather than wedging the dispatch path).
        """
        with self._cond:
            while (
                self._limit > 0
                and self._count > 0
                and self._count + amount > self._limit
            ):
                self._cond.wait()
            self._count += amount

    def get_or_fail(self, amount: int = 1) -> bool:
        with self._cond:
            if self._limit > 0 and self._count + amount > self._limit:
                return False
            self._count += amount
            return True

    def put(self, amount: int = 1) -> None:
        with self._cond:
            self._count -= amount
            self._cond.notify_all()


class AsyncThrottle:
    """asyncio counterpart used by the async messenger."""

    def __init__(self, name: str, limit: int):
        self.name = name
        self._limit = limit
        self._count = 0
        self._cond: asyncio.Condition | None = None

    def _condition(self) -> asyncio.Condition:
        if self._cond is None:
            # lockdep-instrumented inner lock (asyncio.Condition duck-
            # types over acquire/release/locked): the dispatch-throttle
            # lock sits on the message-delivery path and must
            # participate in lock-order validation like every other
            self._cond = asyncio.Condition(
                make_async_lock(f"async_throttle.{self.name}")
            )
        return self._cond

    @property
    def current(self) -> int:
        return self._count

    async def get(self, amount: int = 1) -> None:
        cond = self._condition()
        async with cond:
            while (
                self._limit > 0
                and self._count > 0
                and self._count + amount > self._limit
            ):
                await cond.wait()
            self._count += amount

    async def put(self, amount: int = 1) -> None:
        cond = self._condition()
        async with cond:
            self._count -= amount
            cond.notify_all()
