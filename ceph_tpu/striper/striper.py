"""radosstriper — mirror of src/libradosstriper.

The reference stripes one logical object over many RADOS objects with
the (stripe_unit, stripe_count, object_size) layout shared by librbd and
CephFS file layouts (src/osdc/Striper.cc file_to_extents is the common
math; libradosstriper/RadosStriperImpl.cc drives it):

- the byte stream is cut into stripe units, dealt round-robin across a
  set of `stripe_count` objects (an "object set"), each object taking
  `object_size / stripe_unit` units before the stream moves to the next
  object set;
- the logical size rides as an xattr on the first object
  (striper.size, RadosStriperImpl.cc XATTR_SIZE), so stat/truncate are
  metadata ops.

Same layout math here, over the async IoCtx surface.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..common.errs import ENOENT

SIZE_XATTR = "striper.size"  # RadosStriperImpl XATTR_SIZE analog


@dataclass(frozen=True)
class StripePolicy:
    """File layout (file_layout_t: su/sc/object_size)."""

    stripe_unit: int = 64 * 1024
    stripe_count: int = 4
    object_size: int = 4 * 1024 * 1024

    def __post_init__(self):
        assert self.object_size % self.stripe_unit == 0
        assert self.stripe_unit > 0 and self.stripe_count > 0

    @property
    def units_per_object(self) -> int:
        return self.object_size // self.stripe_unit

    @property
    def set_width(self) -> int:
        """Bytes covered by one object set."""
        return self.object_size * self.stripe_count

    def map_extent(self, off: int, length: int):
        """Logical (off, len) -> [(objno, obj_off, len)] — the
        Striper::file_to_extents math."""
        out = []
        su = self.stripe_unit
        while length > 0:
            unitno = off // su
            in_unit = off % su
            take = min(su - in_unit, length)
            stripeno = unitno // self.stripe_count
            stripepos = unitno % self.stripe_count  # object within the set
            setno = stripeno // self.units_per_object
            unit_in_obj = stripeno % self.units_per_object
            objno = setno * self.stripe_count + stripepos
            obj_off = unit_in_obj * su + in_unit
            out.append((objno, obj_off, take))
            off += take
            length -= take
        return out


class StripedObject:
    """One striped logical object in a pool (RadosStriperImpl)."""

    def __init__(self, ioctx, name: str, policy: StripePolicy | None = None):
        self.ioctx = ioctx
        self.name = name
        self.policy = policy or StripePolicy()

    def _obj(self, objno: int) -> str:
        # "<name>.%016x" object naming (RadosStriperImpl getObjectId)
        return f"{self.name}.{objno:016x}"

    # -- metadata --------------------------------------------------------------

    async def size(self) -> int:
        from ..client.rados import RadosError
        from ..common.errs import ENODATA, ENOENT

        try:
            raw = await self.ioctx.getxattr(self._obj(0), SIZE_XATTR)
            return int(raw.decode())
        except RadosError as e:
            # Only a genuinely absent object/xattr means size 0; a
            # transport error must NOT — write() compares against size()
            # and would shrink the size xattr over live data.
            if e.errno in (-ENOENT, -ENODATA):
                return 0
            raise

    async def _set_size(self, size: int) -> None:
        await self.ioctx.setxattr(self._obj(0), SIZE_XATTR, str(size).encode())

    async def exists(self) -> bool:
        try:
            await self.ioctx.stat(self._obj(0))
            return True
        except Exception:
            return False

    # -- I/O -------------------------------------------------------------------

    async def write(self, data: bytes, off: int = 0) -> None:
        cursor = 0
        for objno, obj_off, ln in self.policy.map_extent(off, len(data)):
            await self.ioctx.write(self._obj(objno), data[cursor : cursor + ln], obj_off)
            cursor += ln
        end = off + len(data)
        if end > await self.size():
            await self._set_size(end)

    async def read(self, length: int = 0, off: int = 0) -> bytes:
        size = await self.size()
        if off >= size:
            return b""
        length = min(length or size - off, size - off)
        from ..client.rados import RadosError
        from ..common.errs import ENOENT

        parts = []
        for objno, obj_off, ln in self.policy.map_extent(off, length):
            try:
                chunk = await self.ioctx.read(self._obj(objno), ln, obj_off)
            except RadosError as e:
                if e.errno != -ENOENT:
                    raise  # transport errors must not read as zeros
                chunk = b""  # sparse / never-written object
            parts.append(chunk.ljust(ln, b"\x00"))
        return b"".join(parts)

    async def truncate(self, size: int) -> None:
        """Shrink/grow (RadosStriperImpl::truncate): drop whole objects
        past the end, trim boundary objects, update the size xattr."""
        old = await self.size()
        if size < old:
            for objno in range(self._max_objno(old) + 1):
                old_local = self._object_local_size(objno, old)
                if old_local == 0:
                    continue
                local = self._object_local_size(objno, size)
                if local == 0 and objno != 0:
                    try:
                        await self.ioctx.remove(self._obj(objno))
                    except Exception:
                        pass
                elif local < old_local:
                    await self.ioctx.truncate(self._obj(objno), local)
        if size != old:
            if not await self.exists() and size > 0:
                await self.ioctx.write(self._obj(0), b"", 0)
            await self._set_size(size)

    def _max_objno(self, size: int) -> int:
        if size == 0:
            return 0
        full_sets = (size - 1) // self.policy.set_width
        return full_sets * self.policy.stripe_count + self.policy.stripe_count - 1

    def _object_local_size(self, objno: int, logical_size: int) -> int:
        """How many bytes of `objno` fall within logical_size (inverse
        of map_extent for one object)."""
        p = self.policy
        setno, stripepos = divmod(objno, p.stripe_count)
        total = 0
        for u in range(p.units_per_object):
            stripeno = setno * p.units_per_object + u
            unit_start = (stripeno * p.stripe_count + stripepos) * p.stripe_unit
            if unit_start >= logical_size:
                break
            total = u * p.stripe_unit + min(p.stripe_unit, logical_size - unit_start)
        return total

    async def remove(self) -> None:
        size = await self.size()
        for objno in range(self._max_objno(size) + 1):
            try:
                await self.ioctx.remove(self._obj(objno))
            except Exception:
                pass
        try:
            await self.ioctx.remove(self._obj(0))
        except Exception:
            pass
