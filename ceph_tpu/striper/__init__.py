"""File striping over RADOS objects (src/libradosstriper)."""

from .striper import StripedObject, StripePolicy

__all__ = ["StripedObject", "StripePolicy"]
