"""GF(2^8) math core: tables, coding matrices, bitsliced GF(2) expansion."""

from .tables import (
    GF_EXP,
    GF_INV_TABLE,
    GF_LOG,
    GF_MUL_TABLE,
    GF_POLY,
    gf_inv,
    gf_matmul,
    gf_matvec,
    gf_mul,
    gf_mul_slow,
    gf_mul_vec,
    gf_pow,
)
from .matrix import (
    gf_invert_matrix,
    identity,
    isa_cauchy_matrix,
    isa_decode_matrix,
    isa_rs_vandermonde_matrix,
    jerasure_cauchy_good_matrix,
    jerasure_cauchy_orig_matrix,
    jerasure_r6_matrix,
    jerasure_vandermonde_matrix,
    vandermonde_mds_check,
)
from .bitslice import (
    bitslice_bytes,
    coeff_bitmatrix,
    expand_matrix,
    unbitslice_bytes,
    xor_matmul_host,
    xor_matmul_host_batch,
)

__all__ = [
    "GF_EXP", "GF_INV_TABLE", "GF_LOG", "GF_MUL_TABLE", "GF_POLY",
    "gf_inv", "gf_matmul", "gf_matvec", "gf_mul", "gf_mul_slow", "gf_mul_vec",
    "gf_pow", "gf_invert_matrix", "identity", "isa_cauchy_matrix",
    "isa_decode_matrix", "isa_rs_vandermonde_matrix",
    "jerasure_cauchy_good_matrix", "jerasure_cauchy_orig_matrix",
    "jerasure_r6_matrix", "jerasure_vandermonde_matrix",
    "vandermonde_mds_check", "bitslice_bytes", "coeff_bitmatrix",
    "expand_matrix", "unbitslice_bytes", "xor_matmul_host",
    "xor_matmul_host_batch",
]
