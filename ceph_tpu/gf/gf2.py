"""GF(2) bit-matrix machinery for the packetized RAID-6 code family.

The reference's liberation / blaum_roth / liber8tion techniques
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.h:169-253)
are pure GF(2) bit-matrix codes: each chunk is w packets, and coding rows
XOR whole packets selected by a (m*w, k*w) 0/1 matrix.  Their generator
functions live in the jerasure submodule (liberation.c), which is NOT
vendored in the reference checkout, so the constructions here are
re-derived from the published code definitions; the test suite proves the
RAID-6 MDS property (every X_i and every X_i ^ X_j invertible) for the
supported parameter envelopes.

Conventions: column-vector, LSB/packet-0 first.  Block X_j (w x w) is data
drive j's contribution to the Q (second coding) drive; the P drive is
always the XOR of all data drives (identity blocks).
"""

from __future__ import annotations

import numpy as np


def gf2_inv(mat: np.ndarray) -> np.ndarray | None:
    """Invert a square 0/1 matrix over GF(2); None if singular.

    Bit-packed Gauss-Jordan: rows are Python ints (arbitrary width), so a
    row XOR is one integer op — the host-side mirror of the device kernel's
    XOR-matmul semantics.
    """
    mat = np.asarray(mat, dtype=np.uint8) & 1
    n = mat.shape[0]
    if mat.shape != (n, n):
        raise ValueError(f"not square: {mat.shape}")
    # row i packed as int: bits 0..n-1 = mat row, bits n..2n-1 = identity
    rows = [
        int.from_bytes(np.packbits(mat[i], bitorder="little").tobytes(), "little")
        | (1 << (n + i))
        for i in range(n)
    ]
    for col in range(n):
        pivot = next(
            (r for r in range(col, n) if rows[r] & (1 << col)), None
        )
        if pivot is None:
            return None
        rows[col], rows[pivot] = rows[pivot], rows[col]
        for r in range(n):
            if r != col and rows[r] & (1 << col):
                rows[r] ^= rows[col]
    out = np.zeros((n, n), dtype=np.uint8)
    for i in range(n):
        inv_bits = rows[i] >> n
        for j in range(n):
            out[i, j] = (inv_bits >> j) & 1
    return out


def gf2_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.int64) @ b.astype(np.int64) % 2).astype(np.uint8)


def is_prime(n: int) -> bool:
    if n < 2:
        return False
    return all(n % d for d in range(2, int(n**0.5) + 1))


def _raid6_bitmatrix(x_blocks: list[np.ndarray], w: int) -> np.ndarray:
    """Assemble [I I ... I; X_0 X_1 ... X_{k-1}] — a (2w, kw) coding matrix."""
    k = len(x_blocks)
    bm = np.zeros((2 * w, k * w), dtype=np.uint8)
    eye = np.eye(w, dtype=np.uint8)
    for j, X in enumerate(x_blocks):
        bm[:w, j * w : (j + 1) * w] = eye
        bm[w:, j * w : (j + 1) * w] = X
    return bm


def liberation_bitmatrix(k: int, w: int) -> np.ndarray:
    """Liberation code Q blocks (Plank's liberation_coding_bitmatrix,
    jerasure lib; ErasureCodeJerasure.cc:450-454 call site): w prime > 2,
    k <= w.  X_j is the cyclic shift-by-j permutation, plus for j > 0 one
    extra bit at row (j*(w-1)/2) mod w — the minimum-density construction
    from the Liberation-codes paper."""
    if not is_prime(w) or w <= 2:
        raise ValueError(f"liberation requires prime w > 2, got {w}")
    if k > w:
        raise ValueError(f"liberation requires k <= w, got k={k} w={w}")
    blocks = []
    for j in range(k):
        X = np.zeros((w, w), dtype=np.uint8)
        for i in range(w):
            X[i, (j + i) % w] = 1
        if j > 0:
            i = (j * ((w - 1) // 2)) % w
            X[i, (i + j - 1) % w] = 1
        blocks.append(X)
    return _raid6_bitmatrix(blocks, w)


def blaum_roth_bitmatrix(k: int, w: int) -> np.ndarray:
    """Blaum-Roth code: w + 1 prime (w == 7 tolerated for legacy profiles,
    ErasureCodeJerasure.cc:459-472).  Arithmetic in the polynomial ring
    GF(2)[x] / M_p(x), M_p = 1 + x + ... + x^{p-1}, p = w + 1; data drive
    j's Q block is multiplication by x^j, i.e. T^j where T is the
    mult-by-x matrix (x^w folds to 1 + x + ... + x^{w-1})."""
    p = w + 1
    if w != 7 and (w <= 2 or not is_prime(p)):
        raise ValueError(f"blaum_roth requires w+1 prime, got w={w}")
    if k > w:
        raise ValueError(f"blaum_roth requires k <= w, got k={k} w={w}")
    T = np.zeros((w, w), dtype=np.uint8)
    for c in range(w - 1):
        T[c + 1, c] = 1
    T[:, w - 1] = 1
    blocks = []
    X = np.eye(w, dtype=np.uint8)
    for _ in range(k):
        blocks.append(X)
        X = gf2_matmul(T, X)
    return _raid6_bitmatrix(blocks, w)


def liber8tion_bitmatrix(k: int) -> np.ndarray:
    """w = 8, m = 2, k <= 8 RAID-6 bit-matrix (the liber8tion envelope,
    ErasureCodeJerasure.cc:511-514).

    The published minimum-density matrices are in the jerasure submodule
    (liberation.c liber8tion_coding_bitmatrix), which is not vendored in
    the reference checkout, so byte-parity is unverifiable; this
    re-design fills the same (k, 2, w=8) envelope with GF(2^8)
    multiplication bit-matrices X_j = M(g^j) — distinct field elements, so
    every X_i and X_i ^ X_j = M(g^i + g^j) is invertible and the RAID-6
    MDS guarantee holds identically (denser matrix, same contract)."""
    w = 8
    if k > w:
        raise ValueError(f"liber8tion requires k <= 8, got k={k}")
    from .bitslice import coeff_bitmatrix
    from .tables import gf_pow

    blocks = [coeff_bitmatrix(gf_pow(2, j)) for j in range(k)]
    return _raid6_bitmatrix(blocks, w)
