"""Bitsliced GF(2^8) — expand GF coding matrices into GF(2) bit-matrices.

The TPU-first trick that makes Reed-Solomon ride the MXU: multiplying a byte by
a constant c in GF(2^8) is a *linear map over GF(2)* on the byte's 8 bits.  So
an (m, k) GF coding matrix expands into an (8m, 8k) 0/1 matrix B, and encoding
becomes

    parity_bits = (B @ data_bits) mod 2

i.e. an integer matmul followed by a parity reduction — exactly the shape the
MXU wants, with the stripe-length axis as the huge N dimension.  This replaces
the reference's per-byte table lookups (ISA-L `ec_encode_data` /
gf-complete SIMD regions) with one dense matmul per launch; it is the same
linearization jerasure's "bitmatrix" techniques use on CPU
(/root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.h:120-167), but
laid out for a systolic array instead of word-wise XOR.

Bit conventions: bit b of a byte is (x >> b) & 1 (LSB-first).  Column j of the
8x8 block for coefficient c holds the bits of c * 2^j, because multiplying the
basis byte 2^j by c yields that column's contribution.
"""

from __future__ import annotations

import numpy as np

from .tables import GF_MUL_TABLE


def coeff_bitmatrix(c: int) -> np.ndarray:
    """(8, 8) 0/1 matrix M_c with M_c[i, j] = bit i of (c * 2^j in GF(2^8)).

    Satisfies: bits(c * x) = M_c @ bits(x) mod 2 for every byte x.
    """
    cols = GF_MUL_TABLE[c, (1 << np.arange(8)).astype(np.uint8)]  # c * 2^j
    return ((cols[None, :] >> np.arange(8)[:, None]) & 1).astype(np.uint8)


def expand_matrix(gf_matrix: np.ndarray) -> np.ndarray:
    """Expand an (m, k) GF(2^8) matrix into its (8m, 8k) GF(2) bit-matrix."""
    gf_matrix = np.asarray(gf_matrix, dtype=np.uint8)
    m, k = gf_matrix.shape
    out = np.zeros((8 * m, 8 * k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            c = int(gf_matrix[i, j])
            if c:
                out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = coeff_bitmatrix(c)
    return out


def bitslice_bytes(data: np.ndarray) -> np.ndarray:
    """Host reference: (k, L) uint8 -> (8k, L) 0/1 bit-planes (LSB-first)."""
    data = np.asarray(data, dtype=np.uint8)
    k, L = data.shape
    planes = (data[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None]) & 1
    return planes.reshape(8 * k, L)


def unbitslice_bytes(planes: np.ndarray) -> np.ndarray:
    """Host reference: (8m, L) 0/1 planes -> (m, L) uint8 bytes."""
    planes = np.asarray(planes, dtype=np.uint8)
    m8, L = planes.shape
    assert m8 % 8 == 0
    p = planes.reshape(m8 // 8, 8, L)
    weights = (1 << np.arange(8, dtype=np.uint16))[None, :, None]
    return (p.astype(np.uint16) * weights).sum(axis=1).astype(np.uint8)


def xor_matmul_host(bit_matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Host reference of the device kernel: GF coding via bitsliced XOR-matmul.

    bit_matrix: (8m, 8k) 0/1; data: (k, L) uint8 -> (m, L) uint8.
    Used by tests as the oracle for the jnp/Pallas implementations.
    """
    planes = bitslice_bytes(data)
    out_planes = (bit_matrix.astype(np.int32) @ planes.astype(np.int32)) & 1
    return unbitslice_bytes(out_planes.astype(np.uint8))


# Host-oracle working-set bound: the int32 plane expansion below costs
# ~40x its input slice, so stripe batches process in slices of at most
# this many input bytes (~8 MiB slice -> ~320 MiB transient), keeping
# the fallback of a max-size aggregated launch from OOMing the daemon
# at exactly the moment its device backend died.
_HOST_BATCH_SLICE_BYTES = 8 << 20


def xor_matmul_host_batch(bit_matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """Batched host oracle: (..., k, L) uint8 -> (..., m, L) uint8.

    Pure numpy end to end — this is the DEGRADED-mode fallback the
    device guard re-runs launches on, so it must never touch the jax
    runtime (a wedged TPU backend can hang any jnp call).  Bit-for-bit
    identical to xor_matmul_host applied per stripe: same LSB-first
    plane layout, same GF(2) matmul-and-mask reduction.
    """
    data = np.asarray(data, dtype=np.uint8)
    lead = data.shape[:-2]
    k, L = data.shape[-2:]
    flat = data.reshape(-1, k, L)
    m = bit_matrix.shape[0] // 8
    stripes = flat.shape[0]
    per_stripe = max(1, k * L)
    step = max(1, _HOST_BATCH_SLICE_BYTES // per_stripe)
    bm32 = bit_matrix.astype(np.int32)
    weights = (1 << np.arange(8, dtype=np.uint16))[None, None, :, None]
    out = np.empty((stripes, m, L), dtype=np.uint8)
    for s0 in range(0, stripes, step):
        part = flat[s0 : s0 + step]
        # (s, k, 8, L) -> (s, 8k, L): chunk-major, bit-minor like
        # bitslice_bytes
        planes = (
            (part[:, :, None, :]
             >> np.arange(8, dtype=np.uint8)[None, None, :, None])
            & 1
        ).reshape(part.shape[0], 8 * k, L)
        out_planes = (bm32 @ planes.astype(np.int32)) & 1
        p = out_planes.reshape(part.shape[0], m, 8, L).astype(np.uint16)
        out[s0 : s0 + step] = (p * weights).sum(axis=2).astype(np.uint8)
    return out.reshape(*lead, m, L)
