"""Coding-matrix construction and inversion over GF(2^8).

Host-side (numpy) mirrors of the matrix conventions the reference plugins use,
so the TPU codec's chunks are byte-identical to theirs:

- ISA-L family (reference /root/reference/src/erasure-code/isa/ErasureCodeIsa.cc:
  :385 `gf_gen_rs_matrix`, :387 `gf_gen_cauchy1_matrix`, :275 `gf_invert_matrix`,
  decode-matrix assembly :255-297).
- jerasure family (reference /root/reference/src/erasure-code/jerasure/
  ErasureCodeJerasure.h:81-253 techniques; matrices re-derived from the published
  jerasure 2.x algorithms — the submodule is not vendored in the reference
  checkout).

All matrices are systematic: the full (k+m, k) "distribution" matrix has the
identity on top; `coding_rows` views just the (m, k) parity part that the device
kernels consume.
"""

from __future__ import annotations

import numpy as np

from .tables import GF_INV_TABLE, GF_MUL_TABLE, gf_inv, gf_matmul, gf_pow


def identity(k: int) -> np.ndarray:
    return np.eye(k, dtype=np.uint8)


# ---------------------------------------------------------------------------
# ISA-L conventions
# ---------------------------------------------------------------------------

def isa_rs_vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L `gf_gen_rs_matrix(a, k+m, k)` — (k+m, k) systematic matrix.

    Parity row i (0-based within the parity block) is the geometric progression
    of g = 2^i: [1, g, g^2, ..., g^(k-1)].  Row 0 is therefore all-ones, which
    is what enables the reference's XOR fast paths (ErasureCodeIsa.cc:125-131,
    :206-216).  NOT guaranteed MDS for large (k, m) — hence the reference's
    safety caps (ErasureCodeIsa.cc:331-361), enforced by the codec layer.
    """
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k] = identity(k)
    gen = 1
    for i in range(m):
        p = 1
        for j in range(k):
            a[k + i, j] = p
            p = GF_MUL_TABLE[p, gen]
        gen = GF_MUL_TABLE[gen, 2]
    return a


def isa_cauchy_matrix(k: int, m: int) -> np.ndarray:
    """ISA-L `gf_gen_cauchy1_matrix(a, k+m, k)` — (k+m, k) systematic matrix.

    Parity entry for absolute row i in [k, k+m) and column j is 1/(i ^ j).
    Always MDS (a true Cauchy matrix: rows indexed by {k..k+m-1}, columns by
    {0..k-1}, disjoint sets).
    """
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k] = identity(k)
    for i in range(k, k + m):
        for j in range(k):
            a[i, j] = GF_INV_TABLE[i ^ j]
    return a


def gf_invert_matrix(mat: np.ndarray) -> np.ndarray | None:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination.

    Returns None when singular — the analog of ISA-L `gf_invert_matrix`
    returning -1, which the reference surfaces as a decode failure
    (ErasureCodeIsa.cc:275-278).  The inverse of a matrix over a field is
    unique, so byte-parity with ISA-L does not depend on pivoting order.
    """
    n = mat.shape[0]
    assert mat.shape == (n, n)
    work = mat.astype(np.uint8).copy()
    out = identity(n)
    for i in range(n):
        if work[i, i] == 0:
            pivots = np.nonzero(work[i + 1:, i])[0]
            if pivots.size == 0:
                return None
            j = i + 1 + int(pivots[0])
            work[[i, j]] = work[[j, i]]
            out[[i, j]] = out[[j, i]]
        inv_piv = gf_inv(int(work[i, i]))
        work[i] = GF_MUL_TABLE[work[i], inv_piv]
        out[i] = GF_MUL_TABLE[out[i], inv_piv]
        # Eliminate column i from every other row.
        factors = work[:, i].copy()
        factors[i] = 0
        out ^= GF_MUL_TABLE[factors[:, None], out[i][None, :]]
        work ^= GF_MUL_TABLE[factors[:, None], work[i][None, :]]
    return out


def isa_decode_matrix(
    encode_coeff: np.ndarray, erasures: list[int], k: int
) -> tuple[np.ndarray, list[int]] | None:
    """Build the (nerrs, k) decode matrix exactly as the reference does.

    Mirrors ErasureCodeIsa.cc:233-297: pick the first k surviving rows
    (`decode_index`), invert that square submatrix of the distribution matrix,
    then each erased data row e takes row e of the inverse, and each erased
    parity row e takes encode_coeff[e] @ inverse.

    Returns (decode_matrix, decode_index) or None when the survivor submatrix
    is singular (possible for non-MDS Vandermonde corners).
    """
    km = encode_coeff.shape[0]
    erased = set(erasures)
    decode_index: list[int] = []
    r = 0
    for _ in range(k):
        while r in erased:
            r += 1
        if r >= km:
            return None
        decode_index.append(r)
        r += 1
    b = encode_coeff[decode_index, :]  # (k, k) survivor rows
    d = gf_invert_matrix(b)
    if d is None:
        return None
    nerrs = len(erasures)
    c = np.zeros((nerrs, k), dtype=np.uint8)
    for p, e in enumerate(erasures):
        if e < k:
            c[p] = d[e]
        else:
            # parity row e regenerated from survivors: coeff_e @ B^-1
            c[p] = gf_matmul(encode_coeff[e][None, :], d)[0]
    return c, decode_index


# ---------------------------------------------------------------------------
# jerasure conventions
# ---------------------------------------------------------------------------

def _extended_vandermonde(rows: int, cols: int) -> np.ndarray:
    """jerasure `reed_sol_extended_vandermonde_matrix(rows, cols, 8)`.

    Row 0 = e_0, last row = e_{cols-1}, middle rows i = [1, i, i^2, ...].
    """
    v = np.zeros((rows, cols), dtype=np.uint8)
    v[0, 0] = 1
    v[rows - 1, cols - 1] = 1
    for i in range(1, rows - 1):
        p = 1
        for j in range(cols):
            v[i, j] = p
            p = GF_MUL_TABLE[p, i]
    return v


def jerasure_vandermonde_matrix(k: int, m: int) -> np.ndarray:
    """jerasure `reed_sol_vandermonde_coding_matrix(k, m, 8)` + identity top.

    Re-derivation of `reed_sol_big_vandermonde_distribution_matrix`: start from
    the extended Vandermonde (k+m, k) matrix, apply **column** operations to
    make the top k x k block the identity (column ops preserve MDS-ness), then
    scale columns so row k is all ones, restoring the identity by scaling the
    corresponding data rows.  This yields a true MDS systematic matrix whose
    first parity row is all ones (the property the reference's RAID-6 and
    single-parity XOR paths rely on).
    """
    rows, cols = k + m, k
    dist = _extended_vandermonde(rows, cols)
    # Column-reduce the top block to the identity.
    for i in range(1, cols):
        # Ensure pivot dist[i, i] is nonzero by swapping *rows* below if needed
        # (rows >= i never touch the already-fixed identity rows above).
        if dist[i, i] == 0:
            nz = np.nonzero(dist[i + 1:, i])[0]
            assert nz.size, "extended Vandermonde cannot be systematized"
            j = i + 1 + int(nz[0])
            dist[[i, j]] = dist[[j, i]]
        if dist[i, i] != 1:
            inv = gf_inv(int(dist[i, i]))
            dist[:, i] = GF_MUL_TABLE[dist[:, i], inv]
        row = dist[i].copy()
        for j in range(cols):
            if j != i and row[j] != 0:
                dist[:, j] ^= GF_MUL_TABLE[row[j], dist[:, i]]
    # Make row k (first parity row) all ones: scale column j by 1/dist[k, j],
    # then restore the identity block by scaling data row j back.
    for j in range(cols):
        t = int(dist[k, j])
        assert t != 0, "MDS violation: zero in first parity row"
        if t != 1:
            inv = gf_inv(t)
            dist[:, j] = GF_MUL_TABLE[dist[:, j], inv]
            dist[j, :] = GF_MUL_TABLE[dist[j, :], t]
    return dist


def jerasure_r6_matrix(k: int) -> np.ndarray:
    """jerasure `reed_sol_r6_coding_matrix(k, 8)` (m == 2, RAID-6).

    Parity row 0 all ones (P), row 1 = powers of 2 (Q).
    """
    a = np.zeros((k + 2, k), dtype=np.uint8)
    a[:k] = identity(k)
    a[k, :] = 1
    p = 1
    for j in range(k):
        a[k + 1, j] = p
        p = GF_MUL_TABLE[p, 2]
    return a


def jerasure_cauchy_orig_matrix(k: int, m: int) -> np.ndarray:
    """jerasure `cauchy_original_coding_matrix(k, m, 8)` + identity top.

    coeff[i][j] = 1 / (i ^ (m + j)) for parity row i in [0, m).
    """
    assert k + m <= 256
    a = np.zeros((k + m, k), dtype=np.uint8)
    a[:k] = identity(k)
    for i in range(m):
        for j in range(k):
            a[k + i, j] = GF_INV_TABLE[i ^ (m + j)]
    return a


_BITCOUNT_TABLE: np.ndarray | None = None


def _bitcount_gf(x: int) -> int:
    """Number of ones in the 8x8 GF(2) bit-matrix of multiply-by-x.

    jerasure's `cauchy_n_ones` equivalent, used by cauchy_good to pick light
    coefficients; a 256-entry table built once from the companion expansion.
    """
    global _BITCOUNT_TABLE
    if _BITCOUNT_TABLE is None:
        from .bitslice import coeff_bitmatrix

        _BITCOUNT_TABLE = np.array(
            [coeff_bitmatrix(c).sum() for c in range(256)], dtype=np.int32
        )
    return int(_BITCOUNT_TABLE[x])


def jerasure_cauchy_good_matrix(k: int, m: int) -> np.ndarray:
    """jerasure `cauchy_good_general_coding_matrix(k, m, 8)` + identity top.

    cauchy_orig improved (jerasure `cauchy_improve_coding_matrix` semantics):
    divide each column j by its row-0 entry so parity row 0 is all ones, then
    for each later parity row, try scaling the whole row by the inverse of each
    of its elements and keep the scaling that minimizes the total number of
    ones in the row's GF(2) bit-matrices (ties keep the earlier candidate).
    """
    a = jerasure_cauchy_orig_matrix(k, m)
    coding = a[k:]
    # Column normalization: make parity row 0 all ones.
    for j in range(k):
        t = int(coding[0, j])
        if t != 1:
            coding[:, j] = GF_MUL_TABLE[coding[:, j], gf_inv(t)]
    # Row lightening for rows 1..m-1.
    for i in range(1, m):
        best = coding[i].copy()
        best_ones = sum(_bitcount_gf(int(x)) for x in best)
        for j in range(k):
            cand = GF_MUL_TABLE[coding[i], gf_inv(int(coding[i, j]))]
            ones = sum(_bitcount_gf(int(x)) for x in cand)
            if ones < best_ones:
                best, best_ones = cand, ones
        coding[i] = best
    a[k:] = coding
    return a


def vandermonde_mds_check(k: int, m: int, matrix: np.ndarray) -> bool:
    """Exhaustively verify every m-erasure pattern is decodable.

    The reference caps ISA Vandermonde at (k<=21, m=4)/(k<=32, m<=3)
    (ErasureCodeIsa.cc:331-361); this is the direct check used by tests to
    validate those envelopes for our matrices.
    """
    import itertools

    km = k + m
    for erasures in itertools.combinations(range(km), m):
        res = isa_decode_matrix(matrix, list(erasures), k)
        if res is None:
            return False
    return True
